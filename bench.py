"""Benchmark harness: prints ONE JSON line with the north-star metric.

Metric (BASELINE.json:2): env frames/sec for the IMPALA V-trace configuration
on TPU. ``vs_baseline`` is the ratio against the driver-set target of
1,000,000 env fps (BASELINE.md — the reference itself has no recorded
published numbers; see SURVEY.md §0/§6).

Usage: python bench.py [preset] [key=value ...]
Default (no preset) = driver mode: measures BOTH flagships — the vector
Pong headline (pong_impala; dispatch-amortized MLP) and, riding in the
``pixel_flagship`` key with equal prominence, the pixel-path CNN flagship
(atari_impala — the reference's real PongNoFrameskip-v4 shape). Explicit
preset = that one measurement only.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys


def _accelerator_alive(timeout: float = 120.0) -> bool:
    """Probe backend init in a THROWAWAY subprocess: the axon TPU plugin has
    been observed to hang indefinitely on first device query when its tunnel
    is down (see .claude/skills/verify gotchas), which would otherwise turn
    the whole benchmark run into a silent hang. A dead probe -> fall back to
    CPU so the driver still records a (clearly labeled) datapoint."""
    import os
    import signal

    # No pipes (a hung plugin helper process could inherit them and keep
    # them open past the child's death, blocking us forever) and a fresh
    # session so the WHOLE process group can be killed on timeout.
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    try:
        return proc.wait(timeout=timeout) == 0
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        return False


def jnp_abs_sum(x):
    import jax.numpy as jnp

    return jnp.sum(jnp.abs(x.astype(jnp.float32)))


def timed_update_window(
    update,
    state,
    updates_per_call: int,
    warmup: int = 3,
    min_seconds: float = 2.0,
    min_calls: int = 10,
):
    """Shared measurement harness (bench.py + scripts/bench_matrix.py — ONE
    copy, so a sync-discipline fix can never drift between them).

    SYNC DISCIPLINE: on the axon plugin, ``jax.block_until_ready`` returns
    before execution finishes (verified 2026-07-30: 500 fused calls
    "completed" in 84ms by block_until_ready, 4.6s by an actual D2H read —
    a 55x phantom speedup that put the apparent fps above the chip's FLOP
    peak). Only a device->host copy truly synchronizes, so every timing
    boundary here reads a scalar off the dependency chain's tail.

    Time-targeted window: run for >= ``min_seconds`` of wall clock (and >=
    ``min_calls`` calls). A fixed small iteration count gave a ~5ms device
    window on fast configs, where per-call dispatch jitter swung results by
    ±40% run to run (observed 30-52M fps on identical configs, 2026-07-30).

    Returns ``(state, timed_calls, elapsed_seconds)``. Raises RuntimeError
    if the device-side update counter disagrees with the number of updates
    dispatched (the counter cannot ack work that never ran).
    """
    import time

    from asyncrl_tpu.utils.checkpoint import _step_of

    def sync(s) -> int:
        return _step_of(s)  # D2H read: forces all queued work

    # Counter base: the state may be non-fresh (checkpoint auto-resume), so
    # the guard compares counter DELTA, not the absolute value.
    base = sync(state)
    for _ in range(warmup):
        state, _ = update(state)
    sync(state)

    timed = 0
    t0 = time.perf_counter()
    while True:
        state, _ = update(state)
        timed += 1
        if timed % min_calls == 0:
            executed = sync(state)
            if time.perf_counter() - t0 >= min_seconds:
                break
    elapsed = time.perf_counter() - t0

    dispatched = (warmup + timed) * updates_per_call
    if executed - base != dispatched:
        raise RuntimeError(
            f"device executed {executed - base} updates, "
            f"dispatched {dispatched}"
        )
    return state, timed, elapsed


def _accelerator_alive_with_retry(
    attempts: int | None = None, wait_s: float | None = None
) -> bool:
    """The axon tunnel goes down for stretches and recovers on its own
    (observed multiple multi-hour outages); a benchmark run is rare and
    valuable enough to wait out a transient blip before settling for the
    CPU-fallback datapoint. Round 1's 3x60s window lost to exactly such an
    outage (VERDICT.md Weak #1), so the default window is now ~15 min of
    probing, and both knobs are environment-tunable:

      BENCH_PROBE_ATTEMPTS / BENCH_PROBE_WAIT_S  override the loop shape;
      BENCH_NO_WAIT=1                            single immediate probe.

    Whatever the probe decides, the CPU fallback is no longer the round's
    only evidence — see the BENCH_HISTORY.json reporting in main().
    """
    import os
    import time

    if os.environ.get("BENCH_NO_WAIT", "").lower() not in ("", "0", "false"):
        return _accelerator_alive()
    if attempts is None:
        attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "6"))
    if wait_s is None:
        wait_s = float(os.environ.get("BENCH_PROBE_WAIT_S", "120"))

    for attempt in range(attempts):
        if _accelerator_alive():
            return True
        if attempt + 1 < attempts:
            print(
                f"bench: accelerator probe {attempt + 1}/{attempts} failed; "
                f"retrying in {wait_s:.0f}s",
                file=sys.stderr,
            )
            time.sleep(wait_s)
    return False


def cpu_fallback_or_refuse(jax, tool: str = "bench") -> bool:
    """Probe the accelerator; on failure either switch this process to CPU
    (returning True) or — under BENCH_REQUIRE_ACCELERATOR=1 — exit(4).

    Queue-driven callers (scripts/tpu_window.sh) set the env var so a CPU
    fallback reads as job FAILURE, not evidence: the tunnel flapped between
    their liveness probe and this run, and stamping a CPU row as the
    real-chip measurement would end the retry loop with the wrong row.
    Shared by bench.py, scripts/roofline.py, scripts/bench_matrix.py.

    ASYNCRL_FORCE_CPU=1 skips the probe and goes straight to CPU: the
    long-running CPU baseline arm must keep its platform provenance pure
    (and stay off the chip) even when the tunnel happens to be up."""
    if os.environ.get("ASYNCRL_FORCE_CPU", "") not in ("", "0"):
        jax.config.update("jax_platforms", "cpu")
        print(f"{tool}: ASYNCRL_FORCE_CPU set; running on CPU",
              file=sys.stderr)
        return True
    if _accelerator_alive_with_retry():
        return False
    if os.environ.get("BENCH_REQUIRE_ACCELERATOR", "") not in ("", "0"):
        print(
            f"{tool}: accelerator unavailable and BENCH_REQUIRE_ACCELERATOR"
            " is set; refusing to fall back",
            file=sys.stderr,
        )
        sys.exit(4)
    jax.config.update("jax_platforms", "cpu")
    print(
        f"{tool}: accelerator backend hung/unavailable; falling back to "
        "CPU (metric label carries the device kind)",
        file=sys.stderr,
    )
    return True


def resolve_bench_config(preset_name: str, overrides: list[str], on_cpu: bool):
    """Effective config for one headline measurement (unit-tested: this is
    the driver-run entry point's decision logic).

    - cartpole geometry widens to saturate a chip;
    - the fused-dispatch default: one tunnel round trip costs ~8 ms here,
      capping an unfused loop at ~1M fps regardless of chip speed, so the
      bench fuses K updates per jitted call (updates_per_call — identical
      training semantics). The accelerator default sits at the measured
      plateau of the live-chip sweep (BENCH_HISTORY 2026-07-31: K=32 ->
      14.8M, K=64 -> 20.8M, K=128 -> 24.2M, K=256 -> 26.6M, K=512 -> 27.3M
      fps on pong_impala); the CPU fallback keeps K=8 — one K=512 call is
      ~75 s of CPU work, which blows any caller's timeout before the first
      timed window completes. Explicit overrides always win.
    """
    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils.config import override

    cfg = presets.get(preset_name)
    if preset_name == "cartpole_impala":
        cfg = cfg.replace(num_envs=8192)
    if not any(o.startswith("updates_per_call=") for o in overrides):
        cfg = cfg.replace(updates_per_call=8 if on_cpu else 512)
    return override(cfg, overrides)


def measure_preset(preset_name: str, overrides: list[str]) -> dict:
    """Measure one Anakin preset's fused-update throughput; returns the
    headline dict ({metric, value, unit, vs_baseline}). Raises SystemExit
    on a non-tpu backend or integrity failure (unchanged semantics)."""
    import jax

    from asyncrl_tpu.api.trainer import Trainer

    cfg = resolve_bench_config(
        preset_name, overrides, jax.devices()[0].platform == "cpu"
    )
    if cfg.backend != "tpu":
        # Checked on the EFFECTIVE config (preset + overrides): this
        # harness times the Anakin learner's bare update loop; a
        # host-backend config measured that way would record a
        # wrong-architecture fps entry. The pipeline-aware harness
        # handles those.
        print(
            f"bench: effective backend={cfg.backend!r}; measure host "
            "backends with scripts/bench_matrix.py (pipeline-aware) "
            "instead",
            file=sys.stderr,
        )
        sys.exit(2)

    trainer = Trainer(cfg)
    state = trainer.state
    # Real copies: with donate_buffers=true the update donates state's
    # buffers, and an aliasing snapshot would be deleted from under us.
    params0 = jax.tree.map(lambda x: x.copy(), state.params)

    try:
        state, timed, elapsed = timed_update_window(
            trainer.learner.update, state, cfg.updates_per_call
        )
    except RuntimeError as e:
        print(
            f"bench: {e}; refusing to report a throughput number",
            file=sys.stderr,
        )
        sys.exit(1)

    # Execution-integrity guard: a wedged accelerator tunnel has been
    # observed acking dispatches without executing them (absurd fps right
    # before a hang). Training must have actually moved the params.
    import numpy as np

    delta = jax.tree.reduce(
        lambda a, b: a + b,
        jax.tree.map(
            lambda a, b: float(jnp_abs_sum(a - b)), state.params, params0
        ),
    )
    if not np.isfinite(delta) or delta == 0.0:
        print(
            f"bench: integrity check failed (param delta {delta}); "
            "refusing to report a throughput number",
            file=sys.stderr,
        )
        sys.exit(1)

    fps = timed * cfg.updates_per_call * cfg.num_envs * cfg.unroll_len / elapsed

    from asyncrl_tpu.utils import bench_history

    dev = bench_history.device_entry()
    bench_history.record_throughput(preset_name, cfg, fps)

    result = {
        "metric": f"env_frames_per_sec ({preset_name}, "
        f"{cfg.num_envs} envs x {cfg.unroll_len} unroll x "
        f"{cfg.updates_per_call} fused updates/call, "
        f"{dev['device_kind']} x{dev['device_count']})",
        "value": round(fps),
        "unit": "frames/sec",
        "vs_baseline": round(fps / bench_history.NORTH_STAR_FPS, 3),
    }
    if dev["platform"] == "cpu":
        attach_last_known_good(result, preset_name)
    return result


def measure_fused_ab(overrides: list[str]) -> dict:
    """A/B the fused Pallas V-trace scan against the lax path on one
    identical Anakin config (``python bench.py fused_ab [key=value ...]``)
    — the device-hot-path sibling of scripts/perf_smoke.sh's overlap_ab.

    Two claims, checked separately because they pin different references:

    - **Loss bit-identity**: the fused kernel's contract is bit-equality
      against the SEQUENTIAL lax scan (ops/pallas_scan.py; the
      associative production scan rounds differently by design), so the
      identity arm runs ``fused_scan="lax", scan_impl="sequential"`` and
      the losses must match to the bit on the shared seed. The identity
      arm also pins ``smap_check="off"`` so both arms compile the SAME
      (unchecked) shard_map wrapper — the replication checker's identity
      collectives move XLA fusion boundaries, which drifts trajectories
      a final ULP on multi-device meshes independent of the kernel.
    - **Throughput**: the perf bar is against the PRODUCTION lax path
      (``fused_scan="lax"`` with the default scan_impl resolution) —
      beating a deliberately-slow reference would be a hollow win. On an
      accelerator the fused arm must not be slower beyond
      ASYNCRL_FUSED_AB_TOLERANCE (default 1.10x, the perf_smoke noise
      convention); the CPU interpreter arm only reports (the Pallas
      interpreter is an emulator — its fps is not evidence either way).

    Records one kind="device_hot_path" probe="fused_ab" ledger row.
    """
    import jax
    import numpy as np

    from asyncrl_tpu.api.trainer import Trainer
    from asyncrl_tpu.envs import registered
    from asyncrl_tpu.utils import bench_history

    on_cpu = jax.devices()[0].platform == "cpu"
    fused_mode = "interpret" if on_cpu else "pallas"
    tolerance = float(os.environ.get("ASYNCRL_FUSED_AB_TOLERANCE", "1.10"))
    preset_name = (
        "pong_impala" if "JaxPong-v0" in registered() else "cartpole_impala"
    )
    cfg = resolve_bench_config(preset_name, overrides, on_cpu)
    if on_cpu:
        # The interpreter arm runs the kernel as a Python emulation: keep
        # the CPU geometry small enough that the probe finishes inside a
        # CI window. Explicit overrides win, as everywhere in bench.py.
        if not any(o.startswith("num_envs=") for o in overrides):
            cfg = cfg.replace(num_envs=64)
        if not any(o.startswith("updates_per_call=") for o in overrides):
            cfg = cfg.replace(updates_per_call=4)
    if cfg.backend != "tpu":
        print(
            f"bench: fused_ab needs the Anakin backend, got "
            f"{cfg.backend!r}",
            file=sys.stderr,
        )
        sys.exit(2)

    def losses_of(arm_cfg, calls: int = 3):
        trainer = Trainer(arm_cfg)
        state = trainer.state
        out = []
        for _ in range(calls):
            state, metrics = trainer.learner.update(state)
            out.append(np.asarray(jax.device_get(metrics["loss"])))
        return np.stack(out), trainer, state

    # Identity arm: fused vs the sequential lax reference, same seed.
    fused_losses, fused_trainer, fused_state = losses_of(
        cfg.replace(fused_scan=fused_mode)
    )
    seq_losses, _, _ = losses_of(
        cfg.replace(fused_scan="lax", scan_impl="sequential", smap_check="off")
    )
    if not np.array_equal(fused_losses, seq_losses):
        print(
            "bench: fused_ab FAILED — fused losses diverged from the "
            f"sequential lax reference (max abs diff "
            f"{np.max(np.abs(fused_losses - seq_losses))})",
            file=sys.stderr,
        )
        sys.exit(1)

    # Throughput arms: fused (continuing the warm trainer) vs the
    # PRODUCTION lax path, both through the shared sync-disciplined
    # window.
    _, timed_f, elapsed_f = timed_update_window(
        fused_trainer.learner.update, fused_state, cfg.updates_per_call
    )
    lax_losses, lax_trainer, lax_state = losses_of(
        cfg.replace(fused_scan="lax")
    )
    _, timed_l, elapsed_l = timed_update_window(
        lax_trainer.learner.update, lax_state, cfg.updates_per_call
    )
    per_call = cfg.updates_per_call * cfg.num_envs * cfg.unroll_len
    fps_fused = timed_f * per_call / elapsed_f
    fps_lax = timed_l * per_call / elapsed_l

    if not on_cpu and fps_fused * tolerance < fps_lax:
        print(
            f"bench: fused_ab FAILED — fused path slower "
            f"({fps_fused:,.0f} vs {fps_lax:,.0f} fps, "
            f"tolerance {tolerance}x)",
            file=sys.stderr,
        )
        sys.exit(1)

    dev = bench_history.device_entry()
    bench_history.record({
        "kind": "device_hot_path",
        "probe": "fused_ab",
        "preset": preset_name,
        **dev,
        "num_envs": cfg.num_envs,
        "unroll_len": cfg.unroll_len,
        "updates_per_call": cfg.updates_per_call,
        "fused_impl": fused_mode,
        "fps_fused": round(fps_fused),
        "fps_lax": round(fps_lax),
        "fused_speedup": round(fps_fused / fps_lax, 3),
        "losses_bit_identical": True,
    })
    return {
        "metric": f"fused_ab ({preset_name}, {cfg.num_envs} envs x "
        f"{cfg.unroll_len} unroll x {cfg.updates_per_call} fused "
        f"updates/call, {fused_mode}, {dev['device_kind']} "
        f"x{dev['device_count']})",
        "fps_fused": round(fps_fused),
        "fps_lax": round(fps_lax),
        "fused_speedup": round(fps_fused / fps_lax, 3),
        "losses_bit_identical": True,
        "unit": "frames/sec",
    }


# Dual-flagship driver mode (VERDICT r3 Next #3/Weak #2): the vector-Pong
# number alone overstates the framework (its MLP is trivial — the win is
# dispatch amortization), so the no-preset invocation measures BOTH
# flagships and reports the pixel-path (CNN, the reference's real Atari
# shape) with equal prominence. The pixel geometry matches the watcher's
# pixel_bench job so ledger rows stay comparable round to round.
PIXEL_FLAGSHIP_PRESET = "atari_impala"
PIXEL_FLAGSHIP_OVERRIDES = ["updates_per_call=8", "num_envs=256"]


def main() -> None:
    import jax

    cpu_fallback_or_refuse(jax, "bench")
    from asyncrl_tpu.envs import registered

    args = sys.argv[1:]
    preset_name = None
    overrides = []
    for a in args:
        if "=" in a:
            overrides.append(a)
        else:
            preset_name = a

    if preset_name == "fused_ab":
        print(json.dumps(measure_fused_ab(overrides)))
        return

    if preset_name is not None:
        print(json.dumps(measure_preset(preset_name, overrides)))
        return

    if overrides:
        # Driver mode's whole point is round-to-round comparable flagship
        # geometry; silently reshaping the vector headline (while the
        # pixel rider ignores the same overrides) would record a
        # non-standard row under the standard label. Overrides belong to
        # explicit single-preset runs.
        print(
            "bench: key=value overrides require naming a preset "
            "(driver mode measures the fixed flagship geometry)",
            file=sys.stderr,
        )
        sys.exit(2)

    # Driver mode: both flagships, vector headline + pixel rider.
    vector_preset = (
        "pong_impala" if "JaxPong-v0" in registered() else "cartpole_impala"
    )
    result = measure_preset(vector_preset, overrides)
    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        # A fresh CPU pixel run is ~minutes of conv on one core for a
        # number nobody compares; ride the newest committed TPU row
        # instead. label="" — the metric already says "not measured";
        # attach's default "[CPU fallback]" would wrongly imply a null
        # value was a CPU measurement.
        pixel = {
            "metric": f"env_frames_per_sec ({PIXEL_FLAGSHIP_PRESET}) "
            "[not measured; tunnel down]",
            "value": None,
            "unit": "frames/sec",
        }
        attach_last_known_good(pixel, PIXEL_FLAGSHIP_PRESET, label="")
    else:
        # Fixed geometry, no user overrides: the pixel rider must stay
        # ledger-comparable round to round (same shape as the watcher's
        # pixel_bench job); override a pixel run explicitly via
        # `python bench.py atari_impala ...` instead. A pixel-side failure
        # must not discard the vector headline already measured — it
        # degrades to an error note (SystemExit: measure_preset refuses
        # via sys.exit on integrity failures).
        try:
            pixel = measure_preset(
                PIXEL_FLAGSHIP_PRESET, list(PIXEL_FLAGSHIP_OVERRIDES)
            )
        except (SystemExit, Exception) as e:  # noqa: BLE001 — any pixel
            # failure (refusal exit, OOM, tunnel error mid-run) degrades
            # the rider; it must never cost the vector headline.
            detail = (
                f"exit {e.code}" if isinstance(e, SystemExit) else repr(e)[:120]
            )
            pixel = {
                "metric": f"env_frames_per_sec ({PIXEL_FLAGSHIP_PRESET}) "
                f"[measurement failed; {detail}]",
                "value": None,
                "unit": "frames/sec",
            }
            attach_last_known_good(pixel, PIXEL_FLAGSHIP_PRESET, label="")
    result["pixel_flagship"] = pixel
    print(json.dumps(result))


def attach_last_known_good(
    result: dict,
    preset_name: str,
    path: str | None = None,
    label: str = " [CPU fallback; tunnel down]",
) -> dict:
    """Headline provenance (VERDICT.md round 2, Weak #1/Next #3): the
    freshly measured number stays in ``result["value"]`` even when it is a
    CPU fallback — a consumer parsing ``value``/``vs_baseline`` must always
    get something this very run measured, never a remembered one. The
    newest committed accelerator measurement for THIS preset rides along
    under the explicitly-named ``last_known_good`` key, carrying its
    capture time and ``captured_by`` provenance verbatim so a
    hand-backfilled entry can never masquerade as harness-captured."""
    from asyncrl_tpu.utils import bench_history

    lkg = bench_history.last_known_good(
        "throughput", preset=preset_name, path=path
    )
    if lkg is not None:
        result["metric"] += label
        # .get() throughout: ledger entries may be hand-backfilled and are
        # not schema-validated — a sparse one degrades this annotation, it
        # must never crash the freshly-measured headline.
        result["last_known_good"] = {
            k: lkg.get(k)
            for k in (
                "frames_per_sec",
                "vs_baseline",
                "ts",
                "preset",
                "num_envs",
                "unroll_len",
                "updates_per_call",
                "device_kind",
                "device_count",
            )
        }
        result["last_known_good"]["captured_by"] = lkg.get(
            "captured_by", "manual"
        )
    return result


if __name__ == "__main__":
    main()
