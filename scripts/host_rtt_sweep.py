"""Inference-batch sweep over the device link (VERDICT round 4, Weak #5 /
Next #6): docs/ARCHITECTURE.md explains the 943-fps tunneled host-path row
with an ~8 ms tunnel-RTT model that had no ledger row behind it. This
script measures the model directly: the jitted policy forward is timed at
batch sizes 32..512, and the per-call time is decomposed by least squares
into

    seconds_per_call(batch) ~= fixed_latency + per_item * batch

If the link RTT dominates (the model's claim), fixed_latency carries the
milliseconds and served fps scales near-linearly with batch; if compute
dominates, per_item does. One ``kind="host_path"`` ledger row with
``sweep`` + the fitted decomposition either confirms the RTT model or
kills it (the docs cite this row either way).

    python scripts/host_rtt_sweep.py [preset] [key=value ...]

Runs under the watcher with BENCH_REQUIRE_ACCELERATOR=1 so the row is
chip-served; a manual CPU run banks an honestly-labeled platform=cpu row
(useful only as the no-RTT control).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import cpu_fallback_or_refuse  # noqa: E402
from host_path_profile import inference_rate  # noqa: E402  (scripts/ sibling)

BATCHES = (32, 64, 128, 256, 512)


def main() -> int:
    import jax

    args = sys.argv[1:]
    overrides = [a for a in args if "=" in a]
    names = [a for a in args if "=" not in a]
    preset_name = names[0] if names else "pendulum_native_ppo"

    cpu_fallback_or_refuse(jax, "host_rtt_sweep")

    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils import bench_history
    from asyncrl_tpu.utils.config import override

    cfg = override(presets.get(preset_name), overrides)
    if cfg.backend not in ("sebulba", "cpu_async"):
        print(
            f"host_rtt_sweep: preset {preset_name!r} is not a host backend",
            file=sys.stderr,
        )
        return 2

    sweep = []
    for batch in BATCHES:
        try:
            row = inference_rate(cfg, batch)
        except Exception as e:  # one OOM batch must not lose the sweep
            sweep.append({"batch": batch, "error": str(e)[:300]})
            continue
        sweep.append(row)
        print(json.dumps(row))

    good = [r for r in sweep if "error" not in r]
    if len(good) < 2:
        print("host_rtt_sweep: not enough points to fit", file=sys.stderr)
        return 1

    batches = np.array([r["batch"] for r in good], np.float64)
    per_call = 1.0 / np.array([r["calls_per_sec"] for r in good], np.float64)
    slope, intercept = np.polyfit(batches, per_call, 1)
    # Clamp BOTH uses of the fit: a near-zero true intercept (the CPU
    # control) can come out slightly negative from least-squares noise,
    # and an unclamped share could then even exceed 1 on a ratio of two
    # negatives — a self-contradictory row next to fixed_latency_ms=0.
    fixed = max(float(intercept), 0.0)
    per_item = max(float(slope), 0.0)
    fixed_ms = fixed * 1e3
    # Share of a mid-sweep (batch-128) call spent in the fixed term: the
    # RTT model predicts this dominates on the tunneled chip.
    denom = fixed + per_item * 128
    mid = fixed / denom if denom > 0 else 0.0
    entry = {
        "kind": "host_path",
        "probe": "rtt_sweep",
        "preset": preset_name,
        **bench_history.device_entry(),
        "sweep": sweep,
        "fixed_latency_ms": round(fixed_ms, 3),
        "per_item_us": round(per_item * 1e6, 3),
        "fixed_share_at_batch128": round(float(mid), 3),
        # "Fixed-latency bound", not "RTT bound": on the tunneled chip the
        # fixed term IS dominated by link RTT; on a CPU control run it is
        # local dispatch overhead. The platform field disambiguates.
        "fixed_latency_bound": bool(mid > 0.5),
    }
    try:
        entry = bench_history.record(entry)
    except OSError as e:
        print(f"host_rtt_sweep: could not persist: {e}", file=sys.stderr)
    print(json.dumps(entry))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
