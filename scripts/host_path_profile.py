"""Host-path (Sebulba) performance identity (VERDICT round 3, Weak #5 /
Next #6): the host backend had a measured number (943 fps,
pendulum_native_ppo on the tunneled chip) but no stated model of what it
SHOULD achieve. This profiler measures the three component rates that
bound a host pipeline and records them with the derived identity:

    pipeline_fps <= min(pool_ceiling, batch_size * inference_rate)

- **pool_ceiling**: raw C++ envpool stepping rate (random actions, no
  learner, no inference) — the host-simulation bound.
- **inference_rate**: calls/sec of the jitted policy forward at the
  per-thread batch size — the action-service bound. On the tunneled
  axon chip every call pays the ~8 ms tunnel RTT, which is what capped
  the round-3 number (128-env batch / 8 ms ≈ 16k fps theoretical; with
  actor/learner contention on the 1-core host, 943 measured). On a
  co-located host+chip (the deployment this backend is FOR), the RTT
  term vanishes.
- **pipeline_fps**: the assembled SebulbaTrainer, measured briefly.

One ``kind="host_path"`` ledger row carries all three plus the derived
bound fraction. Run anywhere (CPU evidence is the point for the host
side); the inference rate is labeled with the platform it was served on.

    python scripts/host_path_profile.py [preset] [key=value ...]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import cpu_fallback_or_refuse  # noqa: E402


def pool_ceiling(env_id: str, num_envs: int, seconds: float = 2.0) -> dict:
    """Raw native-pool step rate with random actions (no policy)."""
    from asyncrl_tpu.envs import native_pool

    pool = native_pool.NativeEnvPool(env_id, num_envs, seed=0)
    try:
        rng = np.random.default_rng(0)

        def actions():
            if pool.continuous:
                return rng.uniform(
                    -1, 1, (num_envs, pool.action_dim)
                ).astype(np.float32)
            return rng.integers(0, pool.num_actions, num_envs, np.int32)

        pool.reset()
        for _ in range(3):
            pool.step(actions())
        steps = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            pool.step(actions())
            steps += 1
        elapsed = time.perf_counter() - t0
    finally:
        pool.close()
    return {
        "env_id": env_id,
        "num_envs": num_envs,
        "pool_fps": round(steps * num_envs / elapsed),
    }


def inference_rate(cfg, batch: int, seconds: float = 2.0) -> dict:
    """Jitted greedy/sample policy forward rate at the per-thread batch."""
    import jax

    from asyncrl_tpu.api.sebulba_trainer import SebulbaTrainer

    trainer = SebulbaTrainer(cfg.replace(total_env_steps=0))
    try:
        infer = trainer._inference_fn
        params = trainer._store.get()[0]
        obs = np.zeros((batch, *trainer.spec.obs_shape), np.float32)
        key = jax.random.PRNGKey(0)
        out = infer(params, obs, key)
        np.asarray(jax.device_get(jax.tree.leaves(out)[0]))  # real sync
        calls = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            out = infer(params, obs, key)
            np.asarray(jax.device_get(jax.tree.leaves(out)[0]))
            calls += 1
        elapsed = time.perf_counter() - t0
    finally:
        trainer.close()
    return {
        "batch": batch,
        "calls_per_sec": round(calls / elapsed, 1),
        "served_fps": round(calls * batch / elapsed),
    }


def pipeline_fps(cfg, seconds: float = 30.0) -> dict:
    """Assembled-pipeline throughput over a short training burst."""
    from asyncrl_tpu.api.factory import make_agent

    fps_log: list[float] = []
    t0 = time.perf_counter()

    class _Enough(Exception):
        pass

    def cb(m):
        fps_log.append(m["fps"])
        if time.perf_counter() - t0 > seconds:
            raise _Enough

    trainer = make_agent(cfg)
    try:
        trainer.train(callback=cb)
    except _Enough:
        pass
    finally:
        trainer.close()
    # First window includes compile; steady state is the tail.
    tail = fps_log[1:] or fps_log
    return {
        "windows": len(fps_log),
        "pipeline_fps": round(float(np.mean(tail))) if tail else None,
    }


def main() -> int:
    import jax

    args = sys.argv[1:]
    overrides = [a for a in args if "=" in a]
    names = [a for a in args if "=" not in a]
    preset_name = names[0] if names else "pendulum_native_ppo"

    cpu_fallback_or_refuse(jax, "host_path_profile")

    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils import bench_history
    from asyncrl_tpu.utils.config import override

    cfg = override(presets.get(preset_name), overrides)
    if cfg.backend not in ("sebulba", "cpu_async"):
        print(
            f"host_path_profile: preset {preset_name!r} is not a host "
            "backend",
            file=sys.stderr,
        )
        return 2

    per_thread = cfg.num_envs // cfg.actor_threads
    pool = pool_ceiling(cfg.env_id, cfg.num_envs)
    print(json.dumps(pool))
    infer = inference_rate(cfg, per_thread)
    print(json.dumps(infer))
    pipe = pipeline_fps(cfg)
    print(json.dumps(pipe))

    # The identity: per-thread actors serve per_thread envs per inference
    # call; actor_threads of them share the host. The bound is the
    # smaller of host simulation and action service.
    bound = min(pool["pool_fps"], infer["served_fps"] * cfg.actor_threads)
    entry = {
        "kind": "host_path",
        "preset": preset_name,
        **bench_history.device_entry(),
        "num_envs": cfg.num_envs,
        "actor_threads": cfg.actor_threads,
        "pool_fps": pool["pool_fps"],
        "inference_batch": infer["batch"],
        "inference_calls_per_sec": infer["calls_per_sec"],
        "inference_served_fps": infer["served_fps"],
        "pipeline_fps": pipe["pipeline_fps"],
        "component_bound_fps": bound,
        "bound_fraction": (
            round(pipe["pipeline_fps"] / bound, 3)
            if pipe["pipeline_fps"] and bound
            else None
        ),
    }
    try:
        entry = bench_history.record(entry)
    except OSError as e:
        print(f"host_path_profile: could not persist: {e}", file=sys.stderr)
    print(json.dumps(entry))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
