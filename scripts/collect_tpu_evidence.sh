#!/bin/bash
# Real-TPU evidence collection (VERDICT.md round 1, Next #1-#3): run the
# benchmark set on the live chip and persist every result in the committed
# BENCH_HISTORY.json ledger. Steps are independent — a tunnel flap mid-way
# loses one step, not the session. Logs to stdout; run under nohup/tee.
#
# Usage: bash scripts/collect_tpu_evidence.sh [--quick]
#   --quick: skip the long time-to-target runs (throughput rows only).
set -u
cd "$(dirname "$0")/.."

QUICK=${1:-}
run() {
  echo "=== $(date -u +%FT%TZ) $*"
  timeout "${STEP_TIMEOUT:-1800}" "$@"
  echo "=== rc=$? $*"
}

# Throughput: vector flagship, pixel/CNN flagship (VERDICT #2), then the
# whole matrix incl. host-path rows (VERDICT #3). BENCH_NO_WAIT: the caller
# already established liveness; a mid-run flap should fail fast, not stall.
export BENCH_NO_WAIT=1
run python bench.py
run python bench.py atari_impala updates_per_call=8
run python bench.py atari_impala updates_per_call=8 num_envs=256
run python scripts/bench_matrix.py
# Roofline/MFU + dispatch-vs-compute for the pixel flagship and the
# vector config (VERDICT #2's requested breakdown).
run python scripts/roofline.py atari_impala updates_per_call=8
run python scripts/roofline.py pong_impala updates_per_call=32
# Device hot path: on-chip bit-identity gates for the fused V-trace tail
# and the RDMA ring, then the fused on/off throughput A/B on the
# flagship geometry (ledger rows kind=kernel_validation/device_hot_path).
run python scripts/validate_pallas_tpu.py fused ring
run python bench.py fused_ab

if [ "$QUICK" != "--quick" ]; then
  # North-star outcomes: wall-clock to target (VERDICT #1 / BASELINE.md).
  STEP_TIMEOUT=3000 run python scripts/run_to_target.py cartpole_a3c \
      --target 475 --budget-seconds 900 eval_every=20
  STEP_TIMEOUT=3000 run python scripts/run_to_target.py pong_impala \
      --target 18.0 --budget-seconds 2400 eval_every=40
fi

# Persist the ledger. Artifact-only, PATH-LIMITED commit: anything else
# staged or modified in the tree stays out of it.
if [ -n "$(git status --porcelain BENCH_HISTORY.json)" ]; then
  # add is required while the ledger is still untracked; the pathspec on
  # commit keeps everything else (staged or not) out of this commit.
  git add BENCH_HISTORY.json
  git -c core.editor=true commit -q -m "Record real-TPU benchmark evidence in BENCH_HISTORY

Automated ledger update from scripts/collect_tpu_evidence.sh on a live
accelerator window; see the entries' device_kind/ts fields.

No-Verification-Needed: benchmark-artifact-only commit" \
    -- BENCH_HISTORY.json \
    && echo "=== BENCH_HISTORY.json committed"
fi
