"""Wall-clock-to-target runner: the north-star OUTCOME measurement
(BASELINE.md: wall-clock to 18.0 mean Pong reward, target < 10 min on TPU;
VERDICT.md round 1, Missing #2). Trains a preset until the in-training
greedy eval reaches the target return, then appends a ``time_to_target``
record to the committed BENCH_HISTORY.json ledger.

    python scripts/run_to_target.py pong_impala \
        [--target 18.0] [--budget-seconds 3600] [key=value ...]

Wall clock is measured from the moment ``train()`` is entered (compile
time included — that is what a user actually waits). The run refuses to
record a success unless training truly hit the target; a budget exhaustion
is recorded too (kind="time_to_target", reached=false) so failed attempts
are visible history, not silence.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _accelerator_alive_with_retry  # noqa: E402


class _TargetReached(Exception):
    pass


def main() -> int:
    import jax

    args = sys.argv[1:]
    target_return = 18.0  # BASELINE.json:2 Pong target
    budget_seconds = 3600.0
    overrides = []
    preset_name = "pong_impala"
    it = iter(args)
    for a in it:
        if a in ("--target", "--budget-seconds"):
            try:
                value = float(next(it))
            except (StopIteration, ValueError):
                print(f"usage: {a} <number>", file=sys.stderr)
                return 2
            if a == "--target":
                target_return = value
            else:
                budget_seconds = value
        elif "=" in a:
            overrides.append(a)
        else:
            preset_name = a

    if not _accelerator_alive_with_retry():
        jax.config.update("jax_platforms", "cpu")
        print(
            "run_to_target: accelerator unavailable; running on CPU "
            "(record will carry platform=cpu and never count as "
            "last-known-good)",
            file=sys.stderr,
        )

    from asyncrl_tpu.api.factory import make_agent
    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils import bench_history
    from asyncrl_tpu.utils.config import override

    cfg = presets.get(preset_name)
    if cfg.eval_every <= 0:
        # Eval cadence drives target detection; check roughly every ~2s of
        # training (eval_every counts update CALLS, aligned to log_every).
        cfg = cfg.replace(eval_every=cfg.log_every, eval_episodes=32)
    cfg = override(cfg, overrides)

    # make_agent dispatches on cfg.backend — a sebulba/cpu_async preset must
    # be measured on ITS architecture, not silently retimed on Anakin.
    trainer = make_agent(cfg)
    dev = bench_history.device_entry()
    status = {"reached": False, "seconds": None, "eval_return": None}
    fps_log: list[float] = []
    t0 = time.perf_counter()

    def on_metrics(agg: dict) -> None:
        fps_log.append(agg["fps"])
        ev = agg.get("eval_return")
        if ev is not None:
            status["eval_return"] = round(ev, 3)
        line = {
            "t": round(time.perf_counter() - t0, 1),
            "env_steps": agg["env_steps"],
            "episode_return": round(agg["episode_return"], 2),
            "fps": round(agg["fps"]),
        }
        if ev is not None:
            line["eval_return"] = round(ev, 2)
        print(json.dumps(line), file=sys.stderr, flush=True)
        if ev is not None and ev >= target_return:
            status.update(
                reached=True, seconds=round(time.perf_counter() - t0, 1)
            )
            raise _TargetReached
        if time.perf_counter() - t0 > budget_seconds:
            status["seconds"] = round(time.perf_counter() - t0, 1)
            raise _TargetReached  # budget exhausted; reached stays False

    try:
        trainer.train(callback=on_metrics)
        if status["seconds"] is None:
            # total_env_steps ran out before target or budget: the attempt's
            # duration and last eval are still evidence, not silence.
            status["seconds"] = round(time.perf_counter() - t0, 1)
    except _TargetReached:
        pass
    finally:
        trainer.close()

    entry = {
        "kind": "time_to_target",
        "preset": preset_name,
        **dev,
        "target_return": target_return,
        "reached": status["reached"],
        "seconds": status["seconds"],
        "eval_return": status["eval_return"],
        "num_envs": cfg.num_envs,
        "unroll_len": cfg.unroll_len,
        "updates_per_call": cfg.updates_per_call,
        "mean_fps": round(sum(fps_log) / max(len(fps_log), 1)),
    }
    try:
        entry = bench_history.record(entry)
    except OSError as e:  # the measurement must outlive a read-only ledger
        print(f"run_to_target: could not persist: {e}", file=sys.stderr)
    print(json.dumps(entry))
    return 0 if status["reached"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
