"""Wall-clock-to-target runner: the north-star OUTCOME measurement
(BASELINE.md: wall-clock to 18.0 mean Pong reward, target < 10 min on TPU;
VERDICT.md round 1, Missing #2). Trains a preset until the in-training
greedy eval reaches the target return, then appends a ``time_to_target``
record to the committed BENCH_HISTORY.json ledger.

    python scripts/run_to_target.py pong_impala \
        [--target 18.0] [--budget-seconds 3600] [key=value ...]

Wall clock is measured from the moment ``train()`` is entered (compile
time included — that is what a user actually waits). The run refuses to
record a success unless training truly hit the target; a budget exhaustion
is recorded too (kind="time_to_target", reached=false) so failed attempts
are visible history, not silence.

Success protocol (VERDICT r4 Next #3): an in-training eval crossing the
target is only a CANDIDATE — with ``eval_episodes=32`` and per-episode std
0.8–3.0, a true-mean-17.9 policy can luck across a single eval. The run
confirms every crossing with an independent fresh-seed eval of
``--confirm-episodes`` (default 64, floored at 64) episodes before banking
``reached=true``; the row records both numbers. A crossing that fails
confirmation resumes training (the budget clock never stops) and is
counted in the row's ``unconfirmed_crossings``.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import cpu_fallback_or_refuse  # noqa: E402


class _Crossed(Exception):
    """In-training eval crossed the target: stop and confirm."""


class _BudgetExhausted(Exception):
    """Wall-clock budget spent: stop and record reached=false."""


# Confirmation evals must be independent of the in-training eval stream
# (Trainer.evaluate defaults to seed=1234 — the same episodes every time);
# a fixed distinct base keeps the protocol reproducible while each retry
# within a session still sees fresh episodes.
CONFIRM_SEED_BASE = 97_531


def main() -> int:
    import jax

    args = sys.argv[1:]
    target_return = 18.0  # BASELINE.json:2 Pong target
    budget_seconds = 3600.0
    confirm_episodes = 64
    overrides = []
    preset_name = "pong_impala"
    it = iter(args)
    for a in it:
        if a in ("--target", "--budget-seconds", "--confirm-episodes"):
            try:
                value = float(next(it))
            except (StopIteration, ValueError):
                print(f"usage: {a} <number>", file=sys.stderr)
                return 2
            if a == "--target":
                target_return = value
            elif a == "--budget-seconds":
                budget_seconds = value
            else:
                # The protocol floor is 64 (VERDICT r4 Weak #2): fewer
                # episodes would re-open the single-lucky-eval hole the
                # confirmation exists to close.
                confirm_episodes = max(64, int(value))
        elif "=" in a:
            overrides.append(a)
        else:
            preset_name = a

    # CPU fallback is VALID evidence here (entry carries platform=cpu and
    # never counts as last-known-good) — but the TPU-window queue sets
    # BENCH_REQUIRE_ACCELERATOR so a flap aborts rather than polluting a
    # TPU checkpoint_dir's accumulated clock with slow CPU sessions.
    cpu_fallback_or_refuse(jax, "run_to_target")

    from asyncrl_tpu.api.factory import make_agent
    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils import bench_history
    from asyncrl_tpu.utils.config import override

    cfg = presets.get(preset_name)
    if cfg.eval_every <= 0:
        # Eval cadence drives target detection; check roughly every ~2s of
        # training (eval_every counts update CALLS, aligned to log_every).
        cfg = cfg.replace(eval_every=cfg.log_every, eval_episodes=32)
    cfg = override(cfg, overrides)

    # Cross-session accumulation (VERDICT.md round 2, Next #1): with a
    # checkpoint_dir, Trainer auto-resumes training state bit-exact, and the
    # wall clock accumulates through a sidecar — so a target reached on the
    # Nth session records the TOTAL training time, not one session's slice.
    # (The clock is training-only wall time: the gaps between sessions are
    # not training and do not count.)
    elapsed_path = (
        os.path.join(cfg.checkpoint_dir, "run_to_target_elapsed.json")
        if cfg.checkpoint_dir
        else None
    )
    prior = {
        "seconds": 0.0,
        "sessions": 0,
        "fps_sum": 0.0,
        "fps_n": 0,
        # Which platforms contributed sessions (a checkpoint can resume
        # across the tunnel boundary — TPU sessions then CPU ones). The
        # wall-clock accumulation stays honest either way, but mean_fps
        # blends platforms, so the entry must say so.
        "platforms": [],
        # Crossings rejected by the confirmation eval in PRIOR sessions
        # (this session's count is confirm["failed"]): the final row's
        # provenance must count every rejected crossing on the arm.
        "unconfirmed_crossings": 0,
    }
    # Prior time counts only when there is actually a checkpoint to resume
    # from — a stale sidecar next to deleted checkpoints must not credit a
    # fresh run with old wall time.
    sidecar_names = {
        os.path.basename(elapsed_path),
        os.path.basename(elapsed_path) + ".tmp",
    } if elapsed_path else set()
    has_checkpoint = cfg.checkpoint_dir and any(
        e not in sidecar_names
        for e in (
            os.listdir(cfg.checkpoint_dir)
            if os.path.isdir(cfg.checkpoint_dir)
            else []
        )
    )
    if elapsed_path and has_checkpoint and os.path.exists(elapsed_path):
        try:
            with open(elapsed_path) as f:
                loaded = json.load(f)
            prior.update({k: loaded[k] for k in prior if k in loaded})
        except (OSError, json.JSONDecodeError, TypeError, KeyError):
            loaded = {}
            print(
                "run_to_target: unreadable elapsed sidecar; counting this "
                "session only",
                file=sys.stderr,
            )
        else:
            if loaded.get("reached", False):
                print(
                    "run_to_target: this checkpoint_dir already holds a "
                    "COMPLETED time-to-target measurement; resuming it "
                    "would record a bogus instant success. Clear the "
                    "directory to start a new measurement.",
                    file=sys.stderr,
                )
                return 3
            print(
                f"run_to_target: resuming after {prior['sessions']} prior "
                f"session(s), {prior['seconds']:.0f}s accumulated",
                file=sys.stderr,
            )

    # The completed-measurement refusal above must run BEFORE backend init:
    # a refusal should be instant and side-effect-free, not pay a (possibly
    # hung-tunnel) accelerator bring-up and an orbax auto-restore first.
    # make_agent dispatches on cfg.backend — a sebulba/cpu_async preset must
    # be measured on ITS architecture, not silently retimed on Anakin.
    trainer = make_agent(cfg)
    dev = bench_history.device_entry()
    status = {"reached": False, "seconds": None, "eval_return": None}
    # Confirmation state lives next to status because save_elapsed (a
    # closure called on every metrics drain) persists the failed-crossing
    # count: a SIGKILL'd session's rejected lucky crossing must survive
    # into the next session's ledger row, not vanish with the process.
    confirm = {"return": None, "failed": 0}
    fps_log: list[float] = []
    t0 = time.perf_counter()

    def total_elapsed() -> float:
        return prior["seconds"] + time.perf_counter() - t0

    def save_elapsed(reached: bool = False) -> None:
        # Atomic (tmp + rename, like bench_history), and OSError-tolerant
        # like bench_history.record: a full/read-only checkpoint volume must
        # degrade the accumulation, never abort the measurement itself.
        if not elapsed_path:
            return
        payload = {
            "seconds": round(total_elapsed(), 1),
            "sessions": prior["sessions"] + 1,
            "fps_sum": prior["fps_sum"] + sum(fps_log),
            "fps_n": prior["fps_n"] + len(fps_log),
            "platforms": sorted(
                set(prior["platforms"]) | {dev["platform"]}
            ),
            "unconfirmed_crossings": (
                prior["unconfirmed_crossings"] + confirm["failed"]
            ),
        }
        if reached:
            payload["reached"] = True
        try:
            tmp = elapsed_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, elapsed_path)
        except OSError as e:
            print(
                f"run_to_target: could not persist elapsed sidecar: {e}",
                file=sys.stderr,
            )

    def on_metrics(agg: dict) -> None:
        fps_log.append(agg["fps"])
        ev = agg.get("eval_return")
        if ev is not None:
            status["eval_return"] = round(ev, 3)
        line = {
            "t": round(total_elapsed(), 1),
            "env_steps": agg["env_steps"],
            "episode_return": round(agg["episode_return"], 2),
            "fps": round(agg["fps"]),
        }
        if ev is not None:
            line["eval_return"] = round(ev, 2)
        print(json.dumps(line), file=sys.stderr, flush=True)
        # Learning curve persisted WITH the run, not only in the (tmp-
        # resident, reboot-mortal) supervisor log: the committed run dir
        # then carries the eval trajectory across sessions as evidence.
        if cfg.checkpoint_dir:
            try:
                with open(
                    os.path.join(cfg.checkpoint_dir, "metrics.jsonl"), "a"
                ) as f:
                    f.write(json.dumps(line) + "\n")
            except OSError:
                pass  # read-only volume: stderr already has the line
        # Persist accumulated wall time on every drain, not just at exit: a
        # SIGKILL'd session's checkpointed training progress survives, so
        # its wall time must survive too (else a later session records an
        # understated time-to-target).
        save_elapsed()
        if ev is not None and ev >= target_return:
            # Candidate only: the crossing's wall clock is frozen here, but
            # reached=true is banked ONLY if the independent confirmation
            # eval below agrees (VERDICT r4 Next #3).
            status["crossing_seconds"] = round(total_elapsed(), 1)
            raise _Crossed
        if total_elapsed() > budget_seconds:
            status["seconds"] = round(total_elapsed(), 1)
            raise _BudgetExhausted

    try:
        while True:
            try:
                trainer.train(callback=on_metrics)
                if status["seconds"] is None:
                    # total_env_steps ran out before target or budget: the
                    # attempt's duration and last eval are still evidence,
                    # not silence.
                    status["seconds"] = round(total_elapsed(), 1)
                break
            except _BudgetExhausted:
                break
            except _Crossed:
                crossing_seconds = status.pop("crossing_seconds")
                # Each crossing gets its own confirmation verdict: a stale
                # value from an earlier rejected crossing must not pair
                # with THIS crossing's numbers in the final row (e.g. when
                # this confirmation attempt crashes below).
                confirm["return"] = None
                # Fresh-seed confirmation, independent of the in-training
                # eval stream. Retries cycle through 8 seeds (params have
                # moved between retries, so reuse is sound) — unbounded
                # fresh seeds would grow SebulbaTrainer's per-(episodes,
                # seed) eval-pool cache linearly with failed crossings.
                seed = CONFIRM_SEED_BASE + (confirm["failed"] % 8)
                try:
                    confirm["return"] = float(
                        trainer.evaluate(
                            num_episodes=confirm_episodes, seed=seed
                        )
                    )
                except Exception as e:
                    # The confirmation eval is bigger than the in-training
                    # one (64 episodes vs 32) — on a memory-edge geometry
                    # it can fail where training did not. The attempt must
                    # still become a visible reached=false row with the
                    # crossing's provenance, not a crash with no ledger
                    # entry ("failed attempts are visible history").
                    status["confirm_error"] = str(e)[:300]
                    status["seconds"] = crossing_seconds
                    print(
                        f"run_to_target: confirmation eval failed: {e}",
                        file=sys.stderr,
                    )
                    break
                print(
                    json.dumps(
                        {
                            "confirm_return": round(confirm["return"], 3),
                            "confirm_episodes": confirm_episodes,
                            "confirm_seed": seed,
                            "crossing_eval": status["eval_return"],
                            "t": crossing_seconds,
                        }
                    ),
                    file=sys.stderr,
                    flush=True,
                )
                if confirm["return"] >= target_return:
                    status.update(reached=True, seconds=crossing_seconds)
                    break
                confirm["failed"] += 1
                # Persist the rejection NOW: a SIGKILL before the resumed
                # training's next metrics drain must not lose it.
                save_elapsed()
                print(
                    "run_to_target: crossing NOT confirmed "
                    f"({confirm['return']:.2f} < {target_return}); "
                    "resuming training",
                    file=sys.stderr,
                )
                # The confirmation eval's wall time stays on the clock (the
                # user waited through it); it may itself exhaust the budget.
                if total_elapsed() > budget_seconds:
                    status["seconds"] = round(total_elapsed(), 1)
                    break
    finally:
        save_elapsed()
        trainer.close()

    entry = {
        "kind": "time_to_target",
        "preset": preset_name,
        # The env actually trained (an override can retarget a preset —
        # e.g. the CPU recipe probe runs pong_pixels_t2t's economics on
        # the VECTOR env; without this field that row would read as a
        # pixel-path result).
        "env_id": cfg.env_id,
        **dev,
        "target_return": target_return,
        "reached": status["reached"],
        "seconds": status["seconds"],
        "eval_return": status["eval_return"],
        # Confirmation provenance (VERDICT r4 Next #3): a reached=true row
        # carries BOTH the in-training crossing eval (eval_return) and the
        # independent fresh-seed confirmation; crossings that failed
        # confirmation are counted, not hidden.
        **(
            {
                "confirm_return": round(confirm["return"], 3),
                "confirm_episodes": confirm_episodes,
            }
            if confirm["return"] is not None
            else {}
        ),
        **(
            {
                "unconfirmed_crossings": (
                    prior["unconfirmed_crossings"] + confirm["failed"]
                )
            }
            if prior["unconfirmed_crossings"] + confirm["failed"]
            else {}
        ),
        **(
            {"confirm_error": status["confirm_error"]}
            if "confirm_error" in status
            else {}
        ),
        "num_envs": cfg.num_envs,
        "unroll_len": cfg.unroll_len,
        "updates_per_call": cfg.updates_per_call,
        # The episode-cap bar this target was measured under (VERDICT r3
        # Weak #4): 3000 = the repo's scoring-rate bar, 27000 =
        # ALE-faithful win-margin semantics.
        **(
            {"pong_max_steps": cfg.pong_max_steps}
            if "JaxPong" in cfg.env_id
            else {}
        ),
        # Decisions-per-core-frame context: a skip-4 row's seconds/fps
        # count agent decisions, 4 core frames each.
        **({"frame_skip": cfg.frame_skip} if cfg.frame_skip != 1 else {}),
        # Consistent with "seconds": averaged over ALL accumulated sessions
        # (window-fps mean, weights carried through the sidecar).
        "mean_fps": round(
            (prior["fps_sum"] + sum(fps_log))
            / max(prior["fps_n"] + len(fps_log), 1)
        ),
    }
    if prior["sessions"]:
        entry["resumed_sessions"] = prior["sessions"]
    session_platforms = sorted(set(prior["platforms"]) | {dev["platform"]})
    if len(session_platforms) > 1:
        # A cross-platform resume: seconds are wall-clock-honest, but the
        # fps average blends device speeds — the row must carry the
        # blend's provenance (the top-level platform field only names the
        # FINAL session's device).
        entry["platforms"] = session_platforms
        entry["mean_fps_mixed_platforms"] = True
    if status["reached"]:
        # Mark the measurement finished. A rerun in this dir would resume
        # the already-trained checkpoint and "reach" the target in seconds
        # — deleting the sidecar would let that record as a bogus fresh
        # time_to_target, so instead the marker makes a rerun refuse
        # (clear the checkpoint dir to start a new measurement).
        save_elapsed(reached=True)
    try:
        entry = bench_history.record(entry)
    except OSError as e:  # the measurement must outlive a read-only ledger
        print(f"run_to_target: could not persist: {e}", file=sys.stderr)
    print(json.dumps(entry))
    return 0 if status["reached"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
