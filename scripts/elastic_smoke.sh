#!/usr/bin/env bash
# Elastic smoke: the operator-facing gate for the elastic runtime
# (asyncrl_tpu/runtime/elastic.py), in two acts:
#
#   1. IDENTITY — a quiet elastic=True run must be BIT-IDENTICAL on
#      losses to a static-fleet elastic=False control on a fixed seed,
#      and neither run's windows may carry any elastic_* key (the
#      introspect=False discipline: off — or armed-but-quiet — changes
#      nothing).
#   2. FUNCTION — a live run is forced through a scale-up and then a
#      scale-down via ASYNCRL_FAULTS scale events (the chaos grammar's
#      `scale` kind, driven through the public env-var surface the way a
#      cluster chaos run would drive it), gating on: both transitions
#      recorded (elastic_scale_up/down counters), the fleet back at its
#      configured size, zero supervised restarts (a scale is not a
#      crash), and /healthz — read over HTTP from the live exposition
#      endpoint — reporting ok after the transitions.
#
# ASYNCRL_SMOKE_RECORD=1 appends a kind="robustness" probe="elastic_ab"
# row to BENCH_HISTORY.json with the static-vs-elastic fps and the
# transition counts.
#
# Usage: scripts/elastic_smoke.sh                  # CPU, ~2 min
#        ASYNCRL_SMOKE_UPDATES=48 scripts/elastic_smoke.sh
#        ASYNCRL_SMOKE_RECORD=1 scripts/elastic_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
UPDATES="${ASYNCRL_SMOKE_UPDATES:-24}"
RECORD="${ASYNCRL_SMOKE_RECORD:-0}"
OUT_DIR="$(mktemp -d)"
trap 'rm -rf "$OUT_DIR"' EXIT

# ---------------------------------------------------------------- act 1
# Identity: elastic=True (quiet) vs elastic=False, fixed seed.
python - "$UPDATES" "$OUT_DIR" <<'EOF'
import json
import sys
import time

import numpy as np

from asyncrl_tpu import make_agent
from asyncrl_tpu.utils.config import Config

updates, out_dir = int(sys.argv[1]), sys.argv[2]
NUM_ENVS, UNROLL = 16, 8
steps = updates * NUM_ENVS * UNROLL


def run(elastic: bool):
    cfg = Config(
        env_id="CartPole-v1", algo="impala", backend="sebulba",
        host_pool="jax", num_envs=NUM_ENVS, actor_threads=1,
        unroll_len=UNROLL, precision="f32", log_every=4, seed=3,
        # Frozen behaviour params: losses must be seed-deterministic for
        # the identity assertion (no publish-timing race).
        actor_staleness=1_000_000,
        elastic=elastic, elastic_max_actors=4,
        # Armed-but-quiet (the test_elastic bit-identity discipline): the
        # 1-actor fleet genuinely starves the learner on this box, so the
        # organic up signal would fire — real, but nondeterministic, and
        # this act is about elastic=True changing NOTHING when no scale
        # event happens.
        elastic_up_stall_frac=1.0, elastic_down_backpressure=0.0,
        elastic_down_admission=0.0,
    )
    agent = make_agent(cfg)
    try:
        t0 = time.perf_counter()
        history = agent.train(total_env_steps=steps)
        elapsed = time.perf_counter() - t0
    finally:
        agent.close()
    return steps / elapsed, history


# Discarded in-process warm-up (the introspect_smoke/perf_smoke
# methodology): without it the first arm pays the JIT compile cost and
# the second runs on the warm cache, writing a phantom fps gap into the
# recorded ledger row for an identical workload.
run(False)
fps_static, hist_static = run(False)
fps_elastic, hist_elastic = run(True)

losses_a = [h["loss"] for h in hist_static]
losses_b = [h["loss"] for h in hist_elastic]
if not np.array_equal(np.asarray(losses_a), np.asarray(losses_b)):
    sys.exit(
        "elastic_smoke FAILED: quiet elastic=True losses diverged from the "
        "static-fleet control on a fixed seed"
    )
print(f"elastic_smoke: losses identical across {len(losses_a)} windows")

for label, hist in (("static", hist_static), ("elastic", hist_elastic)):
    leaked = sorted(
        {k for h in hist for k in h if k.startswith("elastic_")}
    )
    if leaked:
        sys.exit(
            f"elastic_smoke FAILED: quiet {label} run leaked {leaked} "
            "into the window snapshot"
        )
    if "actors_live" not in hist[-1]:
        sys.exit(
            f"elastic_smoke FAILED: {label} run's windows are missing the "
            "fleet gauges (actors_live)"
        )
print("elastic_smoke: zero elastic keys leaked; fleet gauges present")

with open(f"{out_dir}/identity.json", "w") as f:
    json.dump({"fps_static": fps_static, "fps_elastic_quiet": fps_elastic},
              f)
EOF

# ---------------------------------------------------------------- act 2
# Function: forced scale-up then scale-down via ASYNCRL_FAULTS, gated on
# /healthz over the live HTTP endpoint.
export ASYNCRL_FAULTS="actor.step:scale:1.0:0:delta=1,max=1;actor.queue_put:scale:1.0:0:delta=-1,max=1,after=8"
python - "$UPDATES" "$OUT_DIR" <<'EOF'
import json
import sys
import time
import urllib.request

import numpy as np

from asyncrl_tpu import make_agent
from asyncrl_tpu.utils.config import Config

updates, out_dir = int(sys.argv[1]), sys.argv[2]
NUM_ENVS, UNROLL = 16, 8
steps = updates * NUM_ENVS * UNROLL

cfg = Config(
    env_id="CartPole-v1", algo="impala", backend="sebulba",
    host_pool="jax", num_envs=NUM_ENVS, actor_threads=2,
    unroll_len=UNROLL, precision="f32", log_every=4, seed=3,
    elastic=True, elastic_max_actors=4,
    # Organic signals pinned off (the test_elastic e2e discipline): this
    # act asserts EXACT fleet shapes, and on a loaded 1-core box the
    # controller's own stall verdict is genuine but nondeterministic —
    # only the scripted ASYNCRL_FAULTS events may move the fleet here.
    elastic_up_stall_frac=1.0, elastic_down_backpressure=0.0,
    elastic_down_admission=0.0,
    obs_http_port=-1,  # ephemeral /metrics + /healthz endpoint
    # This 1-core box's scheduler noise must not hold /healthz degraded
    # past the end of the run (the gate is about the SCALE transitions).
    health_stall_frac=1.0, health_fps_collapse=0.0,
)
agent = make_agent(cfg)
try:
    t0 = time.perf_counter()
    history = agent.train(total_env_steps=steps)
    elapsed = time.perf_counter() - t0
    last = history[-1]
    if last.get("elastic_scale_up", 0) < 1:
        sys.exit("elastic_smoke FAILED: forced scale-up never applied")
    if last.get("elastic_scale_down", 0) < 1:
        sys.exit("elastic_smoke FAILED: forced scale-down never applied")
    if last.get("actors_live") != float(cfg.actor_threads):
        sys.exit(
            "elastic_smoke FAILED: fleet did not return to its configured "
            f"size (actors_live={last.get('actors_live')})"
        )
    if last.get("actor_restarts", 0) != 0:
        sys.exit(
            "elastic_smoke FAILED: a deliberate scale event was counted "
            "as a supervised restart"
        )
    if not np.isfinite(last["loss"]):
        sys.exit("elastic_smoke FAILED: loss went non-finite under scaling")
    if agent._obs.http is None:
        sys.exit("elastic_smoke FAILED: exposition endpoint did not mount")
    url = f"http://127.0.0.1:{agent._obs.http.port}/healthz"
    verdict = json.load(urllib.request.urlopen(url, timeout=5))
    if verdict["status"] != "ok":
        sys.exit(
            f"elastic_smoke FAILED: /healthz did not recover to ok after "
            f"the scale transitions: {verdict}"
        )
    print(
        f"elastic_smoke: scale-up + scale-down applied, fleet restored, "
        f"/healthz ok (window {verdict['window']})"
    )
finally:
    agent.close()

with open(f"{out_dir}/elastic.json", "w") as f:
    json.dump({
        "fps_elastic_scaled": steps / elapsed,
        "scale_up": int(last["elastic_scale_up"]),
        "scale_down": int(last["elastic_scale_down"]),
    }, f)
EOF
unset ASYNCRL_FAULTS

# --------------------------------------------------------------- ledger
python - "$UPDATES" "$OUT_DIR" "$RECORD" <<'EOF'
import json
import sys

updates, out_dir, record = sys.argv[1], sys.argv[2], sys.argv[3]
identity = json.load(open(f"{out_dir}/identity.json"))
scaled = json.load(open(f"{out_dir}/elastic.json"))
print(
    f"elastic_smoke OK: static {identity['fps_static']:,.0f} fps, quiet "
    f"elastic {identity['fps_elastic_quiet']:,.0f} fps, scaled run "
    f"{scaled['fps_elastic_scaled']:,.0f} fps "
    f"({scaled['scale_up']} up / {scaled['scale_down']} down)"
)
if record not in ("", "0"):
    from asyncrl_tpu.utils import bench_history

    entry = bench_history.record({
        "kind": "robustness",
        "probe": "elastic_ab",
        "preset": "cartpole_impala(sebulba tiny)",
        **bench_history.device_entry(),
        "updates": int(updates),
        "fps_static": round(identity["fps_static"]),
        "fps_elastic_quiet": round(identity["fps_elastic_quiet"]),
        "fps_elastic_scaled": round(scaled["fps_elastic_scaled"]),
        "scale_up": scaled["scale_up"],
        "scale_down": scaled["scale_down"],
        "healthz": "ok",
    })
    print("elastic_smoke: recorded", entry["ts"])
EOF
