"""Pixel-path MFU probe (VERDICT round 3, Next #2): the flagship CNN config
measured 1.45% MFU and is compute-bound (dispatch amortized away), so the
question is WHERE the update's 0.114 s go and what the achievable ceiling
is. This script answers it on the real chip with two measurements:

1. **Geometry sweep** — full fused update at (256, 512 envs; 256x64
   unroll; 1024-env fit geometry): does a bigger per-step conv batch lift
   the MXU utilization the way the roofline predicts?
2. **Phase split** — the update is rollout (T sequential policy forwards
   + env physics + rendering, batch B) followed by the learner pass (one
   T*B-batch forward/backward). Each phase is compiled and timed
   standalone with XLA's own FLOP count, attributing both the seconds and
   the FLOPs. A rollout-dominated step bounds MFU by the env/render VPU
   work, not the convs — a different fix (wider batch, smaller T) than a
   learner-dominated one (layout/dtype/channel-width).

One ``kind="mfu_probe"`` ledger entry carries every row. Run via the TPU
window watcher (stamp ``mfu_probe``, scripts/tpu_window.sh).
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)
from bench import cpu_fallback_or_refuse  # noqa: E402
from roofline import measure, peak_for  # noqa: E402


def _flops_of(compiled) -> float | None:
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, list) else cost
    flops = float(cost.get("flops", float("nan")))
    return None if math.isnan(flops) else flops


def _timed_calls(fn, sync, min_seconds: float = 2.0, warmup: int = 2):
    """Time ``fn()`` repeatedly; ``sync(out)`` must do a real D2H read (the
    axon plugin's block_until_ready returns early — bench.py sync note)."""
    for _ in range(warmup):
        sync(fn())
    calls = 0
    t0 = time.perf_counter()
    while True:
        sync(fn())
        calls += 1
        if time.perf_counter() - t0 >= min_seconds and calls >= 3:
            break
    return calls, time.perf_counter() - t0


def phase_split(cfg) -> dict:
    """Rollout-only vs learner-only timing + FLOPs for one geometry, on a
    plain single-device jit (no shard_map; representative, not identical,
    of the 1-chip sharded program)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from asyncrl_tpu.api.trainer import Trainer
    from asyncrl_tpu.learn.learner import _algo_loss, entropy_coef_at
    from asyncrl_tpu.ops import distributions
    from asyncrl_tpu.ops.normalize import normalizing_apply
    from asyncrl_tpu.rollout.anakin import unroll

    cfg = cfg.replace(updates_per_call=1)
    trainer = Trainer(cfg)
    env, state = trainer.env, trainer.state
    dist = distributions.for_config(cfg, env.spec)
    napply = normalizing_apply(trainer.model.apply, state.obs_stats)

    def rollout_only(params, actor):
        actor, ro, _ = unroll(
            napply, params, env, actor, cfg.unroll_len, dist=dist,
            reward_scale=cfg.reward_scale, step_cost=cfg.step_cost,
        )
        return actor, ro

    def learn_only(params, actor_params, ro):
        def scaled(p, frag):
            loss, metrics = _algo_loss(
                cfg, napply, p, frag, axis_name=None, dist=dist,
                target_params=actor_params,
                entropy_coef=entropy_coef_at(cfg, state.update_step),
            )
            return loss, (loss, metrics)

        (_, _), grads = jax.value_and_grad(scaled, has_aux=True)(
            params, ro
        )
        return grads

    ro_c = jax.jit(rollout_only).lower(state.params, state.actor).compile()
    _, rollout = ro_c(state.params, state.actor)
    ln_c = (
        jax.jit(learn_only)
        .lower(state.params, state.actor_params, rollout)
        .compile()
    )

    def sync_ro(out):
        np.asarray(jax.device_get(out[1].rewards[0, 0]))

    def sync_ln(grads):
        leaf = jax.tree.leaves(grads)[0]
        np.asarray(jax.device_get(leaf.ravel()[0]))

    ro_calls, ro_s = _timed_calls(
        lambda: ro_c(state.params, state.actor), sync_ro
    )
    ln_calls, ln_s = _timed_calls(
        lambda: ln_c(state.params, state.actor_params, rollout), sync_ln
    )

    dev = jax.devices()[0]
    peak = peak_for(dev.device_kind)
    rows = {}
    for name, compiled, calls, secs in (
        ("rollout", ro_c, ro_calls, ro_s),
        ("learner", ln_c, ln_calls, ln_s),
    ):
        flops = _flops_of(compiled)
        s_per = secs / calls
        achieved = flops / s_per if flops is not None else None
        rows[name] = {
            "seconds_per_call": round(s_per, 5),
            "flops_per_call": flops,
            "achieved_tflops": (
                round(achieved / 1e12, 3) if achieved is not None else None
            ),
            "mfu": (
                round(achieved / peak, 4)
                if peak and achieved is not None
                else None
            ),
        }
    total = rows["rollout"]["seconds_per_call"] + rows["learner"]["seconds_per_call"]
    rows["rollout_fraction_of_step"] = round(
        rows["rollout"]["seconds_per_call"] / total, 3
    )
    trainer.close()
    return rows


def main() -> int:
    import jax

    args = sys.argv[1:]
    overrides = [a for a in args if "=" in a]
    names = [a for a in args if "=" not in a]
    preset_name = names[0] if names else "atari_impala"

    cpu_fallback_or_refuse(jax, "mfu_probe")

    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils import bench_history
    from asyncrl_tpu.utils.config import override

    base = override(
        presets.get(preset_name).replace(updates_per_call=8, num_envs=256),
        overrides,
    )

    # Variants scale RELATIVE to the base geometry (overridable, so a CPU
    # smoke test can run the same code path on toy shapes): wider conv
    # batch (2x/4x envs — the 4x needs the grad_accum+remat fit, matching
    # the 1024-env BASELINE geometry on chip) and a longer unroll (bigger
    # learner batch at the same per-step conv batch).
    # The watcher runs this under `timeout`, whose SIGTERM would normally
    # kill the process without banking anything; convert it to SystemExit
    # so the finally-block below records whatever rows completed.
    import signal

    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))

    nv = base.num_envs
    sweep = []
    split = {"skipped": True}
    completed = False
    try:
        for label, variant in (
            (f"{nv}envs", base),
            (f"{2 * nv}envs", base.replace(num_envs=2 * nv)),
            (
                f"{4 * nv}envs_fit",
                base.replace(num_envs=4 * nv, grad_accum=4, remat=True),
            ),
            # The MXU lane-utilization experiment (docs/MFU.md): channel
            # widths 64/128/128 raise the conv N-dimension ceiling from
            # ~22% to ~100% of the 128-wide array. If the analysis is
            # right, this variant's MFU is ~4x the base at similar
            # fps-per-FLOP — evidence that the base MFU is architecture-
            # bound, not scheduling-bound. Wide activations are ~4x the
            # narrow ones (same footprint as the narrow 4x-envs
            # geometry), so it needs the same grad_accum+remat fit.
            (
                "wide_torso_fit",
                base.replace(
                    channels=(64, 128, 128), grad_accum=4, remat=True
                ),
            ),
        ):
            try:
                row = measure(variant, preset_name)
            except Exception as e:  # per-variant OOM must not kill the probe
                sweep.append({"label": label, "error": str(e)[:300]})
                continue
            row["label"] = label
            sweep.append(row)
            print(json.dumps(row))

        try:
            split = phase_split(base)
            print(json.dumps(split))
        except Exception as e:  # the sweep rows must get banked regardless
            split = {"error": str(e)[:300]}
            print(f"mfu_probe: phase split failed: {e}", file=sys.stderr)
        completed = True
    finally:
        # Bank whatever exists — a timeout/flap mid-probe loses only the
        # in-flight variant, not the window's completed measurements. An
        # interrupted probe exits nonzero and the watcher retries, so the
        # retry's FULL row would sit next to this one: partial=true lets
        # consumers prefer the complete row (ADVICE r4 — no silent dupes).
        if sweep:
            entry = {
                "kind": "mfu_probe",
                "preset": preset_name,
                **bench_history.device_entry(),
                "sweep": sweep,
                "phase_split_base": split,
                **({} if completed else {"partial": True}),
            }
            try:
                bench_history.record(entry)
            except OSError as e:
                print(f"mfu_probe: could not persist: {e}", file=sys.stderr)
    print(json.dumps({"ok": True, "rows": len(sweep)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
