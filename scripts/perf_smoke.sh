#!/usr/bin/env bash
# Perf smoke: A/B the zero-copy overlapped data path (config.overlap_h2d,
# rollout/staging.py) against the legacy copy-and-stack drain on a tiny
# pong_impala-shaped sebulba run, printing both fps numbers and the
# pipeline metrics (h2d_wait_s / h2d_bytes / learner_stall_frac /
# slab_reuse_waits), and failing if the overlapped path is slower or the
# two paths' losses diverge on the fixed seed.
#
# This is the operator-facing sibling of tests/test_perf_smoke.py: the
# same A/B, but with a longer measurement window and a strict speed
# assertion — run it on quiet hardware.
#
# Usage: scripts/perf_smoke.sh                    # CPU, ~1-2 min
#        ASYNCRL_SMOKE_UPDATES=64 scripts/perf_smoke.sh
#        ASYNCRL_SMOKE_TOLERANCE=1.10 scripts/perf_smoke.sh  # allow 10% noise
#        ASYNCRL_SMOKE_RECORD=1 scripts/perf_smoke.sh  # append the A/B as a
#          kind="host_path" probe="overlap_ab" row to BENCH_HISTORY.json
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
UPDATES="${ASYNCRL_SMOKE_UPDATES:-24}"
# Default tolerance absorbs scheduler noise on a shared 1-core box (the
# actor and learner threads fight for the same core, swinging identical
# configs ±25% run to run); tighten on quiet multi-core hardware.
TOLERANCE="${ASYNCRL_SMOKE_TOLERANCE:-1.15}"
RECORD="${ASYNCRL_SMOKE_RECORD:-0}"

python - "$UPDATES" "$TOLERANCE" "$RECORD" <<'EOF'
import sys
import time

import numpy as np

from asyncrl_tpu import make_agent
from asyncrl_tpu.configs import presets

updates, tolerance = int(sys.argv[1]), float(sys.argv[2])
record = sys.argv[3] not in ("", "0")
NUM_ENVS, UNROLL = 16, 16
steps = updates * NUM_ENVS * UNROLL


def run(overlap: bool):
    cfg = presets.get("pong_impala").replace(
        backend="sebulba", host_pool="jax", num_envs=NUM_ENVS,
        actor_threads=1, unroll_len=UNROLL, precision="f32", log_every=4,
        seed=3, hidden_sizes=(64, 64),
        # Frozen behaviour params: losses must be seed-deterministic for
        # the identity assertion (no publish-timing race).
        actor_staleness=1_000_000,
        overlap_h2d=overlap,
    )
    agent = make_agent(cfg)
    try:
        agent.train(total_env_steps=NUM_ENVS * UNROLL)  # jit warm-up
        t0 = time.perf_counter()
        history = agent.train(total_env_steps=NUM_ENVS * UNROLL + steps)
        elapsed = time.perf_counter() - t0
    finally:
        agent.close()
    fps = steps / elapsed
    losses = [h["loss"] for h in history]
    label = "overlap_h2d=on " if overlap else "overlap_h2d=off"
    last = history[-1]
    print(
        f"perf_smoke {label}: fps={fps:12,.0f}  "
        f"h2d_wait_s={last['h2d_wait_s']:.4f}  "
        f"h2d_bytes={int(last['h2d_bytes'])}  "
        f"learner_stall_frac={last['learner_stall_frac']:.3f}  "
        f"slab_reuse_waits={int(last.get('slab_reuse_waits', 0))}"
    )
    return fps, losses


# Measurement discipline for a contended box: the FIRST training run in
# a process is systematically ~25% slow (XLA/threadpool/allocator warm-up
# that outlives the per-agent jit warm-up), so a naive on-then-off pair
# biases against whichever path runs first. Discard a warm-up run
# entirely, then alternate off/on/off/on and take best-of-two per mode.
run(True)  # discarded process warm-up
fps_off, losses_off = run(False)
fps_on, losses_on = run(True)
fps_off2, _ = run(False)
fps_on2, _ = run(True)
fps_on, fps_off = max(fps_on, fps_on2), max(fps_off, fps_off2)

if not np.array_equal(np.asarray(losses_on), np.asarray(losses_off)):
    sys.exit(
        "perf_smoke FAILED: overlap on/off losses diverged on a fixed seed"
    )
print(f"perf_smoke: losses identical across {len(losses_on)} windows")

if fps_on * tolerance < fps_off:
    sys.exit(
        f"perf_smoke FAILED: overlapped path slower "
        f"({fps_on:,.0f} vs {fps_off:,.0f} fps, tolerance {tolerance}x)"
    )
print(
    f"perf_smoke OK: overlapped {fps_on:,.0f} fps vs legacy "
    f"{fps_off:,.0f} fps ({fps_on / fps_off:.2f}x)"
)

if record:
    from asyncrl_tpu.utils import bench_history

    entry = bench_history.record({
        "kind": "host_path",
        "probe": "overlap_ab",
        "preset": "pong_impala(sebulba tiny)",
        **bench_history.device_entry(),
        "num_envs": NUM_ENVS,
        "actor_threads": 1,
        "unroll_len": UNROLL,
        "updates": updates,
        "pipeline_fps": round(fps_on),
        "pipeline_fps_legacy": round(fps_off),
        "overlap_speedup": round(fps_on / fps_off, 3),
    })
    print("perf_smoke: recorded", entry["ts"])
EOF
