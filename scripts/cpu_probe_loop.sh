#!/bin/bash
# Supervised CPU training loop that YIELDS the single CPU core to TPU
# windows: run_to_target sessions pinned to the CPU backend
# (ASYNCRL_FORCE_CPU — provenance stays platform=cpu; the watcher's
# target_reached ignores cpu rows), with a supervisor that yields the
# moment the window watcher reports the tunnel UP. Two yield modes:
#
#   YIELD_MODE=stop (default): SIGSTOP the session, SIGCONT when the
#     tunnel is DOWN again. Preserves the session's XLA compile (minutes
#     on this box) and costs the window zero CPU — but run_to_target's
#     perf_counter clock KEEPS TICKING while stopped, so the arm's
#     recorded time_to_target seconds include pause time. Use for
#     LEARNABILITY probes whose evidence is the env_steps-vs-return
#     curve, never for an arm whose wall-clock number will be cited.
#   YIELD_MODE=term: SIGTERM the session (its sidecar persists training
#     clock on every metrics drain, so inter-session gaps are excluded
#     — clock-honest) and relaunch when the tunnel is DOWN. Pays a
#     recompile per window; use for t2t measurement arms.
#
# Sessions resume from checkpoints; the loop exits when the run records
# ANY time_to_target completion for this dir's preset (in-run budget
# decides reached true/false) or MAX_SESSIONS spend out.
#
#   nohup bash scripts/cpu_probe_loop.sh <preset> <checkpoint_dir> \
#       [extra overrides...] > /tmp/probe.log 2>&1 &
#
# Env knobs: YIELD_MODE (stop|term; default stop), SESSION_SECONDS
# (running time per session, pause excluded; default 1200),
# BUDGET_SECONDS (run_to_target budget), MAX_SESSIONS (default 40; only
# sessions that got >= half their running time count — a yield-
# terminated sliver must not burn the session budget).
set -u
cd "$(dirname "$0")/.."
PRESET=${1:?usage: cpu_probe_loop.sh <preset> <checkpoint_dir> [overrides...]}
DIR=${2:?usage: cpu_probe_loop.sh <preset> <checkpoint_dir> [overrides...]}
shift 2
export ASYNCRL_FORCE_CPU=1
export BENCH_NO_WAIT=1
# In stop mode, SIGSTOP pause time still ticks inside run_to_target's
# perf_counter budget check — a long tunnel window would exhaust a tight
# budget with no training done and settle the arm reached=false. Default
# the in-run budget effectively out of the way; the probe's real bound is
# MAX_SESSIONS x SESSION_SECONDS of RUNNING time. term mode keeps an
# honest (clock-meaningful) default.
if [ "${YIELD_MODE:-stop}" = "stop" ]; then
  BUDGET=${BUDGET_SECONDS:-600000}
else
  BUDGET=${BUDGET_SECONDS:-72000}
fi

tunnel_down() {
  local log mtime now
  # A dead watcher must not wedge the probe forever behind its stale log:
  # no live tpu_window.sh process means the core is free regardless of
  # what the leftover log says. (This pgrep pattern cannot self-match:
  # this script's own cmdline does not contain "tpu_window".)
  pgrep -f "tpu_window.sh" >/dev/null 2>&1 || return 0
  log=$(ls -t /tmp/tpu_window*.log 2>/dev/null | head -1)
  [ -n "$log" ] || return 0  # watcher just started, no log yet
  now=$(date +%s)
  mtime=$(stat -c %Y "$log" 2>/dev/null || echo 0)
  # The watcher prints a DOWN line every ~60-150s; during a window the
  # last line is job output (and may sit unchanged for a long job) —
  # only a fresh DOWN line proves the core is free.
  [ $((now - mtime)) -lt 180 ] && tail -1 "$log" | grep -q "tunnel DOWN"
}

# supervise <pid>: STOP/CONT (or term-yield) the session around tunnel
# windows; TERM it once its RUNNING time (pauses excluded) exceeds
# SESSION_SECONDS. Returns the session's exit code; leaves the running
# seconds in RAN_SECONDS so the caller can tell a full session from a
# yield-terminated sliver.
RAN_SECONDS=0
supervise() {
  local pid="$1" ran=0 paused=0
  RAN_SECONDS=0
  # The supervised pid is the timeout BACKSTOP wrapper; STOP/CONT/KILL
  # must also reach its python child or the pause would stop only the
  # wrapper. pgrep -P (exact parent-pid match, no pattern — immune to
  # the cmdline self-match trap) finds it; TERM goes to the wrapper
  # alone, which forwards it to the child.
  sig_all() { kill -"$1" "$pid" $(pgrep -P "$pid") 2>/dev/null; }
  end_session() {
    kill -TERM "$pid" 2>/dev/null  # timeout forwards TERM to the child
    sleep 10
    sig_all KILL
    wait "$pid" 2>/dev/null
  }
  while kill -0 "$pid" 2>/dev/null; do
    if tunnel_down; then
      if [ "$paused" -eq 1 ]; then
        sig_all CONT
        paused=0
        echo "--- $(date -u +%FT%TZ) tunnel DOWN again; session resumed"
      fi
      sleep 30
      ran=$((ran + 30))
      RAN_SECONDS=$ran
      if [ "$ran" -ge "${SESSION_SECONDS:-1200}" ]; then
        end_session
        return 124  # session clock expired: caller relaunches
      fi
    else
      if [ "${YIELD_MODE:-stop}" = "term" ]; then
        # Clock-honest yield: end the session (sidecar already holds its
        # training clock up to the last drain) and relaunch on DOWN.
        echo "--- $(date -u +%FT%TZ) tunnel window: session terminated (YIELD_MODE=term)"
        end_session
        return 124
      fi
      if [ "$paused" -eq 0 ]; then
        sig_all STOP
        paused=1
        echo "--- $(date -u +%FT%TZ) tunnel window: session paused (SIGSTOP)"
      fi
      sleep 60
    fi
  done
  wait "$pid" 2>/dev/null
  return $?
}

sessions=0
while [ "$sessions" -lt "${MAX_SESSIONS:-40}" ]; do
  until tunnel_down; do
    echo "--- $(date -u +%FT%TZ) tunnel window active (or watcher stale); waiting to start"
    sleep 120
  done
  echo "=== $(date -u +%FT%TZ) cpu probe session $((sessions + 1)) ($PRESET -> $DIR)"
  # The timeout wrapper is the orphan backstop: if this supervisor shell
  # dies, the session's DIRECT parent still bounds it (3x the session
  # clock covers stop-mode pauses; the final KILL ends even a process
  # left SIGSTOPped). Normal sessions are ended by supervise long before
  # this fires.
  timeout -k 10 $((${SESSION_SECONDS:-1200} * 3)) \
    python scripts/run_to_target.py "$PRESET" \
      --target 18.0 --budget-seconds "$BUDGET" \
      checkpoint_dir="$DIR" checkpoint_every=50 "$@" &
  supervise $!
  rc=$?
  # A yield-terminated sliver (term mode: session ended by a tunnel
  # window before half its running time) must not burn the session
  # budget — a flappy night would otherwise exhaust MAX_SESSIONS on
  # recompiles with almost no training done.
  if [ "$RAN_SECONDS" -ge $((${SESSION_SECONDS:-1200} / 2)) ]; then
    sessions=$((sessions + 1))
  fi
  echo "=== rc=$rc ran=${RAN_SECONDS}s session_count=$sessions"
  # Relaunch ONLY on the supervisor's session-clock expiry / yield (124)
  # or an external kill (137/143): resume next session. Any other exit
  # means the measurement settled — rc=0 reached, rc=1 budget-exhausted
  # reached=false, rc=3 refused (already complete) — and relaunching
  # would append one duplicate reached=false ledger row per session.
  case "$rc" in 124|137|143) sleep 5 ;; *) break ;; esac
done
