#!/usr/bin/env bash
# Chaos smoke: a short sebulba training job under EACH fault site in turn,
# failing on any non-recovered death. This is the operator-facing sibling
# of `pytest -m chaos` (tests/test_faults.py): same recovery matrix, but
# driven through the public config surface (fault_spec / ASYNCRL_FAULTS
# grammar, utils/faults.py) the way a cluster chaos run would drive it.
#
# Usage: scripts/chaos_smoke.sh            # CPU, ~1 min
#        ASYNCRL_CHAOS_STEPS=1024 scripts/chaos_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
STEPS="${ASYNCRL_CHAOS_STEPS:-512}"

run_one() {
  local label="$1" spec="$2" extra="${3:-}"
  echo "=== chaos_smoke: ${label} (${spec:-unarmed}) ==="
  python - "$spec" "$STEPS" "$extra" <<'EOF'
import sys

spec, steps, extra = sys.argv[1], int(sys.argv[2]), sys.argv[3]

from asyncrl_tpu import make_agent
from asyncrl_tpu.utils.config import Config, override

cfg = Config(
    env_id="CartPole-v1", algo="a3c", backend="sebulba", host_pool="jax",
    num_envs=16, actor_threads=2, unroll_len=4, precision="f32",
    log_every=2, fault_spec=spec,
)
if extra:
    cfg = override(cfg, [kv for kv in extra.split(",") if kv])
agent = make_agent(cfg)
try:
    history = agent.train(total_env_steps=steps)
except Exception as e:
    print(f"chaos_smoke FAILED: training did not recover: {e}", file=sys.stderr)
    raise
finally:
    agent.close()

if agent.env_steps < steps:
    sys.exit(f"chaos_smoke FAILED: reached {agent.env_steps}/{steps} env steps")
window = history[-1]
recovered = (
    window.get("actor_restarts", 0)
    + window.get("server_restarts", 0)
    + sum(v for k, v in window.items() if k.startswith("fault_checkpoint"))
)
if spec and not recovered:
    sys.exit("chaos_smoke FAILED: armed fault produced no recovery activity")
print(
    "chaos_smoke OK:", agent.env_steps, "steps;",
    {k: v for k, v in window.items()
     if "restart" in k or k.startswith("fault_") or k == "queue_backpressure"},
)
EOF
}

# Baseline: unarmed sites must be invisible.
run_one "baseline (no faults)" ""

# One crash per component of the async pipeline.
run_one "actor step crash"      "actor.step:crash:1.0:0:max=1"
run_one "fragment handoff crash" "actor.queue_put:crash:1.0:0:max=1"
run_one "env pool crash"        "pool.step:crash:1.0:0:max=1"
# Both shared-server cores, each through ITS fault site: serve=True (the
# default since the serve core landed) routes inference through
# serve.dispatch — arming server.serve there never fires (the legacy
# site), which silently made this case vacuous until the health-smoke
# round caught it.
run_one "inference server crash (legacy)" "server.serve:crash:1.0:0:max=1" "inference_server=True,serve=False"
run_one "serve-core dispatch crash" "serve.dispatch:crash:1.0:0:max=1" "inference_server=True"

# A hung actor, recovered by the heartbeat watchdog.
run_one "actor stall + watchdog" "actor.step:stall:1.0:0:max=1,stall_s=60" "stall_timeout_s=1.0"

# Checkpoint save under injected failure (bounded retry absorbs it).
TMP_CK="$(mktemp -d)"
trap 'rm -rf "$TMP_CK"' EXIT
run_one "checkpoint save crash" "checkpoint.save:crash:1.0:0:max=2" "checkpoint_dir=${TMP_CK}/ck,checkpoint_every=2"

# The serving fleet's replica kind (fleet.replica site): the trainer
# does not mount a fleet, so this scenario drives the replicated tier
# standalone — kill one replica's serve core mid-traffic and require the
# supervised rebuild AND uninterrupted serving from the survivor.
echo "=== chaos_smoke: replica kill (fleet.replica:replica:rmode=kill) ==="
python - <<'EOF'
import sys
import time

import numpy as np

from asyncrl_tpu.obs import registry as obs_registry
from asyncrl_tpu.serve import FleetRouter, ParamFeed, ServeFleet
from asyncrl_tpu.utils import faults

faults.arm("fleet.replica:replica:1.0:0:rmode=kill,max=1,replica=r0")


def fn(params, obs, key):
    rows = obs.shape[0]
    value = int(params["v"])
    return (
        np.full((rows,), value, np.int32),
        np.zeros((rows,), np.float32),
        key,
    )


feed = ParamFeed({"v": 0})
fleet = ServeFleet(fn, feed, num_replicas=2, deadline_ms=2.0,
                   readmit_after_s=0.05, tick_interval_s=0.02)
fleet.start()
router = FleetRouter(fleet, obs_shape=(4,))
obs = np.zeros((2, 4), np.float32)
victim = fleet.replicas[0]
served = set()
deadline = time.monotonic() + 20.0
try:
    while time.monotonic() < deadline:
        actions, _, version, extras = router.act("default", obs, 500.0)
        if actions.tolist() != [version] * 2:
            sys.exit(f"chaos_smoke FAILED: generation mixing "
                     f"(actions {actions.tolist()} under version {version})")
        served.add(extras["replica"])
        if victim.restarts >= 1 and served == {"r0", "r1"}:
            break
        time.sleep(0.01)
finally:
    router.close()
    fleet.close()
    faults.disarm()

restarts = obs_registry.counter("fleet_replica_restarts").value()
if victim.restarts < 1 or restarts < 1:
    sys.exit("chaos_smoke FAILED: replica kill produced no supervised rebuild")
if served != {"r0", "r1"}:
    sys.exit(f"chaos_smoke FAILED: rebuilt replica never rejoined "
             f"(served: {sorted(served)})")
print("chaos_smoke OK: replica killed, rebuilt (restarts",
      int(restarts), ") and back in rotation")
EOF

echo "=== chaos_smoke: all fault sites recovered ==="
