#!/bin/bash
# CPU learnability probe for the pixel-path recipe (round 5): before chip
# windows are spent on pong_pixels_t2t, find out on the CPU whether the
# skip-4 pixel recipe (shaping, gamma, CNN torso) produces a learning
# signal AT ALL. This is NOT a time-to-target measurement — it runs a
# CPU-feasible geometry (128 envs, no grad_accum/remat, lr scaled with
# batch, rare 8-episode evals: the 27,200-step eval scan is minutes on
# CPU) with the preset's shaping economics, into its own arm dir. Signal
# sought: training episode_return clearly above the random floor within
# the overnight frame budget; its absence falsifies the recipe before it
# costs a window. Core-yielding supervision lives in cpu_probe_loop.sh
# (sessions SIGSTOP during TPU windows).
#
#   nohup bash scripts/cpu_pixel_probe.sh > /tmp/cpu_pixel_probe.log 2>&1 &
set -u
exec bash "$(dirname "$0")/cpu_probe_loop.sh" \
  pong_pixels_t2t "${1:-runs/pong18_pixels_cpu}" \
  num_envs=128 grad_accum=1 remat=false updates_per_call=2 \
  learning_rate=1.5e-4 eval_every=400 eval_episodes=8
