"""Diagnose a trained Pong policy against the 18.0 bar: WHERE do points go?

Loads the latest checkpoint from a run dir, plays N greedy games against the
standard tracker, and reports the stats that separate plateaued (~+4) play
from oracle (~+19) play (scripts/pong_oracle.py):

- defense: points conceded per game, and the paddle-to-ball miss margin
  (how far away was the paddle when the ball got past?)
- offense: points won per game, the agent's contact-offset distribution
  (|offset| ~ 1 = edge hits = max spin; the oracle's winning exploit), and
  the tracker's miss margin on points won.

    python scripts/pong_diagnose.py runs/pong18 [games]

Prints one JSON line of aggregates.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # analysis tool; axon hangs when down

import jax.numpy as jnp
import numpy as np

from asyncrl_tpu.configs import presets
from asyncrl_tpu.envs.pong import PADDLE_HALF, Pong
from asyncrl_tpu.models.networks import is_recurrent
from asyncrl_tpu.ops.normalize import normalizing_apply
from asyncrl_tpu.utils import checkpoint as ckpt_mod
from asyncrl_tpu.utils.config import override

MAX_STEPS = 3000


def load_params(run_dir: str, cfg):
    # create=False: a typo'd run dir must raise, not leave an empty
    # directory behind (the checkpoint.setup read-only-restore contract).
    with ckpt_mod.Checkpointer(run_dir, create=False) as ck:
        step = ck.latest_step()
    if step is None:
        raise SystemExit(f"no checkpoint under {run_dir}")
    from asyncrl_tpu.api.trainer import Trainer

    trainer = Trainer(cfg.replace(checkpoint_dir=""), restore=run_dir)
    return trainer, trainer.state.params, trainer.model, step


def diagnose(apply_fn, params, games: int, seed: int = 7):
    env = Pong()

    def one(key):
        st = env.init(key)

        def body(carry, k):
            st, done = carry
            obs = env.observe(st)
            logits = apply_fn(params, obs[None])[0][0]
            a = jnp.argmax(logits).astype(jnp.int32)
            st2, ts = env.step(st, a, k)
            rec = {
                "reward": jnp.where(done, 0.0, ts.reward),
                # last_obs is the un-reset end-of-step view.
                "ball_y_end": ts.last_obs[1],
                "agent_y_end": ts.last_obs[4],
                "opp_y_end": ts.last_obs[5],
                "alive": (~done).astype(jnp.float32),
            }
            st2 = jax.tree.map(lambda n_, o: jnp.where(done, o, n_), st2, st)
            return (st2, done | ts.done), rec

        keys = jax.random.split(key, MAX_STEPS)
        (_, _), recs = jax.lax.scan(body, (st, jnp.asarray(False)), keys)
        return recs

    keys = jax.random.split(jax.random.PRNGKey(seed), games)
    recs = jax.jit(jax.vmap(one))(keys)
    return {k: np.asarray(v) for k, v in recs.items()}


def main() -> int:
    run_dir = sys.argv[1] if len(sys.argv) > 1 else "runs/pong18"
    games = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    cfg = presets.get("pong_impala")
    cfg = override(cfg, [a for a in sys.argv[3:] if "=" in a])

    trainer, params, model, step = load_params(run_dir, cfg)
    if is_recurrent(model):
        raise SystemExit(
            "pong_diagnose analyzes feed-forward policies only; use "
            "cli/play.py --save for recurrent trajectory dumps"
        )
    # Same normalized view the policy trained on (identity when stats are
    # None) — raw obs into a normalized-trained net would misdescribe it.
    apply_fn = normalizing_apply(model.apply, trainer.state.obs_stats)

    recs = diagnose(apply_fn, params, games)
    # vmap(one) stacks games on the LEADING axis: every rec is [games, T].
    rew = recs["reward"] * recs["alive"]
    won = (rew > 0).sum(axis=1)
    lost = (rew < 0).sum(axis=1)

    # Miss margin on conceded points: |ball_y - agent_y| - PADDLE_HALF at
    # the step the point was lost (ball got past the agent plane).
    lost_mask = rew < 0
    miss_margin = np.abs(recs["ball_y_end"] - recs["agent_y_end"]) - PADDLE_HALF
    win_mask = rew > 0
    win_margin = np.abs(recs["ball_y_end"] - recs["opp_y_end"]) - PADDLE_HALF

    out = {
        "checkpoint_step": step,
        "games": games,
        "mean_return": round(float((won - lost).mean()), 2),
        "points_won_per_game": round(float(won.mean()), 2),
        "points_lost_per_game": round(float(lost.mean()), 2),
        "concede_miss_margin_mean": round(
            float(miss_margin[lost_mask].mean()), 4
        )
        if lost_mask.any()
        else None,
        "concede_miss_margin_p90": round(
            float(np.quantile(miss_margin[lost_mask], 0.9)), 4
        )
        if lost_mask.any()
        else None,
        "win_opp_miss_margin_mean": round(
            float(win_margin[win_mask].mean()), 4
        )
        if win_mask.any()
        else None,
        "episode_len_mean": round(float(recs["alive"].sum(axis=1).mean()), 1),
    }
    print(json.dumps(out))
    # Persist the diagnosis in the evidence trail: plateau-breaking recipe
    # changes (e.g. the round-3 scoring-rate recipe in tpu_window.sh) cite
    # these numbers, so the ledger should carry what was actually measured.
    from asyncrl_tpu.utils import bench_history

    try:
        bench_history.record(
            {
                "kind": "diagnosis",
                "name": "pong_points_decomposition",
                "run_dir": run_dir,
                # NOT device_entry(): this analysis tool pins the CPU
                # backend, so those fields would mislabel a TPU-trained
                # checkpoint's diagnosis as CPU evidence.
                "analysis_platform": "cpu",
                **out,
            }
        )
    except OSError as e:
        print(f"bench_history: could not persist: {e}", file=sys.stderr)
    trainer.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
