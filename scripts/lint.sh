#!/usr/bin/env bash
# Lint gate for asyncrl-tpu: ruff (curated rule set in pyproject.toml)
# plus the framework-aware static passes (python -m asyncrl_tpu.analysis:
# lock discipline, JAX purity, donation safety, thread ownership,
# deadlock/lock-order, device contracts, config contracts, protocol
# typestate, async-signal safety, SPMD sharding contracts, multi-host
# collective congruence, Pallas DMA discipline, deadline flow, token
# refund, time-unit soundness, lockset race detection). The default
# package run covers EVERY
# subpackage — asyncrl_tpu/obs/ (span rings, flight recorder) included,
# so its guarded-by/thread-entry annotations gate like the rest of the
# concurrency substrate. Focused gates beyond the package run live in
# the GATES manifest below — one loop, no hand-maintained command
# blocks: the entry points (scripts/*.py, bench.py, __graft_entry__.py)
# under configflow + the SPMD passes + the wire-budget trio (a smoke
# script that sleeps a millisecond value or drops a deadline guard gates
# here), and the serve/kernel files whose gating must survive any future
# package file-set edit.
#
#   scripts/lint.sh            # lint the package + script entries (CI gate)
#   scripts/lint.sh --fast     # warm-cache mode: a full analyzer cache hit
#                              # replays the manifest AND skips the ruff
#                              # re-run — the gate stays sub-second on an
#                              # unchanged tree (the verify skill's loop).
#                              # The skip keys on the PACKAGE manifest, so
#                              # ruff findings in tests/, scripts/, or
#                              # bench.py edits are deferred to the next
#                              # full run — CI uses plain lint.sh.
#   scripts/lint.sh path.py    # lint specific files (fixtures exit nonzero)
#
# The package run is incremental (--cache-dir .analysis-cache: a second
# consecutive run with no edits replays the manifest without re-parsing)
# and machine-readable (--format json into lint_report.json, stable
# finding IDs). The scripts run caches separately
# (.analysis-cache-scripts): manifests key on the pass tuple, so sharing
# one cache dir would invalidate both manifests every run. Both runs exit
# nonzero on any finding NOT grandfathered in
# asyncrl_tpu/analysis/baseline.json — new findings gate PRs while
# baselined ones burn down explicitly. ruff is optional at runtime (not
# vendored in the training image); the analysis passes always run and
# always gate.
set -u
cd "$(dirname "$0")/.."

fast=0
if [ "${1:-}" = "--fast" ]; then
    fast=1
    shift
fi

run_ruff() {
    if command -v ruff >/dev/null 2>&1; then
        ruff check asyncrl_tpu tests scripts bench.py || rc=1
    elif python -c "import ruff" >/dev/null 2>&1; then
        python -m ruff check asyncrl_tpu tests scripts bench.py || rc=1
    else
        echo "lint.sh: ruff not installed; skipping ruff (analysis passes still gate)" >&2
    fi
}

rc=0
if [ "$#" -gt 0 ]; then
    # Explicit paths: plain text, no cache (fixture runs must not pollute
    # or consult the package manifest).
    run_ruff
    python -m asyncrl_tpu.analysis "$@" || rc=1
    exit $rc
fi

python -m asyncrl_tpu.analysis \
    --cache-dir .analysis-cache \
    --format json --stats \
    > lint_report.json || rc=1

# The race pass must have RUN on the package and found nothing: a
# report where the `races` key is missing means the pass silently fell
# out of the run (a regression the zero-findings exit code would hide).
python - <<'EOF' || rc=1
import json
import sys

with open("lint_report.json") as fh:
    per_pass = json.load(fh)["stats"]["findings_per_pass"]
if per_pass.get("races") != 0:
    print(
        "lint.sh: expected findings_per_pass['races'] == 0, got "
        f"{per_pass.get('races')!r}", file=sys.stderr,
    )
    sys.exit(1)
EOF

# Focused gates, ONE manifest: "name|passes|paths". Each entry gets its
# own cache dir (.analysis-cache-<name>) because manifests key on the
# (file set, pass tuple) pair — sharing a dir would invalidate both
# manifests on every run (the PR-11 scripts-manifest lesson).
#
# - scripts: every repo entry point under configflow (CFG003: smoke
#   scripts can't invent unregistered ASYNCRL_* env vars), the SPMD
#   passes (a launch script that builds its mesh before
#   jax.distributed.initialize, or an unpaired DMA — HSY002/PAL001 and
#   friends), the wire-budget trio (deadline flow, token refund,
#   time-unit soundness: a script that feeds an ms value to time.sleep
#   gates here), and the race pass (a script that spawns a bare
#   Thread against undeclared shared state gates here).
# - fleet: the replicated serving tier is lease-protocol and lock-order
#   critical (held serve-stale anchors, replica rebuild under the fleet
#   tick, the probe/readmit typestate) — gated explicitly so a future
#   baseline or package file-set edit can never silently un-gate it.
# - kernels: the PR-17 device hot path contracts (Pallas DMA start/wait
#   in the fused scan and RDMA ring, sharding hygiene in the ring's
#   collectives, the devq-lease typestate in the HBM rollout queue),
#   explicit for the same un-gating reason.
# - requests: the request hop journal's budget arithmetic (deadline flow
#   into budget_remaining_ms, ms-vs-s unit soundness, the rate-token
#   refund protocol its gateway call sites participate in) — gated
#   explicitly so the wire-tracing layer can never silently drift out of
#   the deadline/refund contract set.
GATES=(
    "scripts|configflow,sharding,hostsync,pallas,deadlines,refund,units,races|scripts/*.py bench.py __graft_entry__.py"
    "fleet|protocols,deadlock|asyncrl_tpu/serve/fleet.py"
    "kernels|pallas,sharding,protocols|asyncrl_tpu/ops/pallas_scan.py asyncrl_tpu/ops/ring_reduce.py asyncrl_tpu/rollout/device_queue.py"
    "requests|deadlines,refund,units,protocols|asyncrl_tpu/obs/requests.py"
)
for gate in "${GATES[@]}"; do
    name="${gate%%|*}"
    rest="${gate#*|}"
    passes="${rest%%|*}"
    paths="${rest#*|}"
    pass_args=()
    for p in ${passes//,/ }; do
        pass_args+=(--pass "$p")
    done
    # $paths is a glob-bearing word list on purpose (scripts/*.py).
    # shellcheck disable=SC2086
    python -m asyncrl_tpu.analysis "${pass_args[@]}" \
        --cache-dir ".analysis-cache-$name" $paths || rc=1
done

if [ "$fast" -eq 1 ] && [ "$rc" -eq 0 ] && python - <<'EOF'
import json
import sys

try:
    with open("lint_report.json") as fh:
        stats = json.load(fh)["stats"]
except Exception:
    sys.exit(1)
sys.exit(0 if stats.get("cache") == "warm" else 1)
EOF
then
    echo "lint.sh: --fast analyzer cache warm; skipping ruff re-run" >&2
else
    run_ruff
fi
exit $rc
