#!/usr/bin/env bash
# Lint gate for asyncrl-tpu: ruff (curated rule set in pyproject.toml)
# plus the framework-aware static passes (python -m asyncrl_tpu.analysis:
# lock discipline, JAX purity, donation safety, thread ownership,
# deadlock/lock-order, device contracts, config contracts, protocol
# typestate, async-signal safety, SPMD sharding contracts, multi-host
# collective congruence, Pallas DMA discipline). The default package run
# covers EVERY subpackage — asyncrl_tpu/obs/ (span rings, flight
# recorder) included, so its guarded-by/thread-entry annotations gate
# like the rest of the concurrency substrate — plus ALL the repo entry
# points (scripts/*.py, bench.py, __graft_entry__.py) under the
# entry-point pass set: configflow (CFG003: smoke scripts can't invent
# unregistered ASYNCRL_* env vars) and the three SPMD passes (a launch
# script that builds its mesh before jax.distributed.initialize, or a
# validation script with an unpaired DMA, gates here — HSY002/PAL001
# and friends).
#
#   scripts/lint.sh            # lint the package + script entries (CI gate)
#   scripts/lint.sh --fast     # warm-cache mode: a full analyzer cache hit
#                              # replays the manifest AND skips the ruff
#                              # re-run — the gate stays sub-second on an
#                              # unchanged tree (the verify skill's loop).
#                              # The skip keys on the PACKAGE manifest, so
#                              # ruff findings in tests/, scripts/, or
#                              # bench.py edits are deferred to the next
#                              # full run — CI uses plain lint.sh.
#   scripts/lint.sh path.py    # lint specific files (fixtures exit nonzero)
#
# The package run is incremental (--cache-dir .analysis-cache: a second
# consecutive run with no edits replays the manifest without re-parsing)
# and machine-readable (--format json into lint_report.json, stable
# finding IDs). The scripts run caches separately
# (.analysis-cache-scripts): manifests key on the pass tuple, so sharing
# one cache dir would invalidate both manifests every run. Both runs exit
# nonzero on any finding NOT grandfathered in
# asyncrl_tpu/analysis/baseline.json — new findings gate PRs while
# baselined ones burn down explicitly. ruff is optional at runtime (not
# vendored in the training image); the analysis passes always run and
# always gate.
set -u
cd "$(dirname "$0")/.."

fast=0
if [ "${1:-}" = "--fast" ]; then
    fast=1
    shift
fi

run_ruff() {
    if command -v ruff >/dev/null 2>&1; then
        ruff check asyncrl_tpu tests scripts bench.py || rc=1
    elif python -c "import ruff" >/dev/null 2>&1; then
        python -m ruff check asyncrl_tpu tests scripts bench.py || rc=1
    else
        echo "lint.sh: ruff not installed; skipping ruff (analysis passes still gate)" >&2
    fi
}

rc=0
if [ "$#" -gt 0 ]; then
    # Explicit paths: plain text, no cache (fixture runs must not pollute
    # or consult the package manifest).
    run_ruff
    python -m asyncrl_tpu.analysis "$@" || rc=1
    exit $rc
fi

python -m asyncrl_tpu.analysis \
    --cache-dir .analysis-cache \
    --format json --stats \
    > lint_report.json || rc=1

# Entry points: configflow + the SPMD contract passes. Own cache
# manifest (manifests key on the (file set, pass tuple) pair, so sharing
# the package dir would invalidate both manifests on every run — the
# PR-11 scripts-manifest pattern, now covering bench.py and
# __graft_entry__.py too).
python -m asyncrl_tpu.analysis \
    --pass configflow --pass sharding --pass hostsync --pass pallas \
    --cache-dir .analysis-cache-scripts \
    scripts/*.py bench.py __graft_entry__.py || rc=1

# The replicated serving tier is lease-protocol and lock-order critical
# (held serve-stale anchors, replica rebuild under the fleet tick, the
# probe/readmit typestate): run the protocol-typestate and deadlock
# passes over it EXPLICITLY, so a future baseline or file-set edit to
# the package run can never silently un-gate serve/fleet.py. Own cache
# dir — manifests key on the (file set, pass tuple) pair.
python -m asyncrl_tpu.analysis \
    --pass protocols --pass deadlock \
    --cache-dir .analysis-cache-fleet \
    asyncrl_tpu/serve/fleet.py || rc=1

# The device hot path's kernels carry the PR-17 contracts: Pallas DMA
# start/wait discipline in the fused scan and RDMA ring, SPMD sharding
# hygiene in the ring's collectives, and the devq-lease typestate in the
# HBM rollout queue. The package run covers them today; this explicit
# gate (the serve/fleet.py pattern) makes that non-optional — a future
# baseline or file-set edit to the package run can never silently
# un-gate the kernels. Own cache dir, same manifest-keying reason.
python -m asyncrl_tpu.analysis \
    --pass pallas --pass sharding --pass protocols \
    --cache-dir .analysis-cache-kernels \
    asyncrl_tpu/ops/pallas_scan.py asyncrl_tpu/ops/ring_reduce.py \
    asyncrl_tpu/rollout/device_queue.py || rc=1

if [ "$fast" -eq 1 ] && [ "$rc" -eq 0 ] && python - <<'EOF'
import json
import sys

try:
    with open("lint_report.json") as fh:
        stats = json.load(fh)["stats"]
except Exception:
    sys.exit(1)
sys.exit(0 if stats.get("cache") == "warm" else 1)
EOF
then
    echo "lint.sh: --fast analyzer cache warm; skipping ruff re-run" >&2
else
    run_ruff
fi
exit $rc
