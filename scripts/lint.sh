#!/usr/bin/env bash
# Lint gate for asyncrl-tpu: ruff (curated rule set in pyproject.toml)
# plus the framework-aware static passes (python -m asyncrl_tpu.analysis:
# lock discipline, JAX purity, donation safety, thread ownership,
# deadlock/lock-order, device contracts, config contracts). The default
# package run covers EVERY subpackage — asyncrl_tpu/obs/ (span rings,
# flight recorder) included, so its guarded-by/thread-entry annotations
# gate like the rest of the concurrency substrate.
#
#   scripts/lint.sh            # lint the package (CI gate)
#   scripts/lint.sh path.py    # lint specific files (fixtures exit nonzero)
#
# The package run is incremental (--cache-dir .analysis-cache: a second
# consecutive run with no edits replays the manifest without re-parsing)
# and machine-readable (--format json into lint_report.json, stable
# finding IDs). It exits nonzero on any finding NOT grandfathered in
# asyncrl_tpu/analysis/baseline.json — new findings gate PRs while
# baselined ones burn down explicitly. ruff is optional at runtime (not
# vendored in the training image); the analysis passes always run and
# always gate.
set -u
cd "$(dirname "$0")/.."

rc=0
if command -v ruff >/dev/null 2>&1; then
    ruff check asyncrl_tpu tests scripts bench.py || rc=1
elif python -c "import ruff" >/dev/null 2>&1; then
    python -m ruff check asyncrl_tpu tests scripts bench.py || rc=1
else
    echo "lint.sh: ruff not installed; skipping ruff (analysis passes still gate)" >&2
fi

if [ "$#" -gt 0 ]; then
    # Explicit paths: plain text, no cache (fixture runs must not pollute
    # or consult the package manifest).
    python -m asyncrl_tpu.analysis "$@" || rc=1
else
    python -m asyncrl_tpu.analysis \
        --cache-dir .analysis-cache \
        --format json --stats \
        > lint_report.json || rc=1
fi
exit $rc
