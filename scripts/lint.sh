#!/usr/bin/env bash
# Lint gate for asyncrl-tpu: ruff (curated rule set in pyproject.toml)
# plus the framework-aware static passes (python -m asyncrl_tpu.analysis:
# lock discipline, JAX purity, donation safety, thread ownership).
#
#   scripts/lint.sh            # lint the package (CI gate)
#   scripts/lint.sh path.py    # lint specific files (fixtures exit nonzero)
#
# Exits nonzero on ANY finding from either tool, so it can gate PRs.
# ruff is optional at runtime (not vendored in the training image); the
# analysis passes always run and always gate.
set -u
cd "$(dirname "$0")/.."

rc=0
if command -v ruff >/dev/null 2>&1; then
    ruff check asyncrl_tpu tests scripts bench.py || rc=1
elif python -c "import ruff" >/dev/null 2>&1; then
    python -m ruff check asyncrl_tpu tests scripts bench.py || rc=1
else
    echo "lint.sh: ruff not installed; skipping ruff (analysis passes still gate)" >&2
fi

python -m asyncrl_tpu.analysis "$@" || rc=1
exit $rc
