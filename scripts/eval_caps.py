"""Both-cap greedy evaluation of a trained Pong checkpoint (VERDICT round 3,
Weak #4 / Next #1): the built-in JaxPong truncates episodes at 3,000 agent
steps, while ALE's PongNoFrameskip-v4 allows 108,000 emulator frames =
27,000 skip-4 decisions (envs/pong.py ALE_MAX_STEPS). The 18.0-bar hunt
deliberately kept the tighter cap (scoring-RATE pressure, strictly harder);
this script makes that choice measurable by evaluating the SAME checkpoint
under both caps and appending one ``kind="eval_cap"`` ledger row per cap,
with the cap in row metadata.

    python scripts/eval_caps.py [preset] [--run-dir runs/pong18_tpu]
        [--episodes 32] [key=value ...]

The restore is read-only (``make_agent(restore=...)`` with an empty
checkpoint_dir): nothing under --run-dir is modified, so the resumable
time-to-target arm can keep accumulating in the same directory.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import cpu_fallback_or_refuse  # noqa: E402

# Single source of truth for the cap pair (ADVICE r4): the env constants,
# not re-typed numbers — a cap change in envs/pong.py propagates here.
from asyncrl_tpu.envs.pong import ALE_MAX_STEPS, MAX_STEPS  # noqa: E402

CAPS = (MAX_STEPS, ALE_MAX_STEPS)  # (repo default, ALE-faithful)


def main() -> int:
    import jax

    preset_name = "pong_t2t"
    run_dir = "runs/pong18_tpu"
    episodes = 32
    overrides = []
    it = iter(sys.argv[1:])
    for a in it:
        if a == "--run-dir":
            run_dir = next(it)
        elif a == "--episodes":
            episodes = int(next(it))
        elif "=" in a:
            overrides.append(a)
        else:
            preset_name = a

    if not os.path.isdir(run_dir):
        print(f"eval_caps: no run dir {run_dir!r}", file=sys.stderr)
        return 2

    # CPU is valid evidence here: greedy eval of a fixed policy measures the
    # POLICY, not the hardware; rows carry platform fields either way.
    cpu_fallback_or_refuse(jax, "eval_caps")

    from asyncrl_tpu.api.factory import make_agent
    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils import bench_history
    from asyncrl_tpu.utils.config import override

    if any(o.startswith("pong_max_steps=") for o in overrides):
        # The script's whole contract is the fixed both-cap sweep; an
        # override would run some third cap while the ledger rows still
        # claim the loop's caps.
        print(
            "eval_caps: pong_max_steps is set by the sweep itself and "
            "cannot be overridden",
            file=sys.stderr,
        )
        return 2

    dev = bench_history.device_entry()
    for cap in CAPS:
        # Overrides first, the sweep's own fields last — a user override
        # must never displace the cap the row's metadata records.
        cfg = override(presets.get(preset_name), overrides).replace(
            pong_max_steps=cap,
            checkpoint_dir="",  # read-only restore; never write to run_dir
            checkpoint_best=False,
        )
        # All three backends expose evaluate(..., return_episodes=True)
        # (SebulbaTrainer grew the path in round 5 — VERDICT r4 Weak #7),
        # so host-backend checkpoints are auditable under both caps too.
        trainer = make_agent(cfg, restore=run_dir)
        try:
            returns = trainer.evaluate(
                num_episodes=episodes,
                # Contain a full game under this cap (cap + serve slack).
                max_steps=cap + 200,
                return_episodes=True,
            )
        finally:
            trainer.close()
        returns = np.asarray(returns, np.float64)
        entry = bench_history.record(
            {
                "kind": "eval_cap",
                "preset": preset_name,
                **dev,
                "run_dir": run_dir,
                "pong_max_steps": cap,
                "ale_faithful_cap": cap >= 27_000,
                "episodes": int(returns.size),
                "eval_return": round(float(returns.mean()), 3),
                "eval_return_std": round(float(returns.std()), 3),
                "eval_return_min": round(float(returns.min()), 3),
                "eval_return_max": round(float(returns.max()), 3),
                "frac_ge_18": round(float((returns >= 18.0).mean()), 3),
            }
        )
        print(json.dumps(entry))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
