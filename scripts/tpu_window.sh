#!/bin/bash
# TPU window watcher, round-5 queue (VERDICT r4 Next #1/#2/#4/#5/#6): the
# axon tunnel flaps — minutes-long UP windows between outages. This loop
# probes liveness and, on each UP window, burns down a prioritized queue
# of real-TPU evidence jobs. Round-5 priority order inside a window:
#
#   1. ALE-faithful time-to-target (pong_t2t_ale, runs/pong18_ale seeded
#      from the accumulated strict-cap arm) — the round-5 headline: a
#      platform=tpu reached=true row (the r4 one was a CPU confirmation).
#      run_to_target now banks reached=true only after a 64-episode
#      fresh-seed confirmation eval.
#   1c. Coarse-to-fine curriculum arm (pong18_curr): from-scratch
#      <10-minute attack — 180s skip-4 burst then skip-1 finish
#      (CPU-validated at 6x fewer core frames than pure skip-1).
#   2. Fresh dual-flagship bench (bench.py driver mode: vector + pixel) —
#      once per window, so every round's BENCH artifact has a same-round
#      TPU pair.
#   3. Pixel-path 18.0 hunt (pong_pixels_t2t -> runs/pong18_pixels, its
#      own budget): the reference flagship's real shape (VERDICT r4 Next
#      #2); a multi-window accumulation arm — expectation 4.5-13.5B
#      decisions (see the preset), so each window banks curve + a
#      reached=false row, not a finish.
#   4. MFU probe incl. the wide-torso lane-ceiling experiment (r4 Next
#      #4) and the host-path inference-batch RTT sweep (r4 Next #6) —
#      promoted above the generic one-shots this round.
#   5. Strict-cap t2t sessions (alternating arms) — the harder
#      scoring-rate bar (r4 Next #5: drive it to a decision).
#   6. Remaining one-shot evidence rows, then long low-marginal jobs.
#
# One-shot jobs stamp /tmp/tpu_window_stamps/<name> on success or
# <name>.permfail on a deterministic failure (tunnel still up); the
# resumable training jobs accumulate wall clock in their run dirs.
#
#   nohup bash scripts/tpu_window.sh > /tmp/tpu_windowN.log 2>&1 &
#
# Every job runs with BENCH_NO_WAIT=1 (the watcher already established
# liveness; a mid-job flap should fail fast and surrender the window) and
# under `timeout` with process-group kill (the axon plugin hangs, not
# errors, when the tunnel dies under it — see bench._accelerator_alive).
set -u
cd "$(dirname "$0")/.."
STAMPS=/tmp/tpu_window_stamps
mkdir -p "$STAMPS"
export BENCH_NO_WAIT=1
# A flap between our probe and a job's own probe must FAIL the job (retry
# next window), not silently bank a CPU row as real-chip evidence.
export BENCH_REQUIRE_ACCELERATOR=1
# Per-arm training budget (seconds) for every time-to-target track; ONE
# definition, interpolated into the flag and the settle checks alike
# (ADVICE r3: the duplicated constant drifted).
BUDGET=10800
# The pixel arm's own, larger budget (VERDICT r4 Next #2 "its own
# budget"): the stated expectation is in the chip-DAYS range, so this
# arm is expected to exhaust windows, not budget — the cap exists so
# the queue can ever settle.
PIXEL_BUDGET=43200
# The coarse-to-fine curriculum arm should close in minutes at chip fps
# (CPU validation: 2.9B core frames); an hour means the transfer failed.
CURR_BUDGET=3600

probe() {
  timeout -k 5 90 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" \
    >/dev/null 2>&1
}

# run_job <stamp-name> <timeout-s> <cmd...>: one-shot; stamps on rc=0.
# On failure, re-probe: tunnel still UP means the failure is REAL (not a
# flap). One real failure earns ONE retry next window (.fail1 marker —
# ADVICE r3: multi-row jobs like bench_matrix die to transient per-row
# contention that a retry clears); a second real failure stamps .permfail
# so the queue can't loop on one deterministically-failing job.
run_job() {
  local stamp="$1" tmo="$2"; shift 2
  [ -e "$STAMPS/$stamp" ] && return 0
  [ -e "$STAMPS/$stamp.permfail" ] && return 0
  # The one retry a real failure earns must wait for a LATER window (the
  # motivating failures are per-window transients like 1-core contention;
  # an immediate same-window retry would hit the same condition and
  # permfail). .fail1 records the failing window; defer while it matches.
  if [ -e "$STAMPS/$stamp.fail1" ] \
     && [ "$(cat "$STAMPS/$stamp.fail1")" = "$WINDOW" ]; then
    echo "=== [$stamp] deferred to next window after real failure"
    return 0
  fi
  echo "=== $(date -u +%FT%TZ) [$stamp] $*"
  timeout -k 10 "$tmo" "$@"
  local rc=$?
  echo "=== rc=$rc [$stamp]"
  if [ "$rc" -eq 0 ]; then
    touch "$STAMPS/$stamp"
    rm -f "$STAMPS/$stamp.fail1"  # stale defer marker must not outlive success
  elif [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    # timeout-killed: the axon plugin HANGS (not errors) when the tunnel
    # dies under a job, so a kill is flap-shaped even if the tunnel is
    # back up by now — always retryable.
    return 1
  elif probe; then
    if [ -e "$STAMPS/$stamp.fail1" ]; then
      echo "=== [$stamp] second real failure: permanent, not retrying"
      touch "$STAMPS/$stamp.permfail"
      return 0  # settled: explicit, not touch's incidental rc
    else
      echo "=== [$stamp] failed with tunnel UP: will retry next window"
      echo "$WINDOW" > "$STAMPS/$stamp.fail1"
      return 1
    fi
  else
    return 1
  fi
}

# A job counts as settled (for queue completion) once it succeeded OR
# permanently failed — else one permfail spins the watcher forever.
settled() { [ -e "$STAMPS/$1" ] || [ -e "$STAMPS/$1.permfail" ]; }

commit_ledger() {
  # Stage the run dirs' CURVES and SIDECARS too: the r4 headline's run dir
  # was never committed because only the ledger file was added (VERDICT r4
  # Weak #1) — the learning curves ARE the auditable evidence. Orbax
  # checkpoint step dirs are deliberately NOT staged here: committing
  # rotating multi-MB binaries every window would balloon history; the
  # final checkpoints land once in the driver's end-of-round commit.
  if [ -n "$(git status --porcelain BENCH_HISTORY.json runs/)" ]; then
    # One guarded add per pathspec: git add is all-or-nothing across its
    # pathspecs — a single zero-match glob (e.g. runs/ pruned) would
    # abort the WHOLE add with nothing staged, silently dropping the
    # ledger commit this function exists to make.
    for spec in BENCH_HISTORY.json runs/README.md \
        'runs/*/metrics.jsonl' 'runs/*/*.json'; do
      git add -- $spec 2>/dev/null
    done
    git -c core.editor=true commit -q -m "Record real-TPU benchmark evidence in BENCH_HISTORY

Automated ledger update from scripts/tpu_window.sh on a live
accelerator window; see the entries' device_kind/ts fields.

No-Verification-Needed: benchmark-artifact-only commit" \
      -- BENCH_HISTORY.json runs/ 2>/dev/null \
      && echo "=== ledger committed"
  fi
}

# target_reached <cap> <presets...>: a non-CPU reached=true
# time_to_target row exists for that episode cap AND one of the named
# presets (rows without pong_max_steps predate the field and belong to
# the 3000 bar). The preset filter keeps the three bars separate: the
# pixel arm and the vector ALE arm share cap 27000 but are different
# measurements — one reaching must not stop the other.
target_reached() {
  CAP="$1" PRESETS="${2:?target_reached needs a preset list}" python - <<'EOF'
import json, os, sys
cap = int(os.environ["CAP"])
presets = set(os.environ["PRESETS"].split())
try:
    entries = json.load(open("BENCH_HISTORY.json"))
except Exception:
    sys.exit(1)
ok = any(
    e.get("kind") == "time_to_target" and e.get("reached")
    and e.get("platform") not in ("cpu",)
    and int(e.get("pong_max_steps", 3000)) == cap
    and e.get("preset") in presets
    for e in entries
)
sys.exit(0 if ok else 1)
EOF
}

# budget_spent <budget-s> <dir>...: every listed arm's accumulated clock
# passed the given budget. An arm seeded by copying another arm's
# checkpoints inherits the donor's elapsed sidecar (the t2t TOTAL must
# stay honest); its own budget, though, starts at the copy —
# seed_offset.json records the inherited seconds and is subtracted here.
budget_spent() {
  local budget="$1"; shift
  DIRS="$*" BUDGET="$budget" python - <<'EOF'
import json, os, sys
def read(d, name):
    try:
        return json.load(open(f"{d}/{name}")).get("seconds", 0)
    except Exception:
        return 0
done = all(
    read(d, "run_to_target_elapsed.json") - read(d, "seed_offset.json")
    >= float(os.environ["BUDGET"])
    for d in os.environ["DIRS"].split()
)
sys.exit(0 if done else 1)
EOF
}

# t2t_session <preset> <arm_dir> [budget] [session-timeout]: one
# resumable training session (default 900s). A seeded arm passes BUDGET +
# its inherited seed offset — run_to_target's own budget check counts the
# inherited sidecar seconds, so the raw BUDGET would stop it before the
# arm got BUDGET seconds of its OWN training (and budget_spent, which
# subtracts the offset, would then never be satisfied). The pixel arm
# passes a longer session timeout: its remat+grad_accum compile eats a
# bigger fixed slice of each session.
t2t_session() {
  local preset="$1" arm="$2" budget="${3:-$BUDGET}" tmo="${4:-900}"
  echo "=== $(date -u +%FT%TZ) [t2t] run_to_target session ($preset -> $arm)"
  timeout -k 10 "$tmo" python scripts/run_to_target.py "$preset" \
    --target 18.0 --budget-seconds "$budget" \
    checkpoint_dir="$arm" checkpoint_every=50
  echo "=== rc=$? [t2t $arm]"
  commit_ledger
}

# seed_offset <dir>: the arm's inherited-seconds offset (0 if none).
seed_offset() {
  python -c "
import json
try:
    print(int(json.load(open('$1/seed_offset.json')).get('seconds', 0)))
except Exception:
    print(0)
" 2>/dev/null || echo 0
}

WINDOW=0
PREV_UP=0
while true; do
  if ! probe; then
    echo "--- $(date -u +%FT%TZ) tunnel DOWN; sleeping 60s"
    PREV_UP=0
    sleep 60
    continue
  fi
  # Stamp key = UTC hour: unique across watcher restarts (a counter
  # would reset and skip the per-window bench), and it ROLLS during a
  # long stable window — so an hours-long window still gets an hourly
  # fresh flagship pair, and a .fail1-deferred job's retry unblocks at
  # the hour instead of waiting for a tunnel flap.
  WINDOW="$(date -u +%Y%m%dT%H)"
  if [ "$PREV_UP" -eq 0 ]; then
    echo "--- $(date -u +%FT%TZ) tunnel UP; window $WINDOW"
  fi
  PREV_UP=1

  # Re-arm settled stamps from the committed ledger: /tmp stamps die on
  # reboot/restart, but a reached=true row is durable — without this the
  # completion check could never pass after a restart.
  target_reached 27000 pong_t2t_ale && touch "$STAMPS/t2t_ale"
  target_reached 3000 "pong_t2t pong_t2t_1024" && touch "$STAMPS/t2t"
  target_reached 27000 pong_pixels_t2t && touch "$STAMPS/t2t_pix"

  # --- 1. ALE-faithful t2t (the round-5 headline; VERDICT r4 Next #1).
  # Seed the arm from the accumulated strict-cap checkpoint so its 28.8
  # training minutes carry into the measurement honestly (sidecar copies
  # along; seed_offset.json keeps the ALE arm's own BUDGET clock at zero).
  if ! target_reached 27000 pong_t2t_ale \
     && [ ! -e "$STAMPS/t2t_ale.permfail" ]; then
    if [ ! -d runs/pong18_ale ] && [ -d runs/pong18_tpu ]; then
      cp -r runs/pong18_tpu runs/pong18_ale
      python - <<'EOF'
import json
path = "runs/pong18_ale/run_to_target_elapsed.json"
try:
    elapsed = json.load(open(path))
except Exception:
    elapsed = {}
secs = elapsed.get("seconds", 0)
# The donor may have FINISHED its own measurement (reached=true sidecar);
# that marker must not make the seeded arm refuse every session (rc=3 in
# run_to_target) — this arm's measurement is its own.
if elapsed.pop("reached", None) is not None:
    json.dump(elapsed, open(path, "w"))
json.dump({"seconds": secs}, open("runs/pong18_ale/seed_offset.json", "w"))
EOF
      echo "=== seeded runs/pong18_ale from runs/pong18_tpu"
    fi
    t2t_session pong_t2t_ale runs/pong18_ale \
      $((BUDGET + $(seed_offset runs/pong18_ale)))
    target_reached 27000 pong_t2t_ale && touch "$STAMPS/t2t_ale"
    budget_spent "$BUDGET" runs/pong18_ale \
      && touch "$STAMPS/t2t_ale.permfail"
  fi

  # --- 1c. Coarse-to-fine curriculum arm: the from-scratch <10-minute
  # attack, CPU-validated end to end (runs/pong18_skip4_cpu crossed the
  # ALE bar at 0.74B decisions ~ 2.9B core frames via skip-4 training +
  # skip-1 finish — 6x fewer core frames than the pure skip-1 arm's
  # 18B). Phase 1: ONE 180s skip-4 burst (pong_t2t_ale4 — the preset is
  # retired as a BAR, reused as a CURRICULUM phase; at chip fps that is
  # several billion coarse decisions). Phase 2: skip-1 finish under the
  # parity preset, same checkpoint dir; the sidecar carries total wall
  # clock across phases, so the final reached row reports the honest
  # from-scratch time. Gated on the arm's own completion (not
  # target_reached: the seeded 1a arm closing the shared bar must not
  # stop this arm's own from-scratch measurement).
  curr_reached() {
    grep -q '"reached": true' \
      runs/pong18_curr/run_to_target_elapsed.json 2>/dev/null
  }
  # Phase 1 is complete when the arm has BANKED >=150s of accumulated
  # wall clock (the sidecar is written on every metrics drain, so it
  # exists only after real training ran) — never on mere dir existence:
  # run_to_target creates the dir at construction, so a compile-eaten or
  # flap-killed first burst would otherwise permanently skip the coarse
  # phase and silently degrade the arm to pure skip-1 (review finding).
  # Sessions repeat the skip-4 burst until the floor is met; phase-2
  # seconds keep the check true forever after.
  curr_phase1_done() {
    python -c "
import json, sys
try:
    ok = json.load(open('runs/pong18_curr/run_to_target_elapsed.json'))\
        .get('seconds', 0) >= 150
except Exception:
    ok = False
sys.exit(0 if ok else 1)" 2>/dev/null
  }
  if ! curr_reached && [ ! -e "$STAMPS/t2t_curr.permfail" ]; then
    if ! curr_phase1_done; then
      t2t_session pong_t2t_ale4 runs/pong18_curr "$CURR_BUDGET" 180
    fi
    if curr_phase1_done; then
      t2t_session pong_t2t_ale runs/pong18_curr "$CURR_BUDGET"
    fi
    budget_spent "$CURR_BUDGET" runs/pong18_curr \
      && touch "$STAMPS/t2t_curr.permfail"
  fi

  # --- 2. Fresh dual-flagship bench, once per window.
  run_job "bench_w$WINDOW" 900 python bench.py || continue
  commit_ledger

  # --- 3. Pixel-path 18.0 hunt (VERDICT r4 Next #2): the reference
  # flagship's real shape. Fresh arm (no seeding — no prior pixel
  # training exists); longer sessions because the remat+grad_accum pixel
  # compile is the fixed per-session cost. Every session appends to the
  # committed learning curve and banks a reached=false row on budget/
  # session end — the multi-window expectation is in the preset comment.
  if ! target_reached 27000 pong_pixels_t2t \
     && [ ! -e "$STAMPS/t2t_pix.permfail" ]; then
    t2t_session pong_pixels_t2t runs/pong18_pixels "$PIXEL_BUDGET" 1500
    target_reached 27000 pong_pixels_t2t && touch "$STAMPS/t2t_pix"
    budget_spent "$PIXEL_BUDGET" runs/pong18_pixels \
      && touch "$STAMPS/t2t_pix.permfail"
  fi

  # --- 4. Promoted probes (VERDICT r4 Next #4/#6): the MFU question and
  # the host-path RTT model need chip rows this round.
  if [ -e scripts/mfu_probe.py ]; then
    # 5 variants x (compile + measure) incl. the wide-torso lane-
    # utilization experiment — the pixel compiles are the cost.
    run_job mfu_probe 1800 python scripts/mfu_probe.py || continue
    commit_ledger
  fi
  if [ -e scripts/host_rtt_sweep.py ]; then
    run_job host_rtt_sweep 600 python scripts/host_rtt_sweep.py || continue
    commit_ledger
  fi

  # --- 5. Strict-cap t2t (the harder scoring-rate bar). The fresh arm
  # trains the batch-scaled recipe (pong_t2t_1024: 4x frames per
  # wall-second + shaping from step one); the resumed arm keeps its
  # checkpoint's pong_t2t geometry.
  if ! target_reached 3000 "pong_t2t pong_t2t_1024" \
     && [ ! -e "$STAMPS/t2t.permfail" ]; then
    if [ -e "$STAMPS/t2t_arm_toggle" ]; then
      ARM_DIR=runs/pong18_fresh1024; ARM_PRESET=pong_t2t_1024
      rm -f "$STAMPS/t2t_arm_toggle"
    else
      ARM_DIR=runs/pong18_tpu; ARM_PRESET=pong_t2t
      touch "$STAMPS/t2t_arm_toggle"
    fi
    t2t_session "$ARM_PRESET" "$ARM_DIR"
    target_reached 3000 "pong_t2t pong_t2t_1024" && touch "$STAMPS/t2t"
    budget_spent "$BUDGET" runs/pong18_tpu runs/pong18_fresh1024 \
      && touch "$STAMPS/t2t.permfail"
  fi

  # --- 6. Remaining one-shot evidence rows.
  # Both-cap eval of the best checkpoint ON THE CHIP (the CPU rows exist;
  # this one carries TPU provenance for the cap-decision evidence).
  run_job eval_caps_tpu 900 python scripts/eval_caps.py pong_t2t \
    --run-dir runs/pong18_tpu --episodes 64 || continue
  commit_ledger
  run_job pixel_bench 420 python bench.py atari_impala updates_per_call=8 num_envs=256 || continue
  run_job roofline_pong 420 python scripts/roofline.py pong_impala updates_per_call=32 || continue
  run_job roofline_atari 480 python scripts/roofline.py atari_impala updates_per_call=8 num_envs=256 || continue
  run_job pallas_validate 420 python scripts/validate_pallas_tpu.py scan || continue
  # Device hot path (this round's kernels): fused V-trace tail + RDMA
  # ring bit-identity gates on real silicon, then the fused on/off
  # throughput A/B on the flagship geometry. Separate stamps from the
  # scan gate so a ring-fabric failure retries without re-proving the
  # settled reverse-scan result.
  run_job kernels_fused_ring 600 python scripts/validate_pallas_tpu.py fused ring || continue
  run_job fused_ab 1200 python bench.py fused_ab || continue
  commit_ledger
  # The reference's FULL 1024-envs/chip pixel geometry (BASELINE.json:9).
  run_job pixel_bench_1024 480 python bench.py atari_impala updates_per_call=8 grad_accum=4 remat=true || continue
  # Vector-flagship env scaling: the 27.3M headline keeps the parity
  # 256-env geometry; with mfu=0.0011 there, the chip has ~100x compute
  # headroom — wider batches amortize the same per-call overhead over
  # more frames. Via roofline.py (kind=roofline rows, with MFU): a
  # kind=throughput row under the same preset would become the
  # flagship's last_known_good despite the non-parity geometry.
  run_job vec_envs1024 420 python scripts/roofline.py pong_impala updates_per_call=512 num_envs=1024 || continue
  run_job vec_envs4096 420 python scripts/roofline.py pong_impala updates_per_call=512 num_envs=4096 || continue
  # Wide-torso pixel preset: the committed fitted geometry end to end.
  run_job pixel_wide 600 python bench.py atari_impala_wide updates_per_call=8 || continue
  commit_ledger

  # --- 7. Long, lower-marginal-value jobs last.
  run_job bench_matrix 1500 python scripts/bench_matrix.py || continue
  commit_ledger
  run_job selfplay_exp 900 python scripts/selfplay_experiment.py 400000000 updates_per_call=32 step_cost=0.005 || continue
  commit_ledger

  if settled t2t_ale && settled t2t && settled t2t_pix \
     && { curr_reached || [ -e "$STAMPS/t2t_curr.permfail" ]; } \
     && settled "bench_w$WINDOW" \
     && settled eval_caps_tpu && settled pixel_bench \
     && settled roofline_pong && settled roofline_atari \
     && settled pallas_validate && settled kernels_fused_ring \
     && settled fused_ab && settled pixel_bench_1024 \
     && settled vec_envs1024 && settled vec_envs4096 \
     && settled pixel_wide \
     && settled bench_matrix && settled selfplay_exp \
     && { [ ! -e scripts/mfu_probe.py ] || settled mfu_probe; } \
     && { [ ! -e scripts/host_rtt_sweep.py ] || settled host_rtt_sweep; }; then
    echo "--- $(date -u +%FT%TZ) queue complete"
    break
  fi
  # A .fail1-deferred job leaves the settled check false while every
  # remaining job this window returns instantly — without a pause that is
  # a probe-spawning busy-loop on the 1-core box for the rest of the
  # window, starving the very jobs the defer was protecting.
  sleep 30
done
