#!/bin/bash
# TPU window watcher (VERDICT round 2, Next #2): the axon tunnel flaps —
# minutes-long UP windows between outages. This loop probes liveness and,
# on each UP window, burns down a prioritized queue of real-TPU evidence
# jobs. One-shot jobs stamp a .done file on success and never re-run; the
# time-to-target training job is resumable (checkpointed + elapsed sidecar)
# and re-fires every window until its ledger entry says reached.
#
#   nohup bash scripts/tpu_window.sh > /tmp/tpu_window.log 2>&1 &
#
# Every job runs with BENCH_NO_WAIT=1 (the watcher already established
# liveness; a mid-job flap should fail fast and surrender the window) and
# under `timeout` with process-group kill (the axon plugin hangs, not
# errors, when the tunnel dies under it — see bench._accelerator_alive).
set -u
cd "$(dirname "$0")/.."
STAMPS=/tmp/tpu_window_stamps
mkdir -p "$STAMPS"
export BENCH_NO_WAIT=1
# A flap between our probe and a job's own probe must FAIL the job (retry
# next window), not silently bank a CPU row as real-chip evidence.
export BENCH_REQUIRE_ACCELERATOR=1

probe() {
  timeout -k 5 90 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" \
    >/dev/null 2>&1
}

# run_job <stamp-name> <timeout-s> <cmd...>: one-shot; stamps on rc=0.
# On failure, re-probe: tunnel still UP means the failure is REAL (not a
# flap) — stamp it .permfail and move on, or the queue would loop on one
# deterministically-failing job and starve everything behind it (observed:
# pallas_validate's genuine kernel mismatch blocked the t2t north star).
run_job() {
  local stamp="$1" tmo="$2"; shift 2
  [ -e "$STAMPS/$stamp" ] && return 0
  [ -e "$STAMPS/$stamp.permfail" ] && return 0
  echo "=== $(date -u +%FT%TZ) [$stamp] $*"
  timeout -k 10 "$tmo" "$@"
  local rc=$?
  echo "=== rc=$rc [$stamp]"
  if [ "$rc" -eq 0 ]; then
    touch "$STAMPS/$stamp"
  elif [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    # timeout-killed: the axon plugin HANGS (not errors) when the tunnel
    # dies under a job, so a kill is flap-shaped even if the tunnel is
    # back up by now — always retryable.
    return 1
  elif probe; then
    echo "=== [$stamp] failed with tunnel UP: permanent, not retrying"
    touch "$STAMPS/$stamp.permfail"
  else
    return 1
  fi
}

# A job counts as settled (for queue completion) once it succeeded OR
# permanently failed — else one permfail spins the watcher forever.
settled() { [ -e "$STAMPS/$1" ] || [ -e "$STAMPS/$1.permfail" ]; }

commit_ledger() {
  if [ -n "$(git status --porcelain BENCH_HISTORY.json)" ]; then
    git add BENCH_HISTORY.json
    git -c core.editor=true commit -q -m "Record real-TPU benchmark evidence in BENCH_HISTORY

Automated ledger update from scripts/tpu_window.sh on a live
accelerator window; see the entries' device_kind/ts fields.

No-Verification-Needed: benchmark-artifact-only commit" \
      -- BENCH_HISTORY.json runs/ 2>/dev/null \
      && echo "=== ledger committed"
  fi
}

target_reached() {
  python - <<'EOF'
import json, sys
try:
    entries = json.load(open("BENCH_HISTORY.json"))
except Exception:
    sys.exit(1)
ok = any(
    e.get("kind") == "time_to_target" and e.get("reached")
    and e.get("platform") not in ("cpu",)
    for e in entries
)
sys.exit(0 if ok else 1)
EOF
}

while true; do
  if ! probe; then
    echo "--- $(date -u +%FT%TZ) tunnel DOWN; sleeping 60s"
    sleep 60
    continue
  fi
  echo "--- $(date -u +%FT%TZ) tunnel UP; draining queue"

  # Short one-shot evidence rows first: a window that dies early still
  # banked something. Order = (value x brevity) descending.
  run_job pixel_bench 420 python bench.py atari_impala updates_per_call=8 num_envs=256 || continue
  commit_ledger
  run_job roofline_pong 420 python scripts/roofline.py pong_impala updates_per_call=32 || continue
  run_job roofline_atari 480 python scripts/roofline.py atari_impala updates_per_call=8 num_envs=256 || continue
  # Pallas kernel gate: first-ever real-chip run of the VMEM reverse-scan
  # (scan_impl note in utils/config.py — promotion blocked on this).
  run_job pallas_validate 420 python scripts/validate_pallas_tpu.py || continue
  # Dispatch-amortization sweep: is 32 fused updates/call still the sweet
  # spot, or does deeper fusion raise the headline? (Ledger rows carry the
  # K in their label; compare offline, then retune bench.py's default.)
  run_job upc64 300 python bench.py pong_impala updates_per_call=64 || continue
  run_job upc128 300 python bench.py pong_impala updates_per_call=128 || continue
  # K=128 measured 24.2M fps (vs 14.8M at K=32); probe whether the curve
  # keeps rising before the headline settles on K=128's plateau.
  run_job upc256 300 python bench.py pong_impala updates_per_call=256 || continue
  run_job upc512 300 python bench.py pong_impala updates_per_call=512 || continue
  # The reference's FULL 1024-envs/chip pixel geometry (BASELINE.json:9):
  # OOMs at 21.3G without microbatching; grad_accum=4 + block remat fits
  # it into the v5e's 15.75G (the r3 grad_accum/remat feature).
  run_job pixel_bench_1024 480 python bench.py atari_impala updates_per_call=8 grad_accum=4 remat=true || continue
  commit_ledger

  # North star: wall-clock to 18.0 on the real chip (BASELINE.json:2).
  # Resumable across windows; stops re-firing once a non-CPU reached=true
  # entry lands. step_cost per scripts/pong_diagnose.py's offense finding.
  if ! target_reached && [ ! -e "$STAMPS/t2t.permfail" ]; then
    # Two arms, alternating one 900s session each; first to 18.0 wins.
    # (a) runs/pong18_tpu — the accumulated checkpoint, tune-and-continue:
    #     tests whether the conservative-long-rally basin (learned under
    #     weak speed pressure) can be escaped in place.
    # (b) runs/pong18_tpu_fresh — the full pong_t2t recipe from step ONE:
    #     shaping present during early policy formation, which a resumed
    #     arm can never retrofit.
    # Recipe = the committed pong_t2t preset in both cases.
    if [ -e "$STAMPS/t2t_arm_toggle" ]; then
      ARM_DIR=runs/pong18_tpu_fresh; rm -f "$STAMPS/t2t_arm_toggle"
    else
      ARM_DIR=runs/pong18_tpu; touch "$STAMPS/t2t_arm_toggle"
    fi
    echo "=== $(date -u +%FT%TZ) [t2t] run_to_target session (arm $ARM_DIR)"
    timeout -k 10 900 python scripts/run_to_target.py pong_t2t \
      --target 18.0 --budget-seconds 10800 \
      checkpoint_dir="$ARM_DIR" checkpoint_every=50
    echo "=== rc=$? [t2t]"
    commit_ledger
    target_reached && touch "$STAMPS/t2t"
    # Budget-exhausted settle: retire the job only when BOTH arms'
    # accumulated clocks pass the budget — else each further session
    # burns a bring-up+compile to immediately append ANOTHER
    # reached=false row.
    python - <<'EOF' && touch "$STAMPS/t2t.permfail"
import json, sys
def secs(d):
    try:
        return json.load(
            open(f"{d}/run_to_target_elapsed.json")
        ).get("seconds", 0)
    except Exception:
        return 0
done = all(
    secs(d) >= 10800
    for d in ("runs/pong18_tpu", "runs/pong18_tpu_fresh")
)
sys.exit(0 if done else 1)
EOF
  fi

  # Host-path rows last (long; lowest marginal value — CPU rows exist).
  # 1500s: the default matrix now includes the heavy atari_impala+fit
  # pixel row (grad_accum=4 micro-passes + remat recompute).
  run_job bench_matrix 1500 python scripts/bench_matrix.py || continue
  commit_ledger
  # Self-play payoff head-to-head (VERDICT r2 Next #5): matched-budget
  # direct-vs-ladder arms, scored on the tracker metric. 400M frames/arm
  # is minutes on the chip.
  run_job selfplay_exp 900 python scripts/selfplay_experiment.py 400000000 updates_per_call=32 step_cost=0.005 || continue
  commit_ledger

  if settled pixel_bench && settled roofline_pong \
     && settled roofline_atari && settled t2t \
     && settled pallas_validate && settled pixel_bench_1024 \
     && settled bench_matrix && settled selfplay_exp; then
    echo "--- $(date -u +%FT%TZ) queue complete"
    break
  fi
done
