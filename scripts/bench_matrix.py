"""Throughput matrix: one JSON line PER WORKLOAD (unlike bench.py, whose
contract is a single line for the driver). Usage:

    python scripts/bench_matrix.py [preset ...] [key=value ...]

Defaults to a representative slice of every workload family: vector/pixel
Atari stand-ins, procedural gridworlds, on-TPU physics locomotion, and the
CartPole smoke. Each preset runs the same measurement discipline as
bench.py — D2H-read sync boundaries (axon's block_until_ready returns
early), a time-targeted >=2s window, and the device-side update-counter
execution guard — at the preset's own geometry.
"""

from __future__ import annotations

import json
import os
import sys

# Shared measurement harness (liveness probe, sync discipline, execution
# guard) lives in bench.py at the repo root — ONE copy for both entry points.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import (  # noqa: E402
    _accelerator_alive_with_retry,
    timed_update_window,
)

DEFAULT_PRESETS = [
    "cartpole_impala",
    "cartpole_qlearn",
    "pong_impala",
    "atari_impala",
    "procgen_ppo",
    "halfcheetah_ppo",
    "brax_ant_ppo",
]


def bench_one(preset_name: str, overrides: list[str]) -> dict:
    import jax

    from asyncrl_tpu.api.trainer import Trainer
    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils.config import override

    cfg = override(presets.get(preset_name), overrides)
    trainer = Trainer(cfg)
    state = trainer.state
    params0 = jax.tree.map(lambda x: x.copy(), state.params)

    state, timed, elapsed = timed_update_window(
        trainer.learner.update, state, cfg.updates_per_call
    )

    import numpy as np

    delta = sum(
        float(jax.numpy.sum(jax.numpy.abs(a - b)))
        for a, b in zip(
            jax.tree.leaves(state.params), jax.tree.leaves(params0)
        )
    )
    # Same refusal policy as bench.py: don't emit an fps figure training
    # didn't earn (frozen params = dropped/ineffective executions).
    if not (np.isfinite(delta) and delta > 0.0):
        raise RuntimeError(f"param delta {delta}: training did not move")
    fps = timed * cfg.updates_per_call * cfg.num_envs * cfg.unroll_len / elapsed

    from asyncrl_tpu.utils import bench_history

    dev = bench_history.device_entry()
    bench_history.record_throughput(preset_name, cfg, fps)
    return {
        "preset": preset_name,
        "env_id": cfg.env_id,
        "num_envs": cfg.num_envs,
        "unroll_len": cfg.unroll_len,
        "frames_per_sec": round(fps),
        "device": f"{dev['device_kind']} x{dev['device_count']}",
    }


def main() -> int:
    import jax

    if not _accelerator_alive_with_retry():
        # Same guard as bench.py: a hung axon tunnel would otherwise block
        # the first device query forever.
        jax.config.update("jax_platforms", "cpu")
        print(
            "bench_matrix: accelerator backend hung/unavailable; falling "
            "back to CPU (device field carries the kind)",
            file=sys.stderr,
        )
    args = sys.argv[1:]
    overrides = [a for a in args if "=" in a]
    names = [a for a in args if "=" not in a] or DEFAULT_PRESETS
    for name in names:
        try:
            print(json.dumps(bench_one(name, overrides)), flush=True)
        except Exception as e:
            print(
                json.dumps(
                    {"preset": name, "error": f"{type(e).__name__}: {e}"}
                ),
                flush=True,
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
