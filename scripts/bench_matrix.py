"""Throughput matrix: one JSON line PER WORKLOAD (unlike bench.py, whose
contract is a single line for the driver). Usage:

    python scripts/bench_matrix.py [preset ...] [key=value ...]

Defaults to a representative slice of every workload family: vector/pixel
Atari stand-ins, procedural gridworlds, on-TPU physics locomotion, and the
CartPole smoke. Each preset runs the same warmup+timed pipelined loop as
bench.py (including its execution-integrity guard logic) at the preset's
own geometry.
"""

from __future__ import annotations

import json
import sys
import time

DEFAULT_PRESETS = [
    "cartpole_impala",
    "pong_impala",
    "atari_impala",
    "procgen_ppo",
    "halfcheetah_ppo",
    "brax_ant_ppo",
]


def bench_one(preset_name: str, overrides: list[str]) -> dict:
    import jax

    from asyncrl_tpu.api.trainer import Trainer
    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils.config import override

    cfg = override(presets.get(preset_name), overrides)
    trainer = Trainer(cfg)
    state = trainer.state
    params0 = jax.tree.map(lambda x: x.copy(), state.params)

    warmup, timed = 3, 20
    for _ in range(warmup):
        state, metrics = trainer.learner.update(state)
    jax.block_until_ready(metrics)
    t0 = time.perf_counter()
    for _ in range(timed):
        state, metrics = trainer.learner.update(state)
    jax.block_until_ready(state)
    elapsed = time.perf_counter() - t0

    import numpy as np

    delta = sum(
        float(jax.numpy.sum(jax.numpy.abs(a - b)))
        for a, b in zip(
            jax.tree.leaves(state.params), jax.tree.leaves(params0)
        )
    )
    fps = timed * cfg.updates_per_call * cfg.num_envs * cfg.unroll_len / elapsed
    return {
        "preset": preset_name,
        "env_id": cfg.env_id,
        "num_envs": cfg.num_envs,
        "unroll_len": cfg.unroll_len,
        "frames_per_sec": round(fps),
        "device": f"{jax.devices()[0].device_kind} x{jax.device_count()}",
        "integrity_ok": bool(np.isfinite(delta) and delta > 0.0),
    }


def main() -> int:
    args = sys.argv[1:]
    overrides = [a for a in args if "=" in a]
    names = [a for a in args if "=" not in a] or DEFAULT_PRESETS
    for name in names:
        try:
            print(json.dumps(bench_one(name, overrides)), flush=True)
        except Exception as e:
            print(
                json.dumps(
                    {"preset": name, "error": f"{type(e).__name__}: {e}"}
                ),
                flush=True,
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
