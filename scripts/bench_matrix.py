"""Throughput matrix: one JSON line PER WORKLOAD (unlike bench.py, whose
contract is a single line for the driver). Usage:

    python scripts/bench_matrix.py [preset ...] [key=value ...]

Defaults to a representative slice of every workload family: vector/pixel
Atari stand-ins, procedural gridworlds, on-TPU physics locomotion, and the
CartPole smoke. Each preset runs the same measurement discipline as
bench.py — D2H-read sync boundaries (axon's block_until_ready returns
early), a time-targeted >=2s window, and the device-side update-counter
execution guard — at the preset's own geometry.
"""

from __future__ import annotations

import json
import os
import sys

# Shared measurement harness (liveness probe, sync discipline, execution
# guard) lives in bench.py at the repo root — ONE copy for both entry points.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import (  # noqa: E402
    cpu_fallback_or_refuse,
    timed_update_window,
)

DEFAULT_PRESETS = [
    "cartpole_impala",
    "cartpole_qlearn",
    "pong_impala",
    # The full 1024-env pixel geometry needs the r3 memory fit: the naive
    # backward's conv activations want 21.3G on a 15.75G v5e (measured
    # OOM 2026-07-31); env-chunked grad accumulation + block remat fit it.
    "atari_impala+fit",
    "procgen_ppo",
    "halfcheetah_ppo",
    "brax_ant_ppo",
    # Population row (api/population.py): K fused seeds advancing in one
    # program, with fused multi-update calls (VERDICT r2 Next #4's ledger
    # evidence). fps counts frames across ALL members.
    "pong_impala+pop4",
    # Host-actor (Sebulba/cpu_async) rows: measured over the live pipeline
    # (actor threads + device learner), not a bare update loop. The
    # inference_server variant quantifies the batched-dispatch win.
    "pendulum_native_ppo",
    "pendulum_native_ppo+server",
    "mujoco_ant_ppo",
    "cartpole_a3c_cpu",
]

# Named variants: "<preset>+server" etc. map to extra overrides;
# "<preset>+popN" runs an N-member population of the preset.
VARIANTS = {
    "+server": ["inference_server=true"],
    # Memory fit for the full-geometry pixel preset (see DEFAULT_PRESETS).
    "+fit": ["grad_accum=4", "remat=true"],
}


def split_variant(name: str) -> tuple[str, list[str], int | None]:
    import re

    m = re.search(r"\+pop(\d+)$", name)
    if m:
        # Fused dispatch is the population's amortization story on a
        # high-latency link (VERDICT r2 Next #4): default the row to K=8,
        # overridable by explicit updates_per_call= args (applied after).
        return name[: m.start()], ["updates_per_call=8"], int(m.group(1))
    for suffix, extra in VARIANTS.items():
        if name.endswith(suffix):
            return name[: -len(suffix)], list(extra), None
    return name, [], None


def bench_host(preset_name: str, cfg, min_seconds: float = 8.0) -> dict:
    """Pipeline throughput for host-backend presets: train() for a wall
    window and average the steady-state metric-window fps (first window
    dropped — it pays the jit compiles). This measures what a user gets —
    actor threads, queue, learner dispatch overlapped — not a bare device
    loop."""
    import time

    from asyncrl_tpu.api.factory import make_agent

    agent = make_agent(cfg)
    windows: list[float] = []
    t0 = time.perf_counter()

    class _Done(Exception):
        pass

    def cb(m):
        windows.append(m["fps"])
        if time.perf_counter() - t0 > min_seconds and len(windows) >= 5:
            raise _Done

    try:
        agent.train(total_env_steps=1 << 40, callback=cb)
    except _Done:
        pass
    finally:
        agent.close()
    if len(windows) < 2:
        raise RuntimeError(f"only {len(windows)} metric windows in window")
    fps = sum(windows[1:]) / len(windows[1:])

    from asyncrl_tpu.utils import bench_history

    dev = bench_history.device_entry()
    bench_history.record_throughput(preset_name, cfg, fps)
    return {
        "preset": preset_name,
        "env_id": cfg.env_id,
        "backend": cfg.backend,
        "host_pool": cfg.host_pool,
        "inference_server": cfg.inference_server,
        "actor_threads": cfg.actor_threads,
        "num_envs": cfg.num_envs,
        "unroll_len": cfg.unroll_len,
        "updates_per_call": cfg.updates_per_call,
        "frames_per_sec": round(fps),
        "device": f"{dev['device_kind']} x{dev['device_count']}",
    }


def bench_population(preset_name: str, cfg, pop_size: int) -> dict:
    """Population throughput: frames/sec across ALL members of a K-fused
    population advancing in one program (same sync/guard discipline)."""
    import jax

    from asyncrl_tpu.api.population import PopulationTrainer

    pop = PopulationTrainer(cfg, pop_size)
    params0 = jax.tree.map(lambda x: x.copy(), pop.state.params)
    state, timed, elapsed = timed_update_window(
        lambda s: pop._step(s, pop.member_seeds),
        pop.state,
        cfg.updates_per_call,
    )
    pop.state = state

    import numpy as np

    delta = sum(
        float(jax.numpy.sum(jax.numpy.abs(a - b)))
        for a, b in zip(
            jax.tree.leaves(state.params), jax.tree.leaves(params0)
        )
    )
    if not (np.isfinite(delta) and delta > 0.0):
        raise RuntimeError(f"param delta {delta}: training did not move")
    fps = (
        timed
        * cfg.updates_per_call
        * pop_size
        * cfg.num_envs
        * cfg.unroll_len
        / elapsed
    )

    from asyncrl_tpu.utils import bench_history

    dev = bench_history.device_entry()
    bench_history.record_throughput(preset_name, cfg, fps)
    pop.close()
    return {
        "preset": preset_name,
        "env_id": cfg.env_id,
        "pop_size": pop_size,
        "num_envs": cfg.num_envs,
        "unroll_len": cfg.unroll_len,
        "updates_per_call": cfg.updates_per_call,
        "frames_per_sec": round(fps),
        "device": f"{dev['device_kind']} x{dev['device_count']}",
    }


def bench_one(preset_name: str, overrides: list[str]) -> dict:
    import jax

    from asyncrl_tpu.api.trainer import Trainer
    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils.config import override

    base_name, extra, pop_size = split_variant(preset_name)
    cfg = override(presets.get(base_name), extra + overrides)
    if pop_size is not None:
        return bench_population(preset_name, cfg, pop_size)
    if cfg.backend in ("sebulba", "cpu_async"):
        return bench_host(preset_name, cfg)
    trainer = Trainer(cfg)
    state = trainer.state
    params0 = jax.tree.map(lambda x: x.copy(), state.params)

    state, timed, elapsed = timed_update_window(
        trainer.learner.update, state, cfg.updates_per_call
    )

    import numpy as np

    delta = sum(
        float(jax.numpy.sum(jax.numpy.abs(a - b)))
        for a, b in zip(
            jax.tree.leaves(state.params), jax.tree.leaves(params0)
        )
    )
    # Same refusal policy as bench.py: don't emit an fps figure training
    # didn't earn (frozen params = dropped/ineffective executions).
    if not (np.isfinite(delta) and delta > 0.0):
        raise RuntimeError(f"param delta {delta}: training did not move")
    fps = timed * cfg.updates_per_call * cfg.num_envs * cfg.unroll_len / elapsed

    from asyncrl_tpu.utils import bench_history

    dev = bench_history.device_entry()
    bench_history.record_throughput(preset_name, cfg, fps)
    return {
        "preset": preset_name,
        "env_id": cfg.env_id,
        "num_envs": cfg.num_envs,
        "unroll_len": cfg.unroll_len,
        "frames_per_sec": round(fps),
        "device": f"{dev['device_kind']} x{dev['device_count']}",
    }


def main() -> int:
    import jax

    cpu_fallback_or_refuse(jax, "bench_matrix")
    args = sys.argv[1:]
    overrides = [a for a in args if "=" in a]
    names = [a for a in args if "=" not in a] or DEFAULT_PRESETS
    failed = 0
    for name in names:
        try:
            print(json.dumps(bench_one(name, overrides)), flush=True)
        except Exception as e:
            failed += 1
            print(
                json.dumps(
                    {"preset": name, "error": f"{type(e).__name__}: {e}"}
                ),
                flush=True,
            )
    # Nonzero on any failed row: a caller stamping this run as complete
    # (tpu_window.sh) must not record success for rows that never landed
    # in the ledger.
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
