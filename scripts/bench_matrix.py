"""Throughput matrix: one JSON line PER WORKLOAD (unlike bench.py, whose
contract is a single line for the driver). Usage:

    python scripts/bench_matrix.py [preset ...] [key=value ...]

Defaults to a representative slice of every workload family: vector/pixel
Atari stand-ins, procedural gridworlds, on-TPU physics locomotion, and the
CartPole smoke. Each preset runs the same measurement discipline as
bench.py — D2H-read sync boundaries (axon's block_until_ready returns
early), a time-targeted >=2s window, and the device-side update-counter
execution guard — at the preset's own geometry.
"""

from __future__ import annotations

import json
import sys
import time

DEFAULT_PRESETS = [
    "cartpole_impala",
    "pong_impala",
    "atari_impala",
    "procgen_ppo",
    "halfcheetah_ppo",
    "brax_ant_ppo",
]


def bench_one(preset_name: str, overrides: list[str]) -> dict:
    import jax

    from asyncrl_tpu.api.trainer import Trainer
    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils.config import override

    cfg = override(presets.get(preset_name), overrides)
    trainer = Trainer(cfg)
    state = trainer.state
    params0 = jax.tree.map(lambda x: x.copy(), state.params)

    # Timing boundaries are D2H reads, NOT jax.block_until_ready: the axon
    # plugin's block_until_ready returns before execution finishes (see
    # bench.py's sync discipline note, 2026-07-30), which inflated fps far
    # beyond the chip's FLOP peak.
    def sync(s) -> int:
        return int(s.update_step)

    warmup = 3
    for _ in range(warmup):
        state, metrics = trainer.learner.update(state)
    sync(state)

    # Time-targeted window, same rationale as bench.py: a fixed small call
    # count gives a dispatch-jitter-dominated device window on fast configs.
    min_seconds, min_calls = 2.0, 10
    timed = 0
    t0 = time.perf_counter()
    while True:
        state, metrics = trainer.learner.update(state)
        timed += 1
        if timed % min_calls == 0:
            executed = sync(state)
            if time.perf_counter() - t0 >= min_seconds:
                break
    elapsed = time.perf_counter() - t0
    dispatched = (warmup + timed) * cfg.updates_per_call
    if executed != dispatched:
        raise RuntimeError(
            f"device executed {executed} updates, dispatched {dispatched}: "
            "refusing to report a throughput number"
        )

    import numpy as np

    delta = sum(
        float(jax.numpy.sum(jax.numpy.abs(a - b)))
        for a, b in zip(
            jax.tree.leaves(state.params), jax.tree.leaves(params0)
        )
    )
    fps = timed * cfg.updates_per_call * cfg.num_envs * cfg.unroll_len / elapsed
    return {
        "preset": preset_name,
        "env_id": cfg.env_id,
        "num_envs": cfg.num_envs,
        "unroll_len": cfg.unroll_len,
        "frames_per_sec": round(fps),
        "device": f"{jax.devices()[0].device_kind} x{jax.device_count()}",
        # Counter mismatch raised above, so this reflects the param-delta
        # check only (training actually moved the weights).
        "integrity_ok": bool(np.isfinite(delta) and delta > 0.0),
    }


def main() -> int:
    args = sys.argv[1:]
    overrides = [a for a in args if "=" in a]
    names = [a for a in args if "=" not in a] or DEFAULT_PRESETS
    for name in names:
        try:
            print(json.dumps(bench_one(name, overrides)), flush=True)
        except Exception as e:
            print(
                json.dumps(
                    {"preset": name, "error": f"{type(e).__name__}: {e}"}
                ),
                flush=True,
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
