#!/usr/bin/env bash
# Trace smoke: run a short CPU sebulba pipeline with tracing ON, validate
# the exported Perfetto JSON against the schema (python -m asyncrl_tpu.obs
# validate), print the stall-attribution report, and A/B throughput
# against tracing OFF — failing if the traced run is more than
# ASYNCRL_TRACE_TOLERANCE (default 1.05 = 5%) slower.
#
# This is the operator-facing gate for the ISSUE 5 overhead budget: the
# span rings must be cheap enough to leave on. Same measurement
# discipline as perf_smoke.sh (the first training run in a process is
# systematically slow): discard a process warm-up run, then alternate
# off/on/off/on and take best-of-two per mode.
#
# Usage: scripts/trace_smoke.sh                    # CPU, ~1-2 min
#        ASYNCRL_SMOKE_UPDATES=64 scripts/trace_smoke.sh
#        ASYNCRL_TRACE_TOLERANCE=1.10 scripts/trace_smoke.sh  # noisy box
#        ASYNCRL_SMOKE_RECORD=1 scripts/trace_smoke.sh  # append the A/B as
#          a kind="observability" probe="trace_ab" row to BENCH_HISTORY.json
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
UPDATES="${ASYNCRL_SMOKE_UPDATES:-24}"
TOLERANCE="${ASYNCRL_TRACE_TOLERANCE:-1.05}"
RECORD="${ASYNCRL_SMOKE_RECORD:-0}"
RUN_DIR="$(mktemp -d /tmp/trace_smoke.XXXXXX)"
trap 'rm -rf "$RUN_DIR"' EXIT

python - "$UPDATES" "$TOLERANCE" "$RECORD" "$RUN_DIR" <<'EOF'
import glob
import sys
import time

from asyncrl_tpu import make_agent
from asyncrl_tpu.configs import presets

updates, tolerance = int(sys.argv[1]), float(sys.argv[2])
record = sys.argv[3] not in ("", "0")
run_dir = sys.argv[4]
NUM_ENVS, UNROLL = 16, 16
steps = updates * NUM_ENVS * UNROLL


def run(traced: bool):
    cfg = presets.get("pong_impala").replace(
        backend="sebulba", host_pool="jax", num_envs=NUM_ENVS,
        actor_threads=1, unroll_len=UNROLL, precision="f32", log_every=4,
        seed=3, hidden_sizes=(64, 64), actor_staleness=1_000_000,
        trace=traced, run_dir=run_dir,
    )
    agent = make_agent(cfg)
    try:
        agent.train(total_env_steps=NUM_ENVS * UNROLL)  # jit warm-up
        t0 = time.perf_counter()
        history = agent.train(total_env_steps=NUM_ENVS * UNROLL + steps)
        elapsed = time.perf_counter() - t0
    finally:
        agent.close()
    fps = steps / elapsed
    label = "trace=on " if traced else "trace=off"
    last = history[-1]
    print(
        f"trace_smoke {label}: fps={fps:12,.0f}  "
        f"spans={int(last.get('trace_spans', 0))}  "
        f"dropped={int(last.get('trace_dropped_spans', 0))}"
    )
    return fps


# Best-of-three per mode, alternating: the 1-core box's scheduler noise
# swings identical configs run to run (see perf_smoke.sh), and best-of-N
# alternation is the discipline that converges on the true ceiling.
run(True)  # discarded process warm-up
fps_off = max(run(False) for _ in range(1))
fps_on = max(run(True) for _ in range(1))
for _ in range(2):
    fps_off = max(fps_off, run(False))
    fps_on = max(fps_on, run(True))

traces = sorted(glob.glob(f"{run_dir}/trace-*.json"))
if not traces:
    sys.exit("trace_smoke FAILED: traced run exported no trace-*.json")
print(f"trace_smoke: {len(traces)} trace export(s); validating + reporting "
      f"on {traces[-1]}")

from asyncrl_tpu.obs.__main__ import main as obs_main

if obs_main(["validate", traces[-1]]) != 0:
    sys.exit("trace_smoke FAILED: exported trace violates the schema")
if obs_main(["report", traces[-1]]) != 0:
    sys.exit("trace_smoke FAILED: obs report errored on the export")

if fps_on * tolerance < fps_off:
    sys.exit(
        f"trace_smoke FAILED: tracing overhead above budget "
        f"({fps_on:,.0f} vs {fps_off:,.0f} fps, tolerance {tolerance}x)"
    )
print(
    f"trace_smoke OK: traced {fps_on:,.0f} fps vs untraced "
    f"{fps_off:,.0f} fps ({fps_on / fps_off:.3f}x, budget {tolerance}x)"
)

if record:
    from asyncrl_tpu.utils import bench_history

    entry = bench_history.record({
        "kind": "observability",
        "probe": "trace_ab",
        "preset": "pong_impala(sebulba tiny)",
        **bench_history.device_entry(),
        "num_envs": NUM_ENVS,
        "actor_threads": 1,
        "unroll_len": UNROLL,
        "updates": updates,
        "fps_traced": round(fps_on),
        "fps_untraced": round(fps_off),
        "trace_overhead": round(fps_off / fps_on, 3),
    })
    print("trace_smoke: recorded", entry["ts"])
EOF
