#!/usr/bin/env bash
# Introspect smoke: A/B the training-introspection layer (ISSUE 8;
# obs/introspect.py + the loss-aux diagnostics) on/off on a tiny
# pong_impala-shaped sebulba run:
#
#   1. IDENTITY — losses must be bit-identical on a fixed seed with
#      introspection on vs off (the diagnostics are aux-only device
#      reductions; they must never perturb the update).
#   2. FUNCTION — the ON run's windows must carry the introspection keys
#      (staleness percentiles, kl, explained_variance, compiles) and the
#      OFF run's must not (off = the pre-ISSUE-8 surface).
#   3. OVERHEAD — the ON run must not be more than
#      ASYNCRL_INTROSPECT_TOLERANCE (default 1.15, the perf_smoke noise
#      budget for this shared 1-core box — identical configs swing ±25%
#      run to run; tighten on quiet hardware) slower, best-of-N
#      alternating per the perf_smoke measurement discipline.
#
# Usage: scripts/introspect_smoke.sh                  # CPU, ~1-2 min
#        ASYNCRL_SMOKE_UPDATES=64 scripts/introspect_smoke.sh
#        ASYNCRL_INTROSPECT_TOLERANCE=1.10 scripts/introspect_smoke.sh
#        ASYNCRL_SMOKE_RECORD=1 scripts/introspect_smoke.sh  # append the
#          A/B as a kind="observability" probe="introspect_ab" row to
#          BENCH_HISTORY.json
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
UPDATES="${ASYNCRL_SMOKE_UPDATES:-24}"
TOLERANCE="${ASYNCRL_INTROSPECT_TOLERANCE:-1.15}"
RECORD="${ASYNCRL_SMOKE_RECORD:-0}"

python - "$UPDATES" "$TOLERANCE" "$RECORD" <<'EOF'
import sys
import time

import numpy as np

from asyncrl_tpu import make_agent
from asyncrl_tpu.configs import presets

updates, tolerance = int(sys.argv[1]), float(sys.argv[2])
record = sys.argv[3] not in ("", "0")
NUM_ENVS, UNROLL = 16, 16
steps = updates * NUM_ENVS * UNROLL

INTROSPECT_KEYS = (
    "staleness_p50", "staleness_p95", "staleness_max",
    "kl", "explained_variance", "compiles", "mem_host_rss_bytes",
)


def run(introspect: bool):
    cfg = presets.get("pong_impala").replace(
        backend="sebulba", host_pool="jax", num_envs=NUM_ENVS,
        actor_threads=1, unroll_len=UNROLL, precision="f32", log_every=4,
        seed=3, hidden_sizes=(64, 64),
        # Frozen behaviour params: losses must be seed-deterministic for
        # the identity assertion (no publish-timing race).
        actor_staleness=1_000_000,
        introspect=introspect,
    )
    agent = make_agent(cfg)
    try:
        agent.train(total_env_steps=NUM_ENVS * UNROLL)  # jit warm-up
        t0 = time.perf_counter()
        history = agent.train(total_env_steps=NUM_ENVS * UNROLL + steps)
        elapsed = time.perf_counter() - t0
    finally:
        agent.close()
    fps = steps / elapsed
    losses = [h["loss"] for h in history]
    last = history[-1]
    label = "introspect=on " if introspect else "introspect=off"
    print(
        f"introspect_smoke {label}: fps={fps:12,.0f}  "
        f"compiles={int(last.get('compiles', 0))}  "
        f"staleness_p95={last.get('staleness_p95', '-')}  "
        f"kl={last.get('kl', '-')}"
    )
    return fps, losses, last


# Best-of-three per mode, alternating (the perf_smoke discipline: the
# first training run in a process is systematically slow, and this
# 1-core box's scheduler noise swings identical configs run to run).
run(True)  # discarded process warm-up
fps_off, losses_off, last_off = run(False)
fps_on, losses_on, last_on = run(True)
for _ in range(2):
    f, _, _ = run(False)
    fps_off = max(fps_off, f)
    f, _, _ = run(True)
    fps_on = max(fps_on, f)

if not np.array_equal(np.asarray(losses_on), np.asarray(losses_off)):
    sys.exit(
        "introspect_smoke FAILED: introspect on/off losses diverged on a "
        "fixed seed — the diagnostics aux perturbed the update"
    )
print(f"introspect_smoke: losses identical across {len(losses_on)} windows")

missing = [k for k in INTROSPECT_KEYS if k not in last_on]
if missing:
    sys.exit(
        f"introspect_smoke FAILED: ON run's window is missing {missing}"
    )
leaked = [k for k in INTROSPECT_KEYS if k in last_off]
if leaked:
    sys.exit(
        f"introspect_smoke FAILED: OFF run's window leaked {leaked}"
    )
print("introspect_smoke: ON windows carry the introspection keys, "
      "OFF windows do not")

if fps_on * tolerance < fps_off:
    sys.exit(
        f"introspect_smoke FAILED: introspection overhead above budget "
        f"({fps_on:,.0f} vs {fps_off:,.0f} fps, tolerance {tolerance}x)"
    )
print(
    f"introspect_smoke OK: introspected {fps_on:,.0f} fps vs plain "
    f"{fps_off:,.0f} fps ({fps_on / fps_off:.3f}x, budget {tolerance}x)"
)

if record:
    from asyncrl_tpu.utils import bench_history

    entry = bench_history.record({
        "kind": "observability",
        "probe": "introspect_ab",
        "preset": "pong_impala(sebulba tiny)",
        **bench_history.device_entry(),
        "num_envs": NUM_ENVS,
        "actor_threads": 1,
        "unroll_len": UNROLL,
        "updates": updates,
        "fps_introspected": round(fps_on),
        "fps_plain": round(fps_off),
        "introspect_overhead": round(fps_off / fps_on, 3),
        "compiles": int(last_on.get("compiles", 0)),
    })
    print("introspect_smoke: recorded", entry["ts"])
EOF
