"""Roofline / MFU analysis for a training config (VERDICT.md round 1,
Next #2: "report fps plus a roofline/MFU estimate and dispatch-vs-compute
breakdown").

    python scripts/roofline.py [preset] [key=value ...]

Method:
- FLOPs per fused update call come from XLA's own cost model
  (``compiled.cost_analysis()['flops']``) — the compiler's count for the
  exact program that runs, not a hand-derived formula.
- Achieved FLOP/s = flops_per_call * calls / elapsed, measured with the
  same D2H-read sync discipline as bench.py.
- MFU = achieved / peak for the device kind (bf16 peak table below; the
  number is labeled n/a on CPU).
- Dispatch-vs-compute: fps measured at updates_per_call=1 vs the
  configured fusion. The gap is the per-call host->device round trip
  amortized away by fusion; on the tunneled chip this dominates.

One JSON line per run, appended to BENCH_HISTORY.json (kind="roofline").
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import cpu_fallback_or_refuse, timed_update_window  # noqa: E402

# Dense peak FLOP/s by device kind prefix (bf16 for TPUs). Sources: public
# cloud TPU spec sheets; extend as kinds appear.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,  # v5e bf16
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v6": 918e12,  # trillium bf16
}


def peak_for(device_kind: str) -> float | None:
    for prefix, peak in PEAK_FLOPS.items():
        if device_kind.startswith(prefix):
            return peak
    return None


def measure(cfg, preset_name: str) -> dict:
    import jax

    from asyncrl_tpu.api.trainer import Trainer

    import math

    trainer = Trainer(cfg)
    state = trainer.state

    # XLA's FLOP count for the exact compiled update program. The AOT
    # executable is ALSO what the timed window runs (an AOT compile does
    # not populate the jit dispatch cache, and the pixel IMPALA-CNN
    # program takes minutes to build — one compile per measure(), not two).
    compiled = trainer.learner._step.lower(state).compile()
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, list) else cost
    flops_per_call = float(cost.get("flops", float("nan")))
    if math.isnan(flops_per_call):
        # Backend without a flops estimate: null, never NaN — the ledger
        # must stay strict JSON.
        flops_per_call = None

    state, calls, elapsed = timed_update_window(
        lambda s: compiled(s), state, cfg.updates_per_call, min_seconds=3.0
    )
    frames = calls * cfg.updates_per_call * cfg.num_envs * cfg.unroll_len
    fps = frames / elapsed
    achieved = (
        flops_per_call * calls / elapsed
        if flops_per_call is not None
        else None
    )

    dev = jax.devices()[0]
    peak = peak_for(dev.device_kind)
    return {
        "preset": preset_name,
        "device_kind": dev.device_kind,
        "num_envs": cfg.num_envs,
        "unroll_len": cfg.unroll_len,
        "updates_per_call": cfg.updates_per_call,
        "frames_per_sec": round(fps),
        "flops_per_call": flops_per_call,
        "achieved_tflops": (
            round(achieved / 1e12, 3) if achieved is not None else None
        ),
        "mfu": (
            round(achieved / peak, 4)
            if peak and achieved is not None
            else None
        ),
        "seconds_per_call": round(elapsed / calls, 5),
    }


def main() -> int:
    import jax

    args = sys.argv[1:]
    overrides = [a for a in args if "=" in a]
    names = [a for a in args if "=" not in a]
    preset_name = names[0] if names else "atari_impala"

    cpu_fallback_or_refuse(jax, "roofline")

    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils import bench_history
    from asyncrl_tpu.utils.config import override

    cfg = override(presets.get(preset_name), overrides)
    if cfg.backend != "tpu":
        print(
            f"roofline: effective backend={cfg.backend!r}; this analysis "
            "times the Anakin update program — host backends are measured "
            "by scripts/bench_matrix.py",
            file=sys.stderr,
        )
        return 2

    fused = measure(cfg, preset_name)
    if cfg.updates_per_call > 1:
        # Dispatch-vs-compute: the SAME geometry without fusion. The fps
        # gap is pure per-call latency (identical math per update).
        unfused = measure(cfg.replace(updates_per_call=1), preset_name)
        dispatch_overhead = round(
            max(
                0.0,
                unfused["seconds_per_call"]
                - fused["seconds_per_call"] / cfg.updates_per_call,
            ),
            5,
        )
        unfused_fps = unfused["frames_per_sec"]
    else:
        # K=1: nothing to compare against — record the fields as
        # UNMEASURED (null), never as a fabricated zero-overhead datapoint.
        dispatch_overhead = None
        unfused_fps = None

    result = {
        "kind": "roofline",
        **bench_history.device_entry(),
        **fused,
        "unfused_frames_per_sec": unfused_fps,
        "dispatch_overhead_s_per_update": dispatch_overhead,
        "compute_s_per_update": round(
            fused["seconds_per_call"] / max(cfg.updates_per_call, 1), 5
        ),
    }
    try:
        bench_history.record(result)
    except OSError as e:
        print(f"roofline: could not persist: {e}", file=sys.stderr)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
