"""Real-chip validation + microbench for the device hot path's Pallas
kernels.

``Config.scan_impl='auto'`` resolves to ``associative`` everywhere because
the Pallas VMEM kernel had never run on actual TPU hardware (utils/config.py
scan_impl note). This script is the validation gate: on a live chip it
judges each kernel set against its contract and appends one
``kind="kernel_validation"`` entry per set to BENCH_HISTORY.json:

- ``scan`` — ``reverse_linear_scan_pallas`` + its explicit-DMA twin
  (``pallas_dma`` — the ROADMAP item-2 beachhead whose start/wait
  discipline the PAL static pass guards) vs the ``lax.associative_scan``
  reference, judged against a float64 sequential truth (scale-aware
  RMS-relative error — a per-element relative metric falsely flags
  rounding tails at large T*B; see the inline comment).
- ``fused`` — the fused V-trace/GAE tail kernel (``ops/pallas_scan.py``)
  vs the sequential lax reference: the contract is BIT-identity (all
  four V-trace outputs and both GAE outputs, ``np.array_equal``), the
  same claim tests/test_differential.py pins through the interpreter,
  here on real silicon where the Mosaic compiler (not the interpreter)
  decides FMA contraction.
- ``ring`` — the RDMA ring all-reduce (``ops/ring_reduce.py``) under a
  ``check_vma=False`` shard_map: bit-identity vs the lax twin (same
  schedule, same operand order), the (n-1)-step ULP envelope vs
  ``psum`` (bit-identity at n=2), replication across devices. Skipped
  (ok) on a single-device chip — there is no ring to run.

    python scripts/validate_pallas_tpu.py [scan] [fused] [ring]

No argv = all sets. Exit 0 = every selected set matched (safe to
promote); exit 1 = mismatch (keep the lax defaults; the ledger entry
records which geometry); exit 2 = no accelerator / bad argv.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import functools

from asyncrl_tpu.ops.scan import reverse_linear_scan
from asyncrl_tpu.utils import bench_history

# (T, B): preset fragment shapes (unroll_len x num_envs) plus a long-horizon
# sequence-parallel shape (SURVEY.md §5.7) and a ragged-tile edge case.
GEOMETRIES = [(32, 256), (32, 1024), (16, 64), (128, 4096), (20, 96)]


def timed(fn, *args, reps=20):
    jax.block_until_ready(fn(*args))  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def validate_scan() -> bool:
    rng = np.random.default_rng(0)
    results = []
    ok = True
    for T, B in GEOMETRIES:
        a = jnp.asarray(rng.uniform(0.8, 1.0, (T, B)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(T, B)).astype(np.float32))
        ref_fn = jax.jit(
            functools.partial(reverse_linear_scan, impl="associative")
        )
        pal_fn = jax.jit(
            functools.partial(reverse_linear_scan, impl="pallas")
        )
        dma_fn = jax.jit(
            functools.partial(reverse_linear_scan, impl="pallas_dma")
        )
        ref = jax.device_get(ref_fn(a, b))
        outs = {}
        errors = {}
        for name, fn in (("pallas", pal_fn), ("pallas_dma", dma_fn)):
            try:
                outs[name] = jax.device_get(fn(a, b))
            except Exception as e:  # noqa: BLE001 — record, don't crash
                errors[name] = str(e)[:300]
        if errors and not outs:
            results.append({"T": T, "B": B, "error": errors})
            ok = False
            continue
        # Judge every f32 implementation against a float64 sequential
        # truth, scale-aware (max abs error over the fragment's RMS).
        # A per-element relative metric is unusable here: b is zero-mean,
        # so some (t, col) entries cancel to near zero and the max over
        # T*B samples of |d|/|ref| reads as "mismatch" purely from f32
        # rounding tails — measured 0.013 between two CORRECT f32 impls
        # on CPU at (128, 4096) while the scale-aware error was ~1e-6.
        xs = np.zeros(B, np.float64)
        truth = np.zeros((T, B), np.float64)
        a64, b64 = np.asarray(a, np.float64), np.asarray(b, np.float64)
        for t in range(T - 1, -1, -1):
            xs = b64[t] + a64[t] * xs
            truth[t] = xs
        rms = float(np.sqrt(np.mean(truth**2))) or 1.0
        err_ref = float(np.max(np.abs(ref - truth))) / rms
        entry = {
            "T": T, "B": B,
            "rms_rel_err_associative": err_ref,
            "associative_us": round(timed(ref_fn, a, b) * 1e6, 1),
        }
        if errors:
            entry["error"] = errors
        match = not errors
        for name, fn in (("pallas", pal_fn), ("pallas_dma", dma_fn)):
            if name not in outs:
                continue
            err = float(np.max(np.abs(outs[name] - truth))) / rms
            # A kernel passes if it is no worse than the associative tree
            # (2x margin for fma-ordering differences) AND under an
            # absolute scale-aware ceiling: the relative gate alone would
            # stamp ok:true in a regime where BOTH f32 implementations
            # are badly wrong (shared-error blind spot — ADVICE r3). 1e-3
            # is ~100x the worst healthy f32 error observed across the
            # swept geometries.
            kernel_ok = bool(
                err <= max(2.0 * err_ref, 1e-5) and err < 1e-3
            )
            match = match and kernel_ok
            t_k = timed(fn, a, b)
            entry[f"rms_rel_err_{name}"] = err
            entry[f"{name}_us"] = round(t_k * 1e6, 1)
            entry[f"{name}_speedup"] = round(
                entry["associative_us"] / max(t_k * 1e6, 1e-9), 2
            )
        # Back-compat aliases consumed by obs doctor / older tooling.
        if "rms_rel_err_pallas" in entry:
            entry["rms_rel_err"] = entry["rms_rel_err_pallas"]
            entry["speedup"] = entry["pallas_speedup"]
        entry["match"] = match
        ok = ok and match
        results.append(entry)
        print(json.dumps(entry))

    entry = {
        "kind": "kernel_validation",
        "kernel": "reverse_linear_scan_pallas",
        **bench_history.device_entry(),
        "ok": ok,
        "geometries": results,
    }
    bench_history.record(entry)
    print(json.dumps({"kernel": "scan", "ok": ok, "n": len(results)}))
    return ok


def validate_fused() -> bool:
    """Fused V-trace/GAE vs the sequential lax reference: bit-identity,
    on the real Mosaic-compiled kernel."""
    from asyncrl_tpu.ops.gae import gae
    from asyncrl_tpu.ops.vtrace import vtrace

    rng = np.random.default_rng(1)
    results = []
    ok = True
    for T, B in GEOMETRIES:
        f = lambda *s: jnp.asarray(rng.standard_normal(s).astype(np.float32))
        kw = dict(
            behaviour_logp=f(T, B), target_logp=f(T, B), rewards=f(T, B),
            discounts=jnp.asarray(
                (0.99 * (rng.random((T, B)) > 0.1)).astype(np.float32)
            ),
            values=f(T, B), bootstrap_value=f(B),
        )
        vt_ref = jax.jit(
            functools.partial(vtrace, scan_impl="sequential", fused="lax")
        )
        vt_pal = jax.jit(functools.partial(vtrace, fused="pallas"))
        entry = {"T": T, "B": B}
        try:
            ref = jax.device_get(vt_ref(**kw))
            out = jax.device_get(vt_pal(**kw))
        except Exception as e:  # noqa: BLE001 — record, don't crash
            entry["error"] = str(e)[:300]
            entry["match"] = False
            ok = False
            results.append(entry)
            print(json.dumps(entry))
            continue
        match = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(ref, out)
        )
        mismatched = [
            name for name, a, b in zip(ref._fields, ref, out)
            if not np.array_equal(np.asarray(a), np.asarray(b))
        ]
        g_ref = jax.device_get(gae(
            kw["rewards"], kw["discounts"], kw["values"],
            kw["bootstrap_value"], gae_lambda=0.95,
            scan_impl="sequential", fused="lax",
        ))
        g_out = jax.device_get(gae(
            kw["rewards"], kw["discounts"], kw["values"],
            kw["bootstrap_value"], gae_lambda=0.95, fused="pallas",
        ))
        if not all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(g_ref, g_out)
        ):
            match = False
            mismatched.append("gae")
        t_ref = timed(lambda: vt_ref(**kw))
        t_pal = timed(lambda: vt_pal(**kw))
        entry.update({
            "match": match,
            "lax_us": round(t_ref * 1e6, 1),
            "pallas_us": round(t_pal * 1e6, 1),
            "speedup": round(t_ref / max(t_pal, 1e-9), 2),
        })
        if mismatched:
            entry["mismatched"] = mismatched
        ok = ok and match
        results.append(entry)
        print(json.dumps(entry))

    bench_history.record({
        "kind": "kernel_validation",
        "kernel": "fused_vtrace_pallas",
        **bench_history.device_entry(),
        "ok": ok,
        "geometries": results,
    })
    print(json.dumps({"kernel": "fused", "ok": ok, "n": len(results)}))
    return ok


def validate_ring() -> bool:
    """RDMA ring vs lax twin (bit-identity) and psum (ULP envelope), on
    the real ICI fabric."""
    from asyncrl_tpu.ops import ring_reduce
    from asyncrl_tpu.parallel.mesh import make_mesh, shard_map
    from jax.sharding import PartitionSpec as P

    devices = jax.devices()
    n = len(devices)
    if n < 2:
        print(json.dumps({
            "kernel": "ring", "ok": True, "skipped": f"{n} device(s)"
        }))
        return True
    mesh = make_mesh((n,), ("dp",), devices=devices)

    def all_reduce(fn, vals, checked):
        def body(x):
            return fn(x[0])[None]

        # The pallas_call has no replication rule on jax 0.4.x, so the
        # kernel (and, for schedule-timing parity, its lax twin) runs
        # under the check_vma=False wrapper; psum keeps the checked path.
        kw = {} if checked else {"check_vma": False}
        return np.asarray(jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"), **kw
        ))(vals))

    rng = np.random.default_rng(2)
    results = []
    ok = True
    # Ragged small, lane-aligned mid, and the largest payload the
    # kernel's VMEM scratch budget admits at this ring size (the
    # gradient-tree regime: ops/ring_reduce.py _MAX_SUBLANES).
    for d in (
        1031,
        2 * n * 64 * 128,
        2 * n * ring_reduce._MAX_SUBLANES * 128,
    ):
        vals = rng.standard_normal((n, d)).astype(np.float32)
        entry = {"n": n, "d": d}
        try:
            pal = all_reduce(
                lambda x: ring_reduce.ring_all_reduce_pallas(
                    x, "dp", axis_size=n
                ),
                vals, checked=False,
            )
            lax_twin = all_reduce(
                lambda x: ring_reduce.ring_all_reduce_lax(
                    x, "dp", axis_size=n
                ),
                vals, checked=False,
            )
            psum = all_reduce(lambda x: jax.lax.psum(x, "dp"), vals, True)
        except Exception as e:  # noqa: BLE001 — record, don't crash
            entry["error"] = str(e)[:300]
            entry["match"] = False
            ok = False
            results.append(entry)
            print(json.dumps(entry))
            continue
        # Twin contract: same schedule, same operand order -> same bits.
        twin_ok = bool(np.array_equal(pal, lax_twin))
        # Replication: every device ends with the same bits.
        rep_ok = all(np.array_equal(pal[0], row) for row in pal[1:])
        # psum envelope: condition-relative (n-1)-step float-fold bound
        # (tests/test_ring_reduce.py rationale); bit-identical at n=2.
        if n == 2:
            psum_ok = bool(np.array_equal(pal, psum))
            psum_err = 0.0 if psum_ok else float(
                np.max(np.abs(pal - psum))
            )
        else:
            cond = np.sum(np.abs(vals), axis=0)
            psum_err = float(np.max(np.abs(pal - psum)[0] / cond))
            psum_ok = psum_err < (n - 1) * np.finfo(np.float32).eps
        match = twin_ok and rep_ok and psum_ok
        entry.update({
            "twin_bit_identical": twin_ok,
            "replicated": rep_ok,
            "psum_err": psum_err,
            "match": match,
        })
        ok = ok and match
        results.append(entry)
        print(json.dumps(entry))

    bench_history.record({
        "kind": "kernel_validation",
        "kernel": "ring_all_reduce_pallas",
        **bench_history.device_entry(),
        "ok": ok,
        "geometries": results,
    })
    print(json.dumps({"kernel": "ring", "ok": ok, "n": len(results)}))
    return ok


KERNEL_SETS = {
    "scan": validate_scan,
    "fused": validate_fused,
    "ring": validate_ring,
}


def main() -> int:
    selected = sys.argv[1:] or list(KERNEL_SETS)
    unknown = [k for k in selected if k not in KERNEL_SETS]
    if unknown:
        print(
            f"validate_pallas_tpu: unknown kernel set(s) {unknown}; "
            f"expected any of {list(KERNEL_SETS)}",
            file=sys.stderr,
        )
        return 2
    if jax.devices()[0].platform == "cpu":
        print("validate_pallas_tpu: no accelerator; refusing (the whole "
              "point is real-chip behaviour)", file=sys.stderr)
        return 2
    ok = True
    for name in selected:
        ok = KERNEL_SETS[name]() and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
