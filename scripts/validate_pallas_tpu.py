"""Real-chip validation + microbench for the Pallas reverse-scan kernel.

``Config.scan_impl='auto'`` resolves to ``associative`` everywhere because
the Pallas VMEM kernel had never run on actual TPU hardware (utils/config.py
scan_impl note). This script is the validation gate: on a live chip it
judges the ``reverse_linear_scan_pallas`` kernel, its explicit-DMA twin
(``pallas_dma`` — the ROADMAP item-2 beachhead whose start/wait discipline
the PAL static pass guards), and the ``lax.associative_scan`` reference
against a float64 sequential truth across the fragment geometries the
presets use (scale-aware RMS-relative error — a per-element relative
metric falsely flags rounding tails at large T*B; see the inline comment),
times all three, and appends a ``kind="kernel_validation"`` entry to
BENCH_HISTORY.json.

    python scripts/validate_pallas_tpu.py

Exit 0 = every geometry matched (the kernel is no less accurate than the
associative reference — safe to promote); exit 1 = mismatch (keep the
associative default, entry records which geometry).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import functools

from asyncrl_tpu.ops.scan import reverse_linear_scan
from asyncrl_tpu.utils import bench_history

# (T, B): preset fragment shapes (unroll_len x num_envs) plus a long-horizon
# sequence-parallel shape (SURVEY.md §5.7) and a ragged-tile edge case.
GEOMETRIES = [(32, 256), (32, 1024), (16, 64), (128, 4096), (20, 96)]


def timed(fn, *args, reps=20):
    jax.block_until_ready(fn(*args))  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main() -> int:
    dev = jax.devices()[0]
    if dev.platform == "cpu":
        print("validate_pallas_tpu: no accelerator; refusing (the whole "
              "point is real-chip behaviour)", file=sys.stderr)
        return 2

    rng = np.random.default_rng(0)
    results = []
    ok = True
    for T, B in GEOMETRIES:
        a = jnp.asarray(rng.uniform(0.8, 1.0, (T, B)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(T, B)).astype(np.float32))
        ref_fn = jax.jit(
            functools.partial(reverse_linear_scan, impl="associative")
        )
        pal_fn = jax.jit(
            functools.partial(reverse_linear_scan, impl="pallas")
        )
        dma_fn = jax.jit(
            functools.partial(reverse_linear_scan, impl="pallas_dma")
        )
        ref = jax.device_get(ref_fn(a, b))
        outs = {}
        errors = {}
        for name, fn in (("pallas", pal_fn), ("pallas_dma", dma_fn)):
            try:
                outs[name] = jax.device_get(fn(a, b))
            except Exception as e:  # noqa: BLE001 — record, don't crash
                errors[name] = str(e)[:300]
        if errors and not outs:
            results.append({"T": T, "B": B, "error": errors})
            ok = False
            continue
        # Judge every f32 implementation against a float64 sequential
        # truth, scale-aware (max abs error over the fragment's RMS).
        # A per-element relative metric is unusable here: b is zero-mean,
        # so some (t, col) entries cancel to near zero and the max over
        # T*B samples of |d|/|ref| reads as "mismatch" purely from f32
        # rounding tails — measured 0.013 between two CORRECT f32 impls
        # on CPU at (128, 4096) while the scale-aware error was ~1e-6.
        xs = np.zeros(B, np.float64)
        truth = np.zeros((T, B), np.float64)
        a64, b64 = np.asarray(a, np.float64), np.asarray(b, np.float64)
        for t in range(T - 1, -1, -1):
            xs = b64[t] + a64[t] * xs
            truth[t] = xs
        rms = float(np.sqrt(np.mean(truth**2))) or 1.0
        err_ref = float(np.max(np.abs(ref - truth))) / rms
        entry = {
            "T": T, "B": B,
            "rms_rel_err_associative": err_ref,
            "associative_us": round(timed(ref_fn, a, b) * 1e6, 1),
        }
        if errors:
            entry["error"] = errors
        match = not errors
        for name, fn in (("pallas", pal_fn), ("pallas_dma", dma_fn)):
            if name not in outs:
                continue
            err = float(np.max(np.abs(outs[name] - truth))) / rms
            # A kernel passes if it is no worse than the associative tree
            # (2x margin for fma-ordering differences) AND under an
            # absolute scale-aware ceiling: the relative gate alone would
            # stamp ok:true in a regime where BOTH f32 implementations
            # are badly wrong (shared-error blind spot — ADVICE r3). 1e-3
            # is ~100x the worst healthy f32 error observed across the
            # swept geometries.
            kernel_ok = bool(
                err <= max(2.0 * err_ref, 1e-5) and err < 1e-3
            )
            match = match and kernel_ok
            t_k = timed(fn, a, b)
            entry[f"rms_rel_err_{name}"] = err
            entry[f"{name}_us"] = round(t_k * 1e6, 1)
            entry[f"{name}_speedup"] = round(
                entry["associative_us"] / max(t_k * 1e6, 1e-9), 2
            )
        # Back-compat aliases consumed by obs doctor / older tooling.
        if "rms_rel_err_pallas" in entry:
            entry["rms_rel_err"] = entry["rms_rel_err_pallas"]
            entry["speedup"] = entry["pallas_speedup"]
        entry["match"] = match
        ok = ok and match
        results.append(entry)
        print(json.dumps(entry))

    entry = {
        "kind": "kernel_validation",
        "kernel": "reverse_linear_scan_pallas",
        **bench_history.device_entry(),
        "ok": ok,
        "geometries": results,
    }
    bench_history.record(entry)
    print(json.dumps({"ok": ok, "n": len(results)}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
