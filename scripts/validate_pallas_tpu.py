"""Real-chip validation + microbench for the Pallas reverse-scan kernel.

``Config.scan_impl='auto'`` resolves to ``associative`` everywhere because
the Pallas VMEM kernel had never run on actual TPU hardware (utils/config.py
scan_impl note). This script is the validation gate: on a live chip it
checks ``reverse_linear_scan_pallas`` against the ``lax.associative_scan``
reference across the fragment geometries the presets use, times both, and
appends a ``kind="kernel_validation"`` entry to BENCH_HISTORY.json.

    python scripts/validate_pallas_tpu.py

Exit 0 = every geometry matched (the kernel is safe to promote); exit 1 =
mismatch (keep the associative default, entry records which geometry).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import functools

from asyncrl_tpu.ops.scan import reverse_linear_scan
from asyncrl_tpu.utils import bench_history

# (T, B): preset fragment shapes (unroll_len x num_envs) plus a long-horizon
# sequence-parallel shape (SURVEY.md §5.7) and a ragged-tile edge case.
GEOMETRIES = [(32, 256), (32, 1024), (16, 64), (128, 4096), (20, 96)]


def timed(fn, *args, reps=20):
    jax.block_until_ready(fn(*args))  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main() -> int:
    dev = jax.devices()[0]
    if dev.platform == "cpu":
        print("validate_pallas_tpu: no accelerator; refusing (the whole "
              "point is real-chip behaviour)", file=sys.stderr)
        return 2

    rng = np.random.default_rng(0)
    results = []
    ok = True
    for T, B in GEOMETRIES:
        a = jnp.asarray(rng.uniform(0.8, 1.0, (T, B)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(T, B)).astype(np.float32))
        ref_fn = jax.jit(
            functools.partial(reverse_linear_scan, impl="associative")
        )
        pal_fn = jax.jit(
            functools.partial(reverse_linear_scan, impl="pallas")
        )
        ref = jax.device_get(ref_fn(a, b))
        try:
            out = jax.device_get(pal_fn(a, b))
        except Exception as e:  # noqa: BLE001 — record, don't crash the sweep
            results.append({"T": T, "B": B, "error": str(e)[:300]})
            ok = False
            continue
        # The kernel's sequential walk is MORE accurate than the
        # associative tree (no re-association); tolerance covers the
        # tree's f32 rounding across log2(T) rounds.
        err = float(np.max(np.abs(out - ref) / (np.abs(ref) + 1e-6)))
        match = bool(err < 1e-4)
        ok = ok and match
        t_ref = timed(ref_fn, a, b)
        t_pal = timed(pal_fn, a, b)
        results.append({
            "T": T, "B": B, "max_rel_err": err, "match": match,
            "associative_us": round(t_ref * 1e6, 1),
            "pallas_us": round(t_pal * 1e6, 1),
            "speedup": round(t_ref / t_pal, 2),
        })
        print(json.dumps(results[-1]))

    entry = {
        "kind": "kernel_validation",
        "kernel": "reverse_linear_scan_pallas",
        **bench_history.device_entry(),
        "ok": ok,
        "geometries": results,
    }
    bench_history.record(entry)
    print(json.dumps({"ok": ok, "n": len(results)}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
