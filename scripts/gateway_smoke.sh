#!/usr/bin/env bash
# Gateway smoke: the external serving gateway (asyncrl_tpu/serve/gateway.py)
# proven as a load-generator A/B in five acts:
#
#   Act 1 — gateway-off bit-identity: a gateway_port=0 run and a mounted-
#     but-idle gateway_port=-1 run produce IDENTICAL per-window losses
#     (the introspect=False discipline at the wire boundary), and the off
#     run leaks ZERO gateway keys into its windows.
#   Act 2 — sustained external QPS: wire clients (two tenant classes) hit
#     /v1/act and /v1/evaluate while training continues and weights swap
#     live; gates: requests served, >1 distinct generation observed over
#     the wire (live zero-drain swaps), per-tenant p99 under
#     ASYNCRL_GATEWAY_P99_MS (default 1500 ms — generous for this shared
#     1-core box, where the learner's jitted update and the gateway
#     share one CPU; tighten on real serving hardware), zero gateway 500s,
#     zero breaker-opens.
#   Act 3 — netfault chaos: every netfault mode (disconnect, slowloris,
#     malformed, crash) under client load with live /healthz polling;
#     gates: training reaches its target (no storm abort, zero dropped
#     work), the fault fired, a flight-recorder dump landed, /healthz
#     finishes ok, and the disconnect act observes the degrade->recover
#     edge (gateway_error_rate fires, then the TTL clears it).
#   Act 4 — replicated fleet (asyncrl_tpu/serve/fleet.py): >= 2 replicas
#     behind one gateway under sustained multi-tenant QPS, in two scenes.
#     Scene A: a live canary PROMOTION (agreeing version) while every
#     response stamps its replica + generation and no batch ever mixes
#     generations. Scene B: an injected-divergence canary with a replica
#     KILL mid-canary through the fleet.replica chaos grammar — gates:
#     the kill lands while the canary is live, the core is supervised
#     back into rotation, the canary auto-ROLLS BACK and vetoes the
#     version, zero generation mixing throughout, and the client sees no
#     availability gap beyond the failover budget (sheds allowed,
#     unavailability not).
#   Act 5 — request tracing (asyncrl_tpu/obs/requests.py): two scenes.
#     Scene A: journaling ARMED over a replicated fleet under two-tenant
#     QPS with a replica KILL mid-run; gates: the kill fired, journals
#     persisted to requests.jsonl, `obs explain --worst 5` renders, and
#     every worst-5 journal names a known deciding stage with its level-0
#     segments summing to its latency within tolerance. Scene B: an
#     on/off A/B of the same sequential wire load; gate: armed-vs-
#     disarmed median latency ratio under ASYNCRL_TRACE_AB_MAX (default
#     1.15x — a noise bar, not a budget: the journal is a few dict
#     appends per request). ASYNCRL_SMOKE_RECORD=1 appends the A/B as a
#     kind="observability" probe="request_trace_ab" BENCH_HISTORY row.
#
# Usage: scripts/gateway_smoke.sh                  # CPU, ~2-3 min
#        ASYNCRL_SMOKE_UPDATES=32 scripts/gateway_smoke.sh
#        ASYNCRL_GATEWAY_QPS=100 ASYNCRL_GATEWAY_P99_MS=500 ...
#        ASYNCRL_SMOKE_RECORD=1 scripts/gateway_smoke.sh  # append the A/B
#          as a kind="robustness" probe="gateway_ab" BENCH_HISTORY row
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
UPDATES="${ASYNCRL_SMOKE_UPDATES:-24}"
QPS="${ASYNCRL_GATEWAY_QPS:-50}"
P99_BUDGET_MS="${ASYNCRL_GATEWAY_P99_MS:-1500}"
RECORD="${ASYNCRL_SMOKE_RECORD:-0}"

python - "$UPDATES" "$QPS" "$P99_BUDGET_MS" "$RECORD" <<'EOF'
import json
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

from asyncrl_tpu import make_agent
from asyncrl_tpu.configs import presets
from asyncrl_tpu.serve import (
    BreakerOpen, GatewayClient, GatewayShed, GatewayUnavailable,
)

updates, qps = int(sys.argv[1]), float(sys.argv[2])
p99_budget_ms = float(sys.argv[3])
record = sys.argv[4] not in ("", "0")
NUM_ENVS, UNROLL, THREADS = 16, 16, 2
steps = updates * NUM_ENVS * UNROLL
ledger = {}


def base_cfg(**overrides):
    base = dict(
        num_envs=NUM_ENVS, actor_threads=THREADS, unroll_len=UNROLL,
        precision="f32", log_every=4, seed=3, hidden_sizes=(64, 64),
        actor_staleness=2,
    )
    base.update(overrides)
    return presets.get("pong_serve").replace(**base)


# ------------------------------------------------------ act 1: bit identity
def losses(history):
    return [h["loss"] for h in history]


def run_plain(gateway_port):
    # Single actor + frozen behaviour params (the elastic_smoke identity
    # discipline): losses must be seed-deterministic — no publish-timing
    # or fragment-interleaving race — for the bit-identity assertion.
    agent = make_agent(base_cfg(
        gateway_port=gateway_port, actor_threads=1,
        actor_staleness=1_000_000,
    ))
    try:
        history = agent.train(total_env_steps=steps)
    finally:
        agent.close()
    return history


hist_off = run_plain(0)
hist_idle = run_plain(-1)
if losses(hist_off) != losses(hist_idle):
    sys.exit(
        "gateway_smoke FAILED (act 1): gateway-off and idle-gateway loss "
        f"streams differ:\n  off : {losses(hist_off)[:4]}...\n  idle: "
        f"{losses(hist_idle)[:4]}..."
    )
leaked = sorted(
    k for h in hist_off for k in h if k.startswith("gateway")
)
if leaked:
    sys.exit(f"gateway_smoke FAILED (act 1): gateway-off leaked {leaked}")
if not any(k.startswith("gateway") for k in hist_idle[-1]):
    sys.exit("gateway_smoke FAILED (act 1): mounted gateway exported no keys")
print(f"gateway_smoke act 1 OK: {len(hist_off)} windows loss-bit-identical; "
      "off leaks zero gateway keys")
ledger["act1_bit_identical"] = True


# --------------------------------------------------- act 2: sustained QPS
class LoadGen:
    def __init__(self, port, tenant, endpoint, rate_hz, seed=0,
                 client_kwargs=None):
        self.client = GatewayClient(
            f"http://127.0.0.1:{port}", tenant=tenant,
            **{
                "deadline_ms": 2000, "retries": 3, "backoff_base_s": 0.01,
                "seed": seed, **(client_kwargs or {}),
            },
        )
        self.endpoint = endpoint
        self.period = 1.0 / rate_hz
        self.served = 0
        self.shed = 0
        self.failed = 0
        self.latencies_ms = []
        self.generations = set()
        self.stop = threading.Event()
        self.thread = threading.Thread(
            target=self._run, name=f"loadgen-{tenant}", daemon=True
        )

    def _run(self):
        call = getattr(self.client, self.endpoint)
        while not self.stop.is_set():
            t0 = time.perf_counter()
            try:
                result = call(np.zeros((2, 6), np.float32))
                self.served += 1
                self.latencies_ms.append(1e3 * (time.perf_counter() - t0))
                self.generations.add(result.generation)
            except (GatewayShed, BreakerOpen):
                self.shed += 1
            except GatewayUnavailable:
                self.failed += 1
            time.sleep(self.period)

    def p99_ms(self, warmup=3):
        """Client-observed p99 over the steady state: the first requests
        pay the one-time jit compile of the external batch shape (a
        cold-start cost, not a serving-latency property) and are
        excluded, the perf_smoke warm-up discipline applied per wire."""
        steady = self.latencies_ms[warmup:]
        if not steady:
            return 0.0
        return float(np.percentile(np.asarray(steady), 99))


# Box-realistic SLO matrix for the measured act: the preset's 250 ms gold
# target breaches constantly on this 1-core box (learner and gateway share
# the CPU), turning the act into a shed/retry storm whose client tails
# measure the retry loop, not the serving path. 1000 ms is the class bar
# this box can actually hold; real serving hardware tightens it.
agent = make_agent(base_cfg(gateway_tenant_spec=(
    "gold:stale:p95_ms=1000,inflight=64;"
    "bulk:shed:rps=100,burst=50;"
    "*:fallback"
)))
agent._start_actors()
port = agent._gateway.port
loaders = [
    LoadGen(port, "gold", "act", qps, seed=11),
    LoadGen(port, "bulk", "evaluate", qps / 2, seed=23),
]
for loader in loaders:
    loader.thread.start()
try:
    t0 = time.perf_counter()
    history = agent.train(total_env_steps=steps)
    elapsed = time.perf_counter() - t0
finally:
    for loader in loaders:
        loader.stop.set()
    for loader in loaders:
        loader.thread.join(timeout=5)
    agent.close()

last = history[-1]
fps = steps / elapsed
served = sum(ld.served for ld in loaders)
generations = set().union(*(ld.generations for ld in loaders))
gold_p99 = loaders[0].p99_ms()
bulk_p99 = loaders[1].p99_ms()
# Liveness: the per-tenant latency taxonomy exported through the window.
for key in ("gateway_gold_latency_ms_p99", "gateway_bulk_latency_ms_p99"):
    if key not in last:
        sys.exit(f"gateway_smoke FAILED (act 2): {key} missing from window")
print(
    f"gateway_smoke act 2: fps={fps:,.0f} served={served} "
    f"(gold act={loaders[0].served}, bulk eval={loaders[1].served}, "
    f"shed={sum(ld.shed for ld in loaders)}) "
    f"generations={len(generations)} gold_p99={gold_p99:.1f}ms "
    f"bulk_p99={bulk_p99:.1f}ms errors={last.get('gateway_errors', 0):.0f}"
)
if served <= 0:
    sys.exit("gateway_smoke FAILED (act 2): no external request served")
if len(generations) < 2:
    sys.exit(
        "gateway_smoke FAILED (act 2): no live weight swap observed over "
        f"the wire (generations {sorted(generations)})"
    )
for name, p99 in (("gold", gold_p99), ("bulk", bulk_p99)):
    if p99 > p99_budget_ms:
        sys.exit(
            f"gateway_smoke FAILED (act 2): tenant {name} p99 {p99:.1f}ms "
            f"over budget {p99_budget_ms:.0f}ms"
        )
if last.get("gateway_errors", 0) > 0:
    sys.exit("gateway_smoke FAILED (act 2): gateway answered 500s under load")
if last.get("gateway_breaker_opened", 0) > 0:
    sys.exit("gateway_smoke FAILED (act 2): a circuit breaker opened")
print("gateway_smoke act 2 OK: sustained QPS under SLO while training, "
      "weights swapping live")
ledger.update({
    "act2_fps": round(fps),
    "act2_served": served,
    "act2_generations": len(generations),
    "act2_gold_p99_ms": round(gold_p99, 2),
    "act2_bulk_p99_ms": round(bulk_p99, 2),
    "p99_budget_ms": p99_budget_ms,
})


# ---------------------------------------------------- act 3: netfault chaos
def healthz(obs_port):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{obs_port}/healthz", timeout=2
        ) as response:
            return json.loads(response.read())["status"]
    except urllib.error.HTTPError as e:  # 503 = degraded/critical
        return json.loads(e.read()).get("status", "unknown")
    except OSError:
        return "unreachable"


def run_netfault(mode, extra_opts=""):
    run_dir = tempfile.mkdtemp(prefix=f"gwsmoke-{mode}-")
    spec = f"gateway.request:netfault:1.0:0:net={mode}{extra_opts}"
    agent = make_agent(base_cfg(
        fault_spec=spec, trace=True, run_dir=run_dir, obs_http_port=-1,
        log_every=2,
    ))
    agent._start_actors()
    port = agent._gateway.port
    obs_port = agent._obs.http.port
    # Act-3 client: a tight deadline (slow-loris must time out, not hang
    # the loader) and a fast-probing breaker, so the fault era is a
    # PREFIX of the run and the steady state after it proves recovery.
    loader = LoadGen(port, "gold", "act", qps, client_kwargs={
        "deadline_ms": 600, "retries": 2, "breaker_reset_s": 0.3,
    })
    loader.thread.start()
    statuses = []
    poll_stop = threading.Event()

    def poll():
        while not poll_stop.is_set():
            statuses.append(healthz(obs_port))
            time.sleep(0.05)

    poller = threading.Thread(target=poll, name="healthz-poll", daemon=True)
    poller.start()
    target = steps
    try:
        history = agent.train(total_env_steps=target)
    finally:
        loader.stop.set()
        loader.thread.join(timeout=5)
    final = healthz(obs_port)
    poll_stop.set()
    poller.join(timeout=5)
    reached = agent.env_steps
    agent.close()
    last = history[-1]
    import glob
    import os
    dumps = glob.glob(os.path.join(run_dir, "flightrec-*.json"))
    print(
        f"gateway_smoke act 3 [{mode}]: served={loader.served} "
        f"netfaults={last.get('gateway_netfaults', 0):.0f} "
        f"restarts={last.get('gateway_restarts', 0):.0f} "
        f"healthz(final)={final} degraded_seen="
        f"{'degraded' in statuses or 'critical' in statuses} "
        f"dumps={len(dumps)}"
    )
    if reached < target:
        sys.exit(f"gateway_smoke FAILED (act 3 {mode}): "
                 f"{reached}/{target} env steps (work was dropped)")
    if last.get("gateway_netfaults", 0) < 1:
        sys.exit(f"gateway_smoke FAILED (act 3 {mode}): fault never fired")
    if mode == "crash" and last.get("gateway_restarts", 0) < 1:
        sys.exit("gateway_smoke FAILED (act 3 crash): no supervised rebuild")
    if last.get("actor_restarts", 0) > 0:
        sys.exit(f"gateway_smoke FAILED (act 3 {mode}): actor fleet dropped")
    if loader.served <= 0:
        sys.exit(f"gateway_smoke FAILED (act 3 {mode}): "
                 "no request survived the fault era")
    if not dumps:
        sys.exit(f"gateway_smoke FAILED (act 3 {mode}): "
                 "no flight-recorder dump landed")
    if final != "ok":
        sys.exit(f"gateway_smoke FAILED (act 3 {mode}): /healthz finished "
                 f"{final!r}, not ok")
    return statuses


# disconnect first, error-heavy: enough failed requests in one window to
# fire the gateway_error_rate detector — the degrade->recover gate.
statuses = run_netfault("disconnect", ",max=4")
if "degraded" not in statuses and "critical" not in statuses:
    sys.exit(
        "gateway_smoke FAILED (act 3 disconnect): /healthz never degraded "
        f"(statuses seen: {sorted(set(statuses))})"
    )
run_netfault("malformed", ",max=4")
run_netfault("slowloris", ",max=2,stall_s=1.5")
run_netfault("crash", ",max=1")
print("gateway_smoke act 3 OK: every netfault mode recovered to /healthz ok")
ledger["act3_modes"] = ["disconnect", "malformed", "slowloris", "crash"]

print("gateway_smoke OK: acts 1-3 green")

if record:
    from asyncrl_tpu.utils import bench_history

    entry = bench_history.record({
        "kind": "robustness",
        "probe": "gateway_ab",
        "preset": "pong_serve(sebulba tiny)",
        **bench_history.device_entry(),
        "num_envs": NUM_ENVS,
        "actor_threads": THREADS,
        "unroll_len": UNROLL,
        "updates": updates,
        "qps_offered": qps,
        **ledger,
    })
    print("gateway_smoke: recorded", entry["ts"])
EOF

# ------------------------------------------------- act 4: replicated fleet
# Standalone fleet (the trainer does not mount one): ParamFeed publisher,
# >= 2 replicas behind ServeGateway via FleetRouter, multi-tenant load.
QPS4="${ASYNCRL_GATEWAY_QPS:-50}"
python - "$QPS4" <<'EOF'
import sys
import threading
import time

import numpy as np

from asyncrl_tpu.obs import registry as obs_registry
from asyncrl_tpu.serve import (
    BreakerOpen, CanaryController, FleetRouter, GatewayClient,
    GatewayShed, GatewayUnavailable, ParamFeed, ServeFleet, ServeGateway,
    parse_tenant_spec,
)
from asyncrl_tpu.utils import faults

qps = float(sys.argv[1])
REPLICAS = 3
TENANT_SPEC = "gold:shed:rps=1000,burst=500;bulk:shed:rps=1000,burst=500"


def version_fn(params, obs, key):
    """action == params["a"]: the version -> action map is the mixing
    oracle — any generation-mixed batch (or mis-stamped response) shows
    an action that disagrees with its version's known value."""
    rows = obs.shape[0]
    value = int(params["a"])
    return (
        np.full((rows,), value, np.int32),
        np.zeros((rows,), np.float32),
        key,
    )


class FleetLoad:
    """Per-tenant load thread recording replica + generation provenance
    and checking the mixing oracle on EVERY response."""

    def __init__(self, port, tenant, rate_hz, vmap, seed):
        self.client = GatewayClient(
            f"http://127.0.0.1:{port}", tenant=tenant, deadline_ms=2000,
            retries=2, backoff_base_s=0.01, seed=seed,
        )
        self.period = 1.0 / rate_hz
        self.vmap = vmap  # version -> expected action value
        self.served = 0
        self.shed = 0
        self.failed = 0
        self.mixed = 0
        self.replicas = set()
        self.versions = set()
        self.stop = threading.Event()
        self.thread = threading.Thread(
            target=self._run, name=f"fleetload-{tenant}", daemon=True
        )

    def _run(self):
        obs = np.zeros((2, 4), np.float32)
        while not self.stop.is_set():
            try:
                result = self.client.act(obs)
                self.served += 1
                self.replicas.add(result.replica)
                self.versions.add(result.generation)
                expected = self.vmap.get(result.generation)
                if expected is not None and any(
                    a != expected for a in result.actions
                ):
                    self.mixed += 1
            except GatewayShed:
                self.shed += 1
            except (GatewayUnavailable, BreakerOpen):
                # Both are availability gaps: an open client breaker
                # means repeated unavailability, not load shedding.
                self.failed += 1
            time.sleep(self.period)


def run_scene(label, vmap, canary, fault_spec, publish, wait_for,
              settle_s=0.0):
    """One fleet scene: build (optionally chaos-armed) fleet + gateway +
    loaders, publish the staged versions, wait for the scene's verdict,
    and gate provenance/mixing/availability on teardown."""
    if fault_spec:
        faults.arm(fault_spec)
    feed = ParamFeed({"a": vmap[0]})
    fleet = ServeFleet(
        version_fn, feed, num_replicas=REPLICAS, deadline_ms=2.0,
        readmit_after_s=0.1, canary=canary, tick_interval_s=0.02,
    )
    fleet.start()
    router = FleetRouter(fleet, obs_shape=(4,))
    gateway = ServeGateway(
        router, port=-1, tenants=parse_tenant_spec(TENANT_SPEC)
    ).start()
    loaders = [
        FleetLoad(gateway.port, "gold", qps / 2, vmap, seed=31),
        FleetLoad(gateway.port, "bulk", qps / 2, vmap, seed=41),
    ]
    for loader in loaders:
        loader.thread.start()
    try:
        time.sleep(0.3)  # a few served requests before the stage turns
        for version, action in publish:
            feed.publish({"a": action})
        deadline = time.monotonic() + 45.0
        while time.monotonic() < deadline and not wait_for(fleet):
            time.sleep(0.05)
        if not wait_for(fleet):
            sys.exit(f"gateway_smoke FAILED (act 4 {label}): scene never "
                     "reached its verdict inside the budget")
        if settle_s:
            time.sleep(settle_s)
    finally:
        for loader in loaders:
            loader.stop.set()
        for loader in loaders:
            loader.thread.join(timeout=5)
        gateway.stop()
        router.close()
        fleet.close()
        faults.disarm()
    served = sum(ld.served for ld in loaders)
    failed = sum(ld.failed for ld in loaders)
    mixed = sum(ld.mixed for ld in loaders)
    replicas = set().union(*(ld.replicas for ld in loaders))
    versions = set().union(*(ld.versions for ld in loaders))
    print(f"gateway_smoke act 4 {label}: served={served} "
          f"shed={sum(ld.shed for ld in loaders)} failed={failed} "
          f"replicas={sorted(replicas)} versions={sorted(versions)}")
    if served < 20:
        sys.exit(f"gateway_smoke FAILED (act 4 {label}): almost no "
                 f"traffic served ({served})")
    if len(replicas) < 2:
        sys.exit(f"gateway_smoke FAILED (act 4 {label}): responses name "
                 f"only {sorted(replicas)} — not a replicated fleet")
    if mixed:
        sys.exit(f"gateway_smoke FAILED (act 4 {label}): {mixed} "
                 "response(s) mixed generations (action != version's "
                 "known value)")
    if failed:
        sys.exit(f"gateway_smoke FAILED (act 4 {label}): {failed} "
                 "unavailability window(s) — failover must absorb every "
                 "replica loss inside the wire budget")
    return fleet


# Scene A — live PROMOTION: v1 agrees with v0 (same action value), the
# canary windows match, the fleet auto-promotes and follows v1.
canary_a = CanaryController(min_serves=24, divergence=0.5, share=4)
fleet_a = run_scene(
    "scene A (promotion)",
    vmap={0: 0, 1: 0},
    canary=canary_a,
    fault_spec="",
    publish=[(1, 0)],
    wait_for=lambda fleet: ("promote", 1) in list(fleet.canary.history),
    settle_s=0.3,
)
if canary_a.stable_version != 1:
    sys.exit("gateway_smoke FAILED (act 4 scene A): promotion did not "
             f"advance the stable version (at {canary_a.stable_version})")
if any(r.version != 1 for r in fleet_a.replicas):
    sys.exit("gateway_smoke FAILED (act 4 scene A): fleet did not follow "
             "the promoted version")

# Scene B — injected divergence + replica KILL mid-canary, through the
# chaos grammar: the fault sleeps for its first 100 tick-calls (~2 s),
# then kills the active canary member (the unnamed-target rule) while
# the high min_serves keeps the canary live past the kill. Gates: the
# kill landed DURING the canary, the core rebuilt, and the divergent
# version rolled back vetoed.
kill_during_canary = {"seen": False}


def scene_b_done(fleet):
    victim_restarts = sum(r.restarts for r in fleet.replicas)
    if victim_restarts >= 1 and fleet.canary.active:
        kill_during_canary["seen"] = True
    return ("rollback", 1) in list(fleet.canary.history)


# window must cover min_serves: the sample deques cap at `window`, so
# the verdict gate (min_serves samples per side) is only reachable when
# window >= min_serves. 150 canary serves at a 1-in-4 split keeps the
# canary alive long enough for the after=100 kill to land mid-canary.
canary_b = CanaryController(window=300, min_serves=150, divergence=0.5, share=4)
fleet_b = run_scene(
    "scene B (kill mid-canary, rollback)",
    vmap={0: 0, 1: 7},
    canary=canary_b,
    fault_spec="fleet.replica:replica:1.0:0:rmode=kill,max=1,after=100",
    publish=[(1, 7)],
    wait_for=scene_b_done,
    settle_s=0.5,  # post-rollback ticks re-pin everyone to stable v0
)
if sum(r.restarts for r in fleet_b.replicas) < 1:
    sys.exit("gateway_smoke FAILED (act 4 scene B): the replica kill "
             "never fired (no supervised rebuild)")
if not kill_during_canary["seen"]:
    sys.exit("gateway_smoke FAILED (act 4 scene B): the kill did not "
             "land while the canary was live")
if 1 not in canary_b.vetoed():
    sys.exit("gateway_smoke FAILED (act 4 scene B): the divergent "
             "version was not vetoed")
if any(r.version != 0 for r in fleet_b.replicas):
    sys.exit("gateway_smoke FAILED (act 4 scene B): a replica still "
             "serves the rolled-back version")
print("gateway_smoke act 4 OK: promotion, kill-mid-canary rollback, "
      "zero mixing, no availability gap")
EOF

# -------------------------------------------- act 5: request tracing
# Scene A: journaling armed over a replicated fleet under two-tenant QPS
# with a replica kill; the persisted journals must survive the `obs
# explain --worst 5` gate. Scene B: on/off A/B of the same wire load.
QPS5="${ASYNCRL_GATEWAY_QPS:-50}"
AB_MAX="${ASYNCRL_TRACE_AB_MAX:-1.15}"
python - "$QPS5" "$AB_MAX" "$RECORD" <<'EOF'
import sys
import tempfile
import threading
import time

import numpy as np

from asyncrl_tpu.obs import requests as obs_requests
from asyncrl_tpu.serve import (
    BreakerOpen, FleetRouter, GatewayClient, GatewayShed,
    GatewayUnavailable, ParamFeed, ServeFleet, ServeGateway,
    parse_tenant_spec,
)
from asyncrl_tpu.utils import faults

qps = float(sys.argv[1])
ab_max = float(sys.argv[2])
record = sys.argv[3] not in ("", "0")
TENANT_SPEC = "gold:shed:rps=1000,burst=500;bulk:shed:rps=1000,burst=500"
DECIDED = {
    getattr(obs_requests, name)
    for name in dir(obs_requests) if name.startswith("DECIDED_")
}


def const_fn(params, obs, key):
    rows = obs.shape[0]
    return (
        np.full((rows,), int(params["a"]), np.int32),
        np.zeros((rows,), np.float32),
        key,
    )


def build_fleet(num_replicas):
    feed = ParamFeed({"a": 0})
    fleet = ServeFleet(
        const_fn, feed, num_replicas=num_replicas, deadline_ms=2.0,
        readmit_after_s=0.1, tick_interval_s=0.02,
    )
    fleet.start()
    router = FleetRouter(fleet, obs_shape=(4,))
    gateway = ServeGateway(
        router, port=-1, tenants=parse_tenant_spec(TENANT_SPEC)
    ).start()
    return fleet, router, gateway


class TraceLoad:
    def __init__(self, port, tenant, rate_hz, seed):
        self.client = GatewayClient(
            f"http://127.0.0.1:{port}", tenant=tenant, deadline_ms=2000,
            retries=2, backoff_base_s=0.01, seed=seed,
        )
        self.period = 1.0 / rate_hz
        self.served = 0
        self.shed = 0
        self.failed = 0
        self.stop = threading.Event()
        self.thread = threading.Thread(
            target=self._run, name=f"traceload-{tenant}", daemon=True
        )

    def _run(self):
        obs = np.zeros((2, 4), np.float32)
        while not self.stop.is_set():
            try:
                self.client.act(obs)
                self.served += 1
            except GatewayShed:
                self.shed += 1
            except (GatewayUnavailable, BreakerOpen):
                self.failed += 1
            time.sleep(self.period)


# ---- scene A: armed journaling + two-tenant QPS + replica kill
run_dir = tempfile.mkdtemp(prefix="gwsmoke-trace-")
# The kill sleeps for its first 50 tick-calls (~1 s at the 0.02 s tick),
# then takes out one replica mid-load; the supervisor rebuilds it.
faults.arm("fleet.replica:replica:1.0:0:rmode=kill,max=1,after=50")
fleet, router, gateway = build_fleet(3)
obs_requests.arm(run_dir=run_dir, meta={"smoke": "gateway_act5"})
loaders = [
    TraceLoad(gateway.port, "gold", qps / 2, seed=7),
    TraceLoad(gateway.port, "bulk", qps / 2, seed=13),
]
for loader in loaders:
    loader.thread.start()
try:
    deadline = time.monotonic() + 20.0
    # Run until the kill landed AND the rebuilt core served again, with
    # a floor of ~3 s of steady two-tenant load either way.
    time.sleep(3.0)
    while time.monotonic() < deadline and (
        sum(r.restarts for r in fleet.replicas) < 1
    ):
        time.sleep(0.1)
    time.sleep(0.5)  # post-rebuild traffic lands in the journal too
    restarts = sum(r.restarts for r in fleet.replicas)
finally:
    for loader in loaders:
        loader.stop.set()
    for loader in loaders:
        loader.thread.join(timeout=5)
    gateway.stop()
    router.close()
    fleet.close()
    faults.disarm()

served = sum(ld.served for ld in loaders)
print(f"gateway_smoke act 5 scene A: served={served} "
      f"shed={sum(ld.shed for ld in loaders)} "
      f"failed={sum(ld.failed for ld in loaders)} restarts={restarts}")
if served < 20:
    sys.exit(f"gateway_smoke FAILED (act 5): almost no traffic ({served})")
if restarts < 1:
    sys.exit("gateway_smoke FAILED (act 5): the replica kill never fired")

text, code = obs_requests.explain(run_dir, worst=5)
if code != 0:
    sys.exit(f"gateway_smoke FAILED (act 5): explain --worst 5 -> {text}")
print("gateway_smoke act 5: obs explain --worst 5")
print("\n".join(f"  {line}" for line in text.splitlines()[:12]))

docs = obs_requests.read_jsonl(f"{run_dir}/requests.jsonl")["requests"]
worst = sorted(
    docs,
    key=lambda d: (int(d.get("status", 0)) != 200,
                   float(d.get("latency_ms", 0.0))),
    reverse=True,
)[:5]
if not worst:
    sys.exit("gateway_smoke FAILED (act 5): no journal persisted")
for doc in worst:
    label = f"trace {doc.get('trace_id')}"
    if doc.get("decided_by") not in DECIDED:
        sys.exit(f"gateway_smoke FAILED (act 5): {label} decided_by="
                 f"{doc.get('decided_by')!r} is not a known stage")
    if int(doc["status"]) != 200 and not doc.get("cause"):
        sys.exit(f"gateway_smoke FAILED (act 5): {label} shed with an "
                 "empty cause")
    gap = abs(obs_requests.level0_sum_ms(doc) - float(doc["latency_ms"]))
    if gap > 0.01:
        sys.exit(f"gateway_smoke FAILED (act 5): {label} level-0 sum "
                 f"misses latency by {gap:.4f} ms")
if not any(
    h.get("stage") == obs_requests.STAGE_ATTEMPT
    for d in docs for h in d.get("hops", ())
):
    sys.exit("gateway_smoke FAILED (act 5): no fleet.attempt hop in any "
             "journal — fleet-level tracing is dark")
print(f"gateway_smoke act 5 scene A OK: {len(docs)} journals persisted, "
      "worst-5 waterfalls sum to their latencies and name their stages")

# ---- scene B: on/off A/B on a clean fleet (no chaos)
obs_requests.disarm()
fleet, router, gateway = build_fleet(2)
client = GatewayClient(
    f"http://127.0.0.1:{gateway.port}", tenant="gold", deadline_ms=2000,
    retries=0,
)


def median_latency_ms(n=150, warmup=20):
    obs = np.zeros((2, 4), np.float32)
    lat = []
    for i in range(n + warmup):
        t0 = time.perf_counter()
        try:
            client.act(obs)
        except GatewayShed:
            continue
        dt = 1e3 * (time.perf_counter() - t0)
        if i >= warmup:
            lat.append(dt)
    if not lat:
        sys.exit("gateway_smoke FAILED (act 5 A/B): nothing served")
    return float(np.median(np.asarray(lat)))


try:
    p50_off = median_latency_ms()
    obs_requests.arm(run_dir=run_dir, meta={"smoke": "gateway_act5_ab"})
    p50_on = median_latency_ms()
finally:
    gateway.stop()
    router.close()
    fleet.close()
    obs_requests.disarm()

ratio = p50_on / max(p50_off, 1e-9)
print(f"gateway_smoke act 5 scene B: p50 off={p50_off:.2f}ms "
      f"on={p50_on:.2f}ms ratio={ratio:.3f}x (bar {ab_max:.2f}x)")
if ratio > ab_max:
    sys.exit(f"gateway_smoke FAILED (act 5 A/B): journaling costs "
             f"{ratio:.3f}x on the serving path (bar {ab_max:.2f}x)")
print("gateway_smoke act 5 OK: traced kill-run journals gate, tracing "
      "overhead inside the noise bar")

if record:
    from asyncrl_tpu.utils import bench_history

    entry = bench_history.record({
        "kind": "observability",
        "probe": "request_trace_ab",
        "preset": "fleet(standalone)",
        **bench_history.device_entry(),
        "qps_offered": qps,
        "p50_off_ms": round(p50_off, 3),
        "p50_on_ms": round(p50_on, 3),
        "trace_overhead_x": round(ratio, 4),
        "ab_bar_x": ab_max,
        "journals_persisted": len(docs),
    })
    print("gateway_smoke: recorded", entry["ts"])
EOF

echo "gateway_smoke OK: all five acts green"
