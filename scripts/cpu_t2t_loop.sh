#!/bin/bash
# CPU arm of the 18.0-Pong time-to-target hunt: supervised, resumable
# sessions pinned to the CPU backend (ASYNCRL_FORCE_CPU — never steals a
# TPU window from scripts/tpu_window.sh; provenance stays platform=cpu).
# Sessions checkpoint + accumulate wall clock; the loop exits when the
# run records ANY time_to_target completion for this dir's preset (the
# in-run budget decides reached true/false) or MAX_SESSIONS spend out.
#
#   nohup bash scripts/cpu_t2t_loop.sh [checkpoint_dir] [extra overrides...] &
set -u
cd "$(dirname "$0")/.."
# Recipe-tagged default dir: resuming an OLD-recipe checkpoint dir would
# silently credit its accumulated clock/optimizer state to the pong_t2t
# label. Pass an explicit dir only to continue a same-recipe run.
DIR=${1:-runs/pong18_cpu_t2t}
shift || true
export ASYNCRL_FORCE_CPU=1
export BENCH_NO_WAIT=1

for i in $(seq 1 "${MAX_SESSIONS:-12}"); do
  echo "=== $(date -u +%FT%TZ) cpu t2t session $i ($DIR)"
  # Same committed pong_t2t recipe as the TPU arm (configs/presets.py) so
  # the two arms stay comparable; only dispatch fusing differs (K=8: at
  # CPU speeds a K=32 call would outlive the metric window).
  timeout -k 10 "${SESSION_SECONDS:-3600}" \
    python scripts/run_to_target.py pong_t2t \
      --target 18.0 --budget-seconds "${BUDGET_SECONDS:-14400}" \
      checkpoint_dir="$DIR" checkpoint_every=50 \
      updates_per_call=8 total_env_steps=2000000000 "$@"
  rc=$?
  echo "=== rc=$rc session $i"
  # Relaunch ONLY on a timeout-kill (the session clock expired mid-run:
  # resume next session). Any other exit means the measurement is settled
  # — rc=0 reached, rc=1 budget-exhausted reached=false, rc=3 refused
  # (already complete) — and relaunching would append one duplicate
  # reached=false ledger row per leftover session.
  if [ "$rc" -ne 124 ] && [ "$rc" -ne 137 ]; then break; fi
  sleep 5
done
