#!/bin/bash
# CPU arm of the 18.0-Pong time-to-target hunt: the committed pong_t2t
# recipe at CPU-feasible dispatch fusing (K=8: at CPU speeds a K=32 call
# would outlive the metric window). Provenance stays platform=cpu; the
# session supervision (resume loop + SIGSTOP-yielding the single core to
# TPU windows) lives in cpu_probe_loop.sh.
#
#   nohup bash scripts/cpu_t2t_loop.sh [checkpoint_dir] [extra overrides...] &
set -u
# Recipe-tagged default dir: resuming an OLD-recipe checkpoint dir would
# silently credit its accumulated clock/optimizer state to the pong_t2t
# label. Pass an explicit dir only to continue a same-recipe run.
DIR=${1:-runs/pong18_cpu_t2t}
shift || true
# This arm's wall clock IS the measurement: yield by clean termination
# (clock-honest), never SIGSTOP (which would credit pause time).
export YIELD_MODE=term
export SESSION_SECONDS=${SESSION_SECONDS:-3600}
export BUDGET_SECONDS=${BUDGET_SECONDS:-14400}
export MAX_SESSIONS=${MAX_SESSIONS:-12}
exec bash "$(dirname "$0")/cpu_probe_loop.sh" pong_t2t "$DIR" \
  updates_per_call=8 total_env_steps=2000000000 "$@"
