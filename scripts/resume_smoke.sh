#!/usr/bin/env bash
# Resume smoke: the operator-facing gate for durable runs
# (asyncrl_tpu/runtime/durability.py), in three acts:
#
#   1. CONTROL — an uninterrupted run to the target (the A side of the
#      A/B; also the in-process JIT warm-up for the timed acts).
#   2. PREEMPT + RESUME — the same run in a child process is killed with
#      a real `kill -TERM` mid-train; the gate asserts the child exited
#      with the distinct EXIT_DRAINED code (86 — the drain completed and
#      the final checkpoint is durable), then resumes it via
#      ASYNCRL_RESUME=1 (the no-code-change knob) to the SAME target,
#      gating on: completion, update counters monotone across the
#      boundary, ONE continuous timeseries (second meta segment, resume
#      marker, env_steps never regressing, the drain's partial-window
#      flush stamped drain_preempt), finite losses, and /healthz — read
#      over HTTP from the live endpoint — ok at the end.
#   3. ROLLBACK — an injected nonfinite-loss burst (corrupt chaos kind)
#      must trigger the quarantine→rollback path and the run must return
#      to /healthz ok and a finite loss WITHOUT human intervention.
#
# ASYNCRL_SMOKE_RECORD=1 appends a kind="robustness" probe="resume_ab"
# row to BENCH_HISTORY.json with the control-vs-resumed fps and the
# drain/rollback evidence.
#
# Usage: scripts/resume_smoke.sh                  # CPU, ~3 min
#        ASYNCRL_SMOKE_UPDATES=48 scripts/resume_smoke.sh
#        ASYNCRL_SMOKE_RECORD=1 scripts/resume_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
# The preempt child runs from a script file in $OUT_DIR, so the repo
# root must be on sys.path explicitly (nothing installs the package).
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
UPDATES="${ASYNCRL_SMOKE_UPDATES:-24}"
RECORD="${ASYNCRL_SMOKE_RECORD:-0}"
OUT_DIR="$(mktemp -d)"
trap 'rm -rf "$OUT_DIR"' EXIT

# ---------------------------------------------------------------- act 1
# Control: the uninterrupted A side (doubles as the JIT warm-up).
python - "$UPDATES" "$OUT_DIR" <<'EOF'
import json
import sys
import time

import numpy as np

from asyncrl_tpu import make_agent
from asyncrl_tpu.utils.config import Config

updates, out_dir = int(sys.argv[1]), sys.argv[2]
NUM_ENVS, UNROLL = 16, 8
steps = updates * NUM_ENVS * UNROLL

cfg = Config(
    env_id="CartPole-v1", algo="impala", backend="sebulba",
    host_pool="jax", num_envs=NUM_ENVS, actor_threads=2,
    unroll_len=UNROLL, precision="f32", log_every=4, seed=3,
)
agent = make_agent(cfg)
try:
    t0 = time.perf_counter()
    history = agent.train(total_env_steps=steps)
    elapsed = time.perf_counter() - t0
    if not np.isfinite(history[-1]["loss"]):
        sys.exit("resume_smoke FAILED: control run loss went non-finite")
    control = {
        "fps": steps / elapsed,
        "updates": agent._updates,
        "final_loss": float(history[-1]["loss"]),
    }
finally:
    agent.close()
with open(f"{out_dir}/control.json", "w") as f:
    json.dump(control, f)
print(f"resume_smoke: control run {control['updates']} updates, "
      f"{control['fps']:,.0f} fps")
EOF

# ---------------------------------------------------------------- act 2
# Preempt: a child process killed with a REAL SIGTERM mid-train must
# drain (exit 86), then resume to the same target.
RUN_DIR="$OUT_DIR/run"
CK_DIR="$OUT_DIR/ck"
cat > "$OUT_DIR/train_child.py" <<'EOF'
import sys

from asyncrl_tpu import make_agent
from asyncrl_tpu.utils.config import Config

steps, ck_dir, run_dir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
cfg = Config(
    env_id="CartPole-v1", algo="impala", backend="sebulba",
    host_pool="jax", num_envs=16, actor_threads=2, unroll_len=8,
    precision="f32", log_every=4, seed=3,
    checkpoint_dir=ck_dir, checkpoint_every=4,
    run_dir=run_dir, obs_http_port=-1,
    # This 1-core box's scheduler noise must not degrade the verdict the
    # resumed run is gated on (the gate is about the DRAIN protocol).
    health_stall_frac=1.0, health_fps_collapse=0.0,
    drain_grace_s=60.0,
)
agent = make_agent(cfg)
try:
    agent.train(total_env_steps=steps)  # SIGTERM raises PreemptedExit
finally:
    agent.close()
print("resume_smoke child: ran to completion (was never preempted)")
EOF

STEPS=$((UPDATES * 16 * 8))
python "$OUT_DIR/train_child.py" "$STEPS" "$CK_DIR" "$RUN_DIR" &
CHILD=$!
# Kill once the run is genuinely mid-train: the first periodic
# checkpoint manifest proves updates are flowing.
DEADLINE=$((SECONDS + 240))
until compgen -G "$CK_DIR/manifest-*.json" > /dev/null; do
    if ! kill -0 "$CHILD" 2>/dev/null || ((SECONDS > DEADLINE)); then
        echo "resume_smoke FAILED: child never reached its first checkpoint"
        exit 1
    fi
    sleep 0.5
done
sleep 1
kill -TERM "$CHILD"
set +e
wait "$CHILD"
RC=$?
set -e
if [[ "$RC" != 86 ]]; then
    echo "resume_smoke FAILED: preempted child exited $RC, expected the"
    echo "EXIT_DRAINED code 86 (drain completed, final checkpoint durable)"
    exit 1
fi
echo "resume_smoke: SIGTERM'd child drained and exited 86"

# Resume via the env knob to the SAME target; gate in-process.
ASYNCRL_RESUME=1 python - "$STEPS" "$CK_DIR" "$RUN_DIR" "$OUT_DIR" <<'EOF'
import json
import sys
import time
import urllib.request

import numpy as np

from asyncrl_tpu import make_agent
from asyncrl_tpu.utils.config import Config

steps, ck_dir, run_dir, out_dir = (
    int(sys.argv[1]), sys.argv[2], sys.argv[3], sys.argv[4])
cfg = Config(
    env_id="CartPole-v1", algo="impala", backend="sebulba",
    host_pool="jax", num_envs=16, actor_threads=2, unroll_len=8,
    precision="f32", log_every=4, seed=3,
    checkpoint_dir=ck_dir, checkpoint_every=4,
    run_dir=run_dir, obs_http_port=-1,
    health_stall_frac=1.0, health_fps_collapse=0.0,
    drain_grace_s=60.0,
)
agent = make_agent(cfg)
try:
    run_state = (agent._ckpt.restore_meta or {}).get("run_state")
    if not run_state:
        sys.exit("resume_smoke FAILED: drained checkpoint carried no "
                 "run_state metadata")
    restored = int(run_state["updates"])
    if restored < 1:
        sys.exit("resume_smoke FAILED: resumed at zero updates")
    restored_env_steps = agent.env_steps
    t0 = time.perf_counter()
    history = agent.train(total_env_steps=steps)
    elapsed = time.perf_counter() - t0
    if agent.env_steps < steps:
        sys.exit("resume_smoke FAILED: resumed run stopped short of the "
                 f"target ({agent.env_steps} < {steps})")
    if agent._updates <= restored:
        sys.exit("resume_smoke FAILED: update counter did not advance "
                 "monotonically across the resume boundary")
    losses = [h["loss"] for h in history]
    if not np.all(np.isfinite(losses)):
        sys.exit("resume_smoke FAILED: non-finite loss after resume")
    url = f"http://127.0.0.1:{agent._obs.http.port}/healthz"
    verdict = json.load(urllib.request.urlopen(url, timeout=5))
    if verdict["status"] != "ok":
        sys.exit(f"resume_smoke FAILED: /healthz not ok after resume: "
                 f"{verdict}")
    resumed = {
        "fps": (steps - restored_env_steps) / elapsed,
        "updates_restored": restored,
        "updates_final": agent._updates,
        "final_loss": float(losses[-1]),
    }
finally:
    agent.close()

# One continuous timeseries: two meta segments (preempted + resumed),
# exactly one resume marker, env_steps monotone, and the drain's final
# partial-window flush stamped drain_preempt.
metas = resumes = preempt_flushes = 0
env_steps_series = []
with open(f"{run_dir}/timeseries.jsonl") as f:
    for line in f:
        doc = json.loads(line)
        if doc.get("kind") == "meta":
            metas += 1
        elif doc.get("kind") == "sample":
            window = doc["window"]
            env_steps_series.append(window.get("env_steps", 0.0))
            if window.get("drain_preempt"):
                preempt_flushes += 1
        elif (doc.get("kind") == "event"
                and doc.get("event", {}).get("event_type") == "resume"):
            resumes += 1
if metas != 2 or resumes != 1 or preempt_flushes != 1:
    sys.exit(f"resume_smoke FAILED: timeseries segments malformed "
             f"(metas={metas}, resume_markers={resumes}, "
             f"drain_flushes={preempt_flushes})")
if env_steps_series != sorted(env_steps_series):
    sys.exit("resume_smoke FAILED: env_steps regressed across the resume "
             "boundary — counters are not monotone")
print(f"resume_smoke: resumed {restored} -> {resumed['updates_final']} "
      "updates, timeseries continuous, /healthz ok")
with open(f"{out_dir}/resumed.json", "w") as f:
    json.dump(resumed, f)
EOF

# ---------------------------------------------------------------- act 3
# Rollback: an injected nonfinite-loss burst must quarantine, roll back
# to the last-good checkpoint, and return to /healthz ok on its own.
python - "$UPDATES" "$OUT_DIR" <<'EOF'
import json
import sys
import urllib.request

import numpy as np

from asyncrl_tpu import make_agent
from asyncrl_tpu.obs import registry as obs_registry
from asyncrl_tpu.utils.config import Config

updates, out_dir = int(sys.argv[1]), sys.argv[2]
NUM_ENVS, UNROLL = 16, 4
steps = max(updates, 26) * NUM_ENVS * UNROLL

cfg = Config(
    env_id="CartPole-v1", algo="a3c", backend="sebulba",
    host_pool="jax", num_envs=NUM_ENVS, actor_threads=2,
    unroll_len=UNROLL, precision="f32", log_every=2, seed=3,
    checkpoint_dir=f"{out_dir}/rollback_ck", checkpoint_every=2,
    rollback_bad_windows=2, rollback_max_attempts=3,
    obs_http_port=-1, health_stall_frac=1.0, health_fps_collapse=0.0,
    fault_spec="actor.queue_put:corrupt:1.0:0:max=12,after=16",
)
agent = make_agent(cfg)
try:
    history = agent.train(total_env_steps=steps)
    last = history[-1]
    restores = obs_registry.counter("rollback_restores").value()
    quarantines = obs_registry.counter("rollback_quarantine").value()
    skips = last.get("nonfinite_skips", 0.0)
    if restores < 1:
        sys.exit("resume_smoke FAILED: injected divergence never rolled "
                 "back")
    if quarantines < 1:
        sys.exit("resume_smoke FAILED: divergence was not quarantined "
                 "before the rollback")
    if skips < 1:
        sys.exit("resume_smoke FAILED: the NaN-guard never skipped a "
                 "poisoned update")
    if not np.isfinite(last["loss"]):
        sys.exit("resume_smoke FAILED: loss still non-finite after the "
                 "rollback recovered")
    url = f"http://127.0.0.1:{agent._obs.http.port}/healthz"
    verdict = json.load(urllib.request.urlopen(url, timeout=5))
    if verdict["status"] != "ok":
        sys.exit(f"resume_smoke FAILED: /healthz did not recover after "
                 f"the rollback: {verdict}")
    print(f"resume_smoke: rollback probe — {int(restores)} restore(s), "
          f"{int(skips)} NaN-guard skip(s), /healthz ok")
    rollback = {"restores": int(restores), "nan_skips": int(skips)}
finally:
    agent.close()
with open(f"{out_dir}/rollback.json", "w") as f:
    json.dump(rollback, f)
EOF

# --------------------------------------------------------------- ledger
python - "$UPDATES" "$OUT_DIR" "$RECORD" <<'EOF'
import json
import sys

updates, out_dir, record = sys.argv[1], sys.argv[2], sys.argv[3]
control = json.load(open(f"{out_dir}/control.json"))
resumed = json.load(open(f"{out_dir}/resumed.json"))
rollback = json.load(open(f"{out_dir}/rollback.json"))
print(
    f"resume_smoke OK: control {control['fps']:,.0f} fps / "
    f"{control['updates']} updates; preempted run resumed "
    f"{resumed['updates_restored']} -> {resumed['updates_final']} updates; "
    f"rollback probe {rollback['restores']} restore(s)"
)
if record not in ("", "0"):
    from asyncrl_tpu.utils import bench_history

    entry = bench_history.record({
        "kind": "robustness",
        "probe": "resume_ab",
        "preset": "cartpole_impala(sebulba tiny)",
        **bench_history.device_entry(),
        "updates": int(updates),
        "fps_control": round(control["fps"]),
        "fps_resumed": round(resumed["fps"]),
        "updates_restored": resumed["updates_restored"],
        "updates_final": resumed["updates_final"],
        "rollback_restores": rollback["restores"],
        "nan_guard_skips": rollback["nan_skips"],
        "healthz": "ok",
    })
    print("resume_smoke: recorded", entry["ts"])
EOF
