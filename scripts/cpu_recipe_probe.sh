#!/bin/bash
# CPU learnability probe for the pixel-path RECIPE ECONOMICS (round 5).
#
# Why not probe the pixel preset itself on CPU: the IMPALA-CNN costs
# ~540 MFLOP per env frame end to end (docs/MFU.md FLOP ledger), so one
# CPU core sustains only ~100-300 pixel fps — an overnight run is
# 10-40M frames, far below where pixel-Pong shows any learning signal.
# A CPU pixel probe is uninformative either way (tried 2026-07-31;
# session produced no measurable window in 10 minutes).
#
# What IS CPU-testable overnight: the part of pong_pixels_t2t that is
# NEW relative to the proven vector recipe — the skip-4 episode
# economics (gamma 0.995^4~=0.98, step_cost 0.01x4=0.04, ALE cap under
# frame_skip=4). This probe runs those economics on the VECTOR env
# (same game dynamics, 6-dim obs, MLP torso) at vector speeds (~50k
# fps/core -> 1.5B+ frames overnight). Judgment: compare
# runs/pong18_skip4_cpu/metrics.jsonl env_steps-vs-return against the
# proven skip-1 vector trajectory (runs/pong18_tpu, which crossed ~0
# return around 1-2B decisions) — per-CORE-FRAME learning efficiency
# should be comparable (1 skip-4 decision = 4 core frames); stagnation
# far below that line falsifies the re-derived gamma/step_cost before
# they cost a chip window. The CNN-representation question remains
# chip-gated either way.
# STATUS: the original arm (runs/pong18_skip4_cpu) SETTLED reached=true
# on 2026-08-01 via the coarse-to-fine path (skip-4 training + skip-1
# finish after the preset's revert — see runs/README.md). Rerunning this
# script against that dir refuses (completed measurement); use a fresh
# dir for a new experiment. The skip-4 knobs are now explicit overrides
# (the preset reverted to skip-1), so this script keeps meaning what its
# header says regardless of preset evolution.
#
#   nohup bash scripts/cpu_recipe_probe.sh > /tmp/cpu_recipe_probe.log 2>&1 &
set -u
exec bash "$(dirname "$0")/cpu_probe_loop.sh" \
  pong_pixels_t2t "${1:-runs/pong18_skip4_cpu}" \
  env_id=JaxPong-v0 torso=mlp frame_pool=false \
  frame_skip=4 gamma=0.98 step_cost=0.04 \
  num_envs=256 grad_accum=1 remat=false updates_per_call=8 \
  learning_rate=1.5e-4 eval_every=200 eval_episodes=8
