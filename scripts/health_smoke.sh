#!/usr/bin/env bash
# Health smoke: the operator-facing gate for the run-health telemetry
# layer (obs/timeseries.py, obs/health.py, obs/http.py, obs doctor).
#
# Three checks, driven through the public config surface the way a
# cluster health probe would drive it:
#
#   1. LIVE DEGRADE/RECOVER — a short traced run with a crash storm
#      injected via utils/faults.py (both actors' first step) and the
#      exposition endpoint on an ephemeral port: /healthz must answer
#      503/degraded-or-critical while the storm is inside the verdict
#      TTL and 200/ok again after it ages out; /metrics must scrape in
#      Prometheus format mid-run.
#   2. DOCTOR CLEAN — `python -m asyncrl_tpu.obs doctor` over a clean
#      recorded run_dir, compared against a ledger row at the run's own
#      measured throughput: must exit 0.
#   3. DOCTOR REGRESSION — the same run against an induced 100x-higher
#      baseline row: must exit nonzero and say REGRESSED.
#
# The doctor checks run against a TEMP ledger (ASYNCRL_BENCH_HISTORY
# redirect) so smoke rows never enter the committed evidence trail.
#
# Usage: scripts/health_smoke.sh                    # CPU, ~1-2 min
#        ASYNCRL_SMOKE_UPDATES=64 scripts/health_smoke.sh
#        ASYNCRL_SMOKE_RECORD=1 scripts/health_smoke.sh  # append the
#          result as a kind="observability" probe="health_smoke" row to
#          BENCH_HISTORY.json
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
UPDATES="${ASYNCRL_SMOKE_UPDATES:-24}"
RECORD="${ASYNCRL_SMOKE_RECORD:-0}"
WORK_DIR="$(mktemp -d /tmp/health_smoke.XXXXXX)"
trap 'rm -rf "$WORK_DIR"' EXIT

python - "$UPDATES" "$RECORD" "$WORK_DIR" <<'EOF'
import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

from asyncrl_tpu import make_agent
from asyncrl_tpu.utils.config import Config

updates = int(sys.argv[1])
record = sys.argv[2] not in ("", "0")
work_dir = sys.argv[3]


def get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def run(run_dir, fault_spec, scrape):
    cfg = Config(
        env_id="CartPole-v1", algo="a3c", backend="sebulba",
        host_pool="jax", num_envs=16, actor_threads=2, unroll_len=4,
        precision="f32", log_every=2, seed=7,
        trace=True, run_dir=run_dir, obs_http_port=(-1 if scrape else 0),
        # The degrade/recover assertion is about the CRASH STORM verdict;
        # this run's windows are ~100ms, where a scheduler hiccup halves
        # fps and fires the (orthogonal) fps_collapse detector — a dip
        # landing on the final windows read as "never recovered". 0
        # disables that one detector so the gate tests what it claims.
        health_fps_collapse=0.0,
        health_window_ttl=2, fault_spec=fault_spec,
    )
    agent = make_agent(cfg)
    statuses = []

    def cb(window):
        if not scrape:
            return
        base = f"http://127.0.0.1:{agent._obs.http.port}"
        code, body = get(f"{base}/healthz")
        statuses.append((code, json.loads(body)["status"]))
        if len(statuses) == 1:
            code, body = get(f"{base}/metrics")
            assert code == 200 and b"# TYPE asyncrl_fps gauge" in body, (
                "health_smoke FAILED: /metrics did not scrape in "
                "Prometheus format"
            )

    steps = updates * 16 * 4
    try:
        history = agent.train(total_env_steps=steps, callback=cb)
    finally:
        agent.close()
    return history, statuses


# --- 1. live degrade/recover under an injected crash storm -----------
faulted_dir = os.path.join(work_dir, "faulted")
history, statuses = run(
    faulted_dir, "actor.step:crash:1:0:max=2", scrape=True
)
print(f"health_smoke: /healthz over {len(statuses)} windows: "
      f"{[s for _, s in statuses]}")
bad = [i for i, (code, s) in enumerate(statuses) if s != "ok"]
if not bad:
    sys.exit(
        "health_smoke FAILED: /healthz never degraded under the "
        "injected crash storm"
    )
if statuses[bad[0]][0] != 503:
    sys.exit("health_smoke FAILED: degraded verdict did not answer 503")
if not any(s == "ok" for code, s in statuses[bad[-1] + 1:]):
    sys.exit(
        "health_smoke FAILED: /healthz never recovered after the storm "
        f"aged out (statuses {statuses})"
    )
if not history[0].get("health_events"):
    sys.exit(
        "health_smoke FAILED: the storm window's sample carries no "
        "health_events (shared-snapshot drift?)"
    )
print("health_smoke: live degrade/recover OK "
      f"(degraded windows {bad}, recovered after)")

# --- 2+3. doctor verdicts against a temp ledger ----------------------
clean_dir = os.path.join(work_dir, "clean")
history, _ = run(clean_dir, "", scrape=False)
run_fps = max(w["fps"] for w in history)

ledger = os.path.join(work_dir, "bench_history.json")
env = dict(os.environ, ASYNCRL_BENCH_HISTORY=ledger)


def doctor(tag):
    proc = subprocess.run(
        [sys.executable, "-m", "asyncrl_tpu.obs", "doctor", clean_dir],
        env=env, capture_output=True, text=True,
    )
    print(f"health_smoke: doctor ({tag}) rc={proc.returncode}")
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    return proc


with open(ledger, "w") as f:
    json.dump([{
        "ts": "health-smoke", "kind": "throughput",
        "preset": "cartpole_a3c", "platform": "cpu",
        "frames_per_sec": round(run_fps),
    }], f)
proc = doctor("clean baseline")
if proc.returncode != 0 or "CLEAN" not in proc.stdout:
    sys.exit("health_smoke FAILED: doctor flagged a clean run")

with open(ledger, "w") as f:
    json.dump([{
        "ts": "health-smoke", "kind": "throughput",
        "preset": "cartpole_a3c", "platform": "cpu",
        "frames_per_sec": round(run_fps * 100),
    }], f)
proc = doctor("induced regression")
if proc.returncode == 0 or "REGRESSED" not in proc.stdout:
    sys.exit(
        "health_smoke FAILED: doctor did not flag an induced 100x fps "
        "regression"
    )

print(f"health_smoke OK: degrade/recover + doctor verdicts "
      f"(clean fps {run_fps:,.0f})")

if record:
    from asyncrl_tpu.utils import bench_history

    entry = bench_history.record({
        "kind": "observability",
        "probe": "health_smoke",
        "preset": "cartpole_a3c(sebulba tiny)",
        **bench_history.device_entry(),
        "updates": updates,
        "fps": round(run_fps),
        "healthz_degraded_windows": len(bad),
        "doctor_clean_rc": 0,
        "doctor_regression_rc": 1,
    })
    print("health_smoke: recorded", entry["ts"])
EOF
