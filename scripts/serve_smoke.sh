#!/usr/bin/env bash
# Serve smoke: latency/throughput A/B of the continuous-batching serve
# core (config.serve=True, asyncrl_tpu/serve/) against the legacy
# coalescing InferenceServer (serve=False) on a short CPU sebulba run.
# Gates:
#   - throughput: the serve core must not be slower than the legacy
#     server beyond ASYNCRL_SERVE_TOLERANCE (default 1.10 — this 1-core
#     box's scheduler noise swings identical configs run to run, see
#     perf_smoke.sh; the strict comparison belongs on quiet hardware),
#   - latency: the serve core's p95 serve latency must stay within
#     ASYNCRL_SERVE_P95_MS (default 250 ms — generous for a shared CI
#     box; tighten on real serving hardware),
#   - liveness: the serve run must export p50/p95/p99 latency and at
#     least one dispatch through the metrics window.
#
# Same measurement discipline as trace_smoke.sh: discard a process
# warm-up run, then alternate legacy/serve and take best-of-N per mode.
#
# Usage: scripts/serve_smoke.sh                    # CPU, ~1-2 min
#        ASYNCRL_SMOKE_UPDATES=64 scripts/serve_smoke.sh
#        ASYNCRL_SERVE_TOLERANCE=1.20 scripts/serve_smoke.sh  # noisy box
#        ASYNCRL_SMOKE_RECORD=1 scripts/serve_smoke.sh  # append the A/B as
#          a kind="serving" probe="serve_ab" row to BENCH_HISTORY.json
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
UPDATES="${ASYNCRL_SMOKE_UPDATES:-24}"
TOLERANCE="${ASYNCRL_SERVE_TOLERANCE:-1.10}"
P95_BUDGET_MS="${ASYNCRL_SERVE_P95_MS:-250}"
RECORD="${ASYNCRL_SMOKE_RECORD:-0}"

python - "$UPDATES" "$TOLERANCE" "$P95_BUDGET_MS" "$RECORD" <<'EOF'
import sys
import time

from asyncrl_tpu import make_agent
from asyncrl_tpu.configs import presets

updates, tolerance = int(sys.argv[1]), float(sys.argv[2])
p95_budget_ms = float(sys.argv[3])
record = sys.argv[4] not in ("", "0")
NUM_ENVS, UNROLL, THREADS = 16, 16, 2
steps = updates * NUM_ENVS * UNROLL


def run(serve: bool):
    cfg = presets.get("pong_impala").replace(
        backend="sebulba", host_pool="jax", num_envs=NUM_ENVS,
        actor_threads=THREADS, unroll_len=UNROLL, precision="f32",
        log_every=4, seed=3, hidden_sizes=(64, 64),
        actor_staleness=1_000_000, inference_server=True, serve=serve,
    )
    agent = make_agent(cfg)
    try:
        agent.train(total_env_steps=NUM_ENVS * UNROLL)  # jit warm-up
        t0 = time.perf_counter()
        history = agent.train(total_env_steps=NUM_ENVS * UNROLL + steps)
        elapsed = time.perf_counter() - t0
    finally:
        agent.close()
    fps = steps / elapsed
    last = history[-1]
    label = "serve-core" if serve else "legacy    "
    lat = {
        q: float(last.get(f"serve_latency_ms_{q}", 0.0))
        for q in ("p50", "p95", "p99")
    }
    if serve:
        print(
            f"serve_smoke {label}: fps={fps:12,.0f}  "
            f"p50={lat['p50']:.1f}ms p95={lat['p95']:.1f}ms "
            f"p99={lat['p99']:.1f}ms  "
            f"dispatch_full={int(last.get('serve_dispatch_full', 0))} "
            f"deadline={int(last.get('serve_dispatch_deadline', 0))}"
        )
    else:
        print(f"serve_smoke {label}: fps={fps:12,.0f}")
    return fps, last, lat


# Best-of-N per mode, alternating (the perf_smoke/trace_smoke discipline
# for this 1-core box's scheduler noise).
run(True)  # discarded process warm-up
fps_legacy, _, _ = run(False)
fps_serve, last_serve, lat = run(True)
for _ in range(2):
    f, _, _ = run(False)
    fps_legacy = max(fps_legacy, f)
    f, cand_last, cand_lat = run(True)
    if f > fps_serve:
        fps_serve, last_serve, lat = f, cand_last, cand_lat

# Liveness gate: the serve run must have exported the latency taxonomy
# and dispatched through the continuous-batching scheduler.
for key in ("serve_latency_ms_p50", "serve_latency_ms_p95",
            "serve_latency_ms_p99"):
    if key not in last_serve:
        sys.exit(f"serve_smoke FAILED: {key} missing from metrics window")
dispatches = last_serve.get("serve_dispatch_full", 0) + last_serve.get(
    "serve_dispatch_deadline", 0
)
if dispatches <= 0:
    sys.exit("serve_smoke FAILED: serve core recorded no dispatches")

if lat["p95"] > p95_budget_ms:
    sys.exit(
        f"serve_smoke FAILED: p95 serve latency {lat['p95']:.1f}ms over "
        f"budget {p95_budget_ms:.0f}ms"
    )
if fps_serve * tolerance < fps_legacy:
    sys.exit(
        f"serve_smoke FAILED: serve core slower than legacy beyond budget "
        f"({fps_serve:,.0f} vs {fps_legacy:,.0f} fps, tolerance "
        f"{tolerance}x)"
    )
print(
    f"serve_smoke OK: serve {fps_serve:,.0f} fps vs legacy "
    f"{fps_legacy:,.0f} fps ({fps_serve / fps_legacy:.3f}x, budget "
    f"{tolerance}x); p95 {lat['p95']:.1f}ms <= {p95_budget_ms:.0f}ms"
)

if record:
    from asyncrl_tpu.utils import bench_history

    entry = bench_history.record({
        "kind": "serving",
        "probe": "serve_ab",
        "preset": "pong_impala(sebulba tiny)",
        **bench_history.device_entry(),
        "num_envs": NUM_ENVS,
        "actor_threads": THREADS,
        "unroll_len": UNROLL,
        "updates": updates,
        "fps_serve": round(fps_serve),
        "fps_legacy": round(fps_legacy),
        "serve_speedup": round(fps_serve / fps_legacy, 3),
        "serve_latency_ms_p50": round(lat["p50"], 2),
        "serve_latency_ms_p95": round(lat["p95"], 2),
        "serve_latency_ms_p99": round(lat["p99"], 2),
        "p95_budget_ms": p95_budget_ms,
    })
    print("serve_smoke: recorded", entry["ts"])
EOF
