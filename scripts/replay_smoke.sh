#!/usr/bin/env bash
# Replay smoke: the operator-facing gate for the device replay ring +
# IMPACT-mode learner (ISSUE 14; learn/replay.py), in two acts:
#
#   1. IDENTITY — replay_slabs=0 must be the pre-PR program: two
#      replay-off runs on a fixed seed (after a discarded in-process
#      warm-up, the elastic_smoke discipline) must be BIT-IDENTICAL on
#      losses, and neither run's windows may carry any replay key
#      (reuse_*, target_kl, replay_fill_frac, learner_stall_trend).
#   2. DUTY CYCLE — a replay-on run (same workload, same seed, same
#      fixed env-step budget) must drive learner_stall_frac STRICTLY
#      below the replay-off run's (the ISSUE-14 gate; the measured
#      reduction ratio is recorded — the acceptance target is >= 2x),
#      with the greedy eval return within noise of the off run's
#      (>= half; both recorded verbatim), and every window carrying the
#      replay telemetry.
#
# ASYNCRL_SMOKE_RECORD=1 appends a kind="perf" probe="replay_ab" row to
# BENCH_HISTORY.json with the stall fractions, reduction ratio, evals,
# and fps — and, because a throughput row should land with every perf
# probe (the ledger's freshness discipline), also runs
# `python bench.py pong_impala` for a fresh pong_impala row on this box.
#
# Usage: scripts/replay_smoke.sh                   # CPU, ~1-2 min
#        ASYNCRL_SMOKE_UPDATES=400 scripts/replay_smoke.sh
#        ASYNCRL_SMOKE_RECORD=1 scripts/replay_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
# Act 2's fixed env-step budget, in learner update-equivalents. The
# default is solve-scale for this box (~100k env steps, ~15-25s/run):
# below ~300 the greedy eval of a still-near-uniform policy is noise and
# the sample-efficiency comparison meaningless.
UPDATES="${ASYNCRL_SMOKE_UPDATES:-800}"
RECORD="${ASYNCRL_SMOKE_RECORD:-0}"
OUT_DIR="$(mktemp -d)"
trap 'rm -rf "$OUT_DIR"' EXIT

# ---------------------------------------------------------------- act 1
# Identity: replay off twice, fixed seed, bit-identical + zero keys.
python - "$OUT_DIR" <<'EOF'
import json
import sys

import numpy as np

from asyncrl_tpu import make_agent
from asyncrl_tpu.utils.config import Config

out_dir = sys.argv[1]
NUM_ENVS, UNROLL, UPDATES = 16, 8, 24
REPLAY_KEYS = (
    "replay_fill_frac", "reuse_p50", "reuse_p95", "reuse_max",
    "target_lag_mean", "target_kl", "learner_stall_trend",
)


def run():
    cfg = Config(
        env_id="CartPole-v1", algo="impala", backend="sebulba",
        host_pool="jax", num_envs=NUM_ENVS, actor_threads=1,
        unroll_len=UNROLL, precision="f32", log_every=4, seed=3,
        # Frozen behaviour params: losses must be seed-deterministic
        # for the identity assertion (no publish-timing race).
        actor_staleness=1_000_000,
    )
    agent = make_agent(cfg)
    try:
        history = agent.train(total_env_steps=UPDATES * NUM_ENVS * UNROLL)
        target_none = agent.state.target_params is None
    finally:
        agent.close()
    return history, target_none


run()  # discarded warm-up: both measured arms run on a warm jit cache
h1, t1 = run()
h2, t2 = run()
losses_a = np.asarray([h["loss"] for h in h1])
losses_b = np.asarray([h["loss"] for h in h2])
if not np.array_equal(losses_a, losses_b):
    sys.exit(
        "replay_smoke FAILED: replay-off losses diverged across two "
        "fixed-seed runs"
    )
leaked = sorted({k for h in h1 + h2 for k in h if k in REPLAY_KEYS})
if leaked:
    sys.exit(
        f"replay_smoke FAILED: replay-off run leaked {leaked} into the "
        "window snapshot"
    )
if not (t1 and t2):
    sys.exit(
        "replay_smoke FAILED: replay-off learner carries a target "
        "network (replay-shaped state was traced with the ring off)"
    )
print(
    f"replay_smoke act 1: replay-off bit-identical across "
    f"{len(losses_a)} windows, zero replay keys, no target net"
)
with open(f"{out_dir}/identity.json", "w") as f:
    json.dump({"windows": len(losses_a)}, f)
EOF

# ---------------------------------------------------------------- act 2
# Duty cycle: replay on vs off at the SAME fixed env-step budget.
python - "$UPDATES" "$OUT_DIR" <<'EOF'
import json
import sys
import time

import numpy as np

from asyncrl_tpu import make_agent
from asyncrl_tpu.utils.config import Config

updates, out_dir = int(sys.argv[1]), sys.argv[2]
NUM_ENVS, UNROLL = 16, 8
steps = updates * NUM_ENVS * UNROLL
REPLAY_KEYS = (
    "replay_fill_frac", "reuse_p50", "reuse_p95", "target_kl",
    "learner_stall_trend",
)


def run(budget=steps, **kw):
    cfg = Config(
        env_id="CartPole-v1", algo="impala", backend="sebulba",
        host_pool="jax", num_envs=NUM_ENVS, actor_threads=1,
        unroll_len=UNROLL, precision="f32", log_every=8, seed=3,
        actor_staleness=1, **kw,
    )
    agent = make_agent(cfg)
    try:
        t0 = time.perf_counter()
        history = agent.train(total_env_steps=budget)
        elapsed = time.perf_counter() - t0
        eval_return = agent.evaluate(num_episodes=32)
    finally:
        agent.close()
    stall = float(np.mean([h["learner_stall_frac"] for h in history]))
    return history, stall, eval_return, budget / elapsed


# Discarded warm-ups for BOTH arms (each act runs in its own process,
# and the two arms compile different programs): the measured runs must
# not pay jit-compile wall time into their stall/fps accounting.
tiny = 8 * NUM_ENVS * UNROLL
run(budget=tiny)
run(budget=tiny, replay_slabs=4, replay_passes=3, target_update_period=16)
hist_off, stall_off, eval_off, fps_off = run()
hist_on, stall_on, eval_on, fps_on = run(
    replay_slabs=4, replay_passes=3, target_update_period=16
)

missing = [k for k in REPLAY_KEYS if k not in hist_on[-1]]
if missing:
    sys.exit(
        f"replay_smoke FAILED: replay-on windows are missing {missing}"
    )
if not stall_on < stall_off:
    sys.exit(
        f"replay_smoke FAILED: learner_stall_frac did not drop under "
        f"replay (off {stall_off:.3f} vs on {stall_on:.3f})"
    )
ratio = stall_off / max(stall_on, 1e-9)
if not np.isfinite(eval_on) or eval_on < 0.5 * eval_off:
    sys.exit(
        f"replay_smoke FAILED: replay-on eval return regressed beyond "
        f"noise (off {eval_off:.1f} vs on {eval_on:.1f} at {steps} env "
        "steps)"
    )
print(
    f"replay_smoke act 2: stall {stall_off:.3f} -> {stall_on:.3f} "
    f"({ratio:.2f}x reduction; acceptance target >= 2x), eval "
    f"{eval_off:.1f} -> {eval_on:.1f} at {steps} fixed env steps, "
    f"reuse_p50 {hist_on[-1]['reuse_p50']:.1f}, fill "
    f"{hist_on[-1]['replay_fill_frac']:.2f}"
)
with open(f"{out_dir}/replay.json", "w") as f:
    json.dump({
        "env_steps": steps,
        "stall_off": stall_off,
        "stall_on": stall_on,
        "stall_reduction": ratio,
        "eval_off": eval_off,
        "eval_on": eval_on,
        "fps_off": fps_off,
        "fps_on": fps_on,
        "reuse_p50": hist_on[-1]["reuse_p50"],
        "reuse_p95": hist_on[-1]["reuse_p95"],
        "replay_fill_frac": hist_on[-1]["replay_fill_frac"],
    }, f)
EOF

# --------------------------------------------------------------- ledger
python - "$OUT_DIR" "$RECORD" <<'EOF'
import json
import sys

out_dir, record = sys.argv[1], sys.argv[2]
replay = json.load(open(f"{out_dir}/replay.json"))
print(
    f"replay_smoke OK: stall {replay['stall_off']:.3f} -> "
    f"{replay['stall_on']:.3f} ({replay['stall_reduction']:.2f}x), eval "
    f"{replay['eval_off']:.1f} -> {replay['eval_on']:.1f}, fps "
    f"{replay['fps_off']:,.0f} -> {replay['fps_on']:,.0f}"
)
if record not in ("", "0"):
    from asyncrl_tpu.utils import bench_history

    entry = bench_history.record({
        "kind": "perf",
        "probe": "replay_ab",
        "preset": "cartpole_impala(sebulba tiny, replay 4x3)",
        **bench_history.device_entry(),
        **replay,
        "notes": (
            "fixed-env-step A/B on this box: replay_slabs=4 "
            "replay_passes=3 target_update_period=16 vs replay off; "
            "stall = mean learner_stall_frac over the run"
        ),
    })
    print(f"replay_smoke: ledger row appended ({entry['ts']})")
EOF

# A perf probe should land next to a fresh throughput row (the ledger
# had none since 2026-08-03): bench.py self-records pong_impala.
if [ "$RECORD" != "0" ] && [ -n "$RECORD" ]; then
    python bench.py pong_impala
fi
