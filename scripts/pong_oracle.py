"""Feasibility oracle for the 18.0 Pong bar (VERDICT round 2, Missing #1).

The 18.0 mean-return target (BASELINE.json:2) is calibrated to sit ABOVE the
greedy-scripted ceiling (+14.8, tests/test_pong.py) — so before spending
wall-clock on long training runs, this script answers: can ANY policy
expressible from the 6-dim observation actually score >= 18 against the
standard tracker opponent?

It plays a one-ply lookahead oracle: while the ball approaches, enumerate
every paddle position reachable by contact time (the reachable set is the
0.05-step lattice around the current paddle y), simulate the full rally
forward with the EXACT env step math (ball advance, wall folds, paddle
bounce/spin, rate-limited tracker pursuit), and choose the contact point
whose return the tracker misses by the widest margin. This is not a
practical agent (63-way rollout sim per step) — it is an upper-bound probe
for learned play, and its per-decision structure (aim where the tracker
cannot arrive) is exactly what the RL agent must discover.

    python scripts/pong_oracle.py [games] [opponent]

Prints one JSON line: {"oracle_return": ..., "games": N, "opponent": ...}.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# The axon sitecustomize force-sets jax_platforms="axon,cpu" via jax.config,
# IGNORING the JAX_PLATFORMS env var (see tests/conftest.py) — and the axon
# client hangs indefinitely while its tunnel is down. This is a pure-analysis
# tool; CPU is always the right backend for it.
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from asyncrl_tpu.envs.pong import (
    AGENT_SPEED,
    AGENT_X,
    BALL_VX,
    MAX_SPIN,
    OPP_SPEED,
    OPP_X,
    PADDLE_HALF,
    PREDICTIVE_SPEED,
    Pong,
)

SIM_STEPS = 80  # > two court crossings at |vx| = 0.03 over 0.9 width
N_CANDIDATES = 63  # lattice offsets -31..31 around the current paddle y
DEADZONE = 0.026  # match reference_policy's hold band


def _sim_rally(ball, agent_y, opp_y, target, opp_speed):
    """Exact forward sim of one rally with the agent parked toward
    ``target``: returns (our_miss, opp_miss, margin) where margin is the
    |ball_y - opp_y| - PADDLE_HALF gap at the opponent-plane crossing
    (positive = the tracker cannot reach the return)."""

    def body(carry, _):
        ball, ay, oy, our_miss, opp_miss, margin, live = carry
        # Agent: move toward target at full speed (the executed policy's
        # own motion rule), hold inside the deadzone.
        dy = target - ay
        ay = jnp.clip(
            ay + jnp.where(jnp.abs(dy) > DEADZONE, jnp.sign(dy), 0.0) * AGENT_SPEED,
            PADDLE_HALF,
            1.0 - PADDLE_HALF,
        )
        # Tracker: rate-limited pursuit of the ball's current y.
        oy = jnp.clip(
            oy + jnp.clip(ball[1] - oy, -opp_speed, opp_speed),
            PADDLE_HALF,
            1.0 - PADDLE_HALF,
        )
        # Ball advance + wall fold (envs/pong.py step math).
        x = ball[0] + ball[2]
        y = ball[1] + ball[3]
        vx, vy = ball[2], ball[3]
        vy = jnp.where(y < 0.0, jnp.abs(vy), vy)
        y = jnp.where(y < 0.0, -y, y)
        vy = jnp.where(y > 1.0, -jnp.abs(vy), vy)
        y = jnp.where(y > 1.0, 2.0 - y, y)

        cross_agent = (x >= AGENT_X) & (vx > 0)
        cross_opp = (x <= OPP_X) & (vx < 0)
        agent_hit = cross_agent & (jnp.abs(y - ay) <= PADDLE_HALF)
        opp_hit = cross_opp & (jnp.abs(y - oy) <= PADDLE_HALF)

        our_miss = our_miss | (live & cross_agent & ~agent_hit)
        opp_miss = opp_miss | (live & cross_opp & ~opp_hit)
        margin = jnp.where(
            live & cross_opp, jnp.abs(y - oy) - PADDLE_HALF, margin
        )
        live = live & ~(cross_opp | (cross_agent & ~agent_hit))

        new_vx = jnp.where(
            agent_hit, -BALL_VX, jnp.where(opp_hit, BALL_VX, vx)
        )
        new_vy = jnp.where(
            agent_hit,
            MAX_SPIN * (y - ay) / PADDLE_HALF,
            jnp.where(opp_hit, MAX_SPIN * (y - oy) / PADDLE_HALF, vy),
        )
        new_x = jnp.where(
            agent_hit, 2.0 * AGENT_X - x, jnp.where(opp_hit, 2.0 * OPP_X - x, x)
        )
        ball = jnp.stack([new_x, y, new_vx, new_vy])
        return (ball, ay, oy, our_miss, opp_miss, margin, live), None

    init = (
        ball,
        agent_y,
        opp_y,
        jnp.asarray(False),
        jnp.asarray(False),
        jnp.float32(-1.0),
        jnp.asarray(True),
    )
    (_, _, _, our_miss, opp_miss, margin, _), _ = jax.lax.scan(
        body, init, None, length=SIM_STEPS
    )
    return our_miss, opp_miss, margin


def oracle_policy(obs: jax.Array, opp_speed: float) -> jax.Array:
    """One-ply lookahead: pick the reachable contact point whose return the
    tracker misses by the widest margin."""
    ball = jnp.stack(
        [obs[0], obs[1], obs[2] * BALL_VX, obs[3] * MAX_SPIN]
    )
    ay, oy = obs[4], obs[5]

    ks = jnp.arange(N_CANDIDATES, dtype=jnp.float32) - (N_CANDIDATES // 2)
    targets = jnp.clip(
        ay + AGENT_SPEED * ks, PADDLE_HALF, 1.0 - PADDLE_HALF
    )

    def score(target):
        our_miss, opp_miss, margin = _sim_rally(
            ball, ay, oy, target, opp_speed
        )
        return jnp.where(
            our_miss,
            -1e6 + margin,
            jnp.where(opp_miss, 1e3 + margin, margin),
        )

    scores = jax.vmap(score)(targets)
    best = targets[jnp.argmax(scores)]
    # Ball receding: park at the court center (serve-return readiness).
    target = jnp.where(ball[2] > 0, best, 0.5)
    dy = target - ay
    return jnp.where(
        dy > DEADZONE, 2, jnp.where(dy < -DEADZONE, 3, 0)
    ).astype(jnp.int32)


def play(env, policy_fn, n=32, seed=0, max_steps=3000):
    def one(key):
        st = env.init(key)

        def body(carry, k):
            st, total, done = carry
            obs = env.observe(st)
            a = policy_fn(obs, k)
            st2, ts = env.step(st, a, k)
            st2 = jax.tree.map(lambda n_, o: jnp.where(done, o, n_), st2, st)
            total = total + jnp.where(done, 0.0, ts.reward)
            return (st2, total, done | ts.done), None

        keys = jax.random.split(key, max_steps)
        (_, total, _), _ = jax.lax.scan(
            body, (st, 0.0, jnp.asarray(False)), keys
        )
        return total

    keys = jax.random.split(jax.random.PRNGKey(seed), n)
    return np.asarray(jax.jit(jax.vmap(one))(keys))


def main() -> int:
    games = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    opponent = sys.argv[2] if len(sys.argv) > 2 else "tracker"
    opp_speed = OPP_SPEED if opponent == "tracker" else PREDICTIVE_SPEED
    env = Pong(opponent)
    returns = play(
        env, lambda obs, k: oracle_policy(obs, opp_speed), n=games
    )
    out = {
        "oracle_return": round(float(returns.mean()), 2),
        "min": float(returns.min()),
        "max": float(returns.max()),
        "games": games,
        "opponent": opponent,
    }
    print(json.dumps(out))
    # Evidence trail: the oracle result is the reachability proof for the
    # 18.0 bar — persist it like pong_diagnose's rows (analysis host, not
    # training hardware).
    from asyncrl_tpu.utils import bench_history

    try:
        bench_history.record(
            {
                "kind": "feasibility",
                "name": "pong_oracle_lookahead",
                "analysis_platform": "cpu",
                **out,
            }
        )
    except OSError as e:
        print(f"bench_history: could not persist: {e}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    main()
