"""Feasibility oracle for the 18.0 Pong bar (VERDICT round 2, Missing #1).

The 18.0 mean-return target (BASELINE.json:2) is calibrated to sit ABOVE the
greedy-scripted ceiling (+14.8, tests/test_pong.py) — so before spending
wall-clock on long training runs, this script answers: can ANY policy
expressible from the 6-dim observation actually score >= 18 against the
standard tracker opponent?

It plays a one-ply lookahead oracle: while the ball approaches, enumerate
every paddle position reachable by contact time (the reachable set is the
0.05-step lattice around the current paddle y), simulate the full rally
forward with the EXACT env step math (ball advance, wall folds, paddle
bounce/spin, rate-limited tracker pursuit), and choose the contact point
whose return the tracker misses by the widest margin. This is not a
practical agent (63-way rollout sim per step) — it is an upper-bound probe
for learned play, and its per-decision structure (aim where the tracker
cannot arrive) is exactly what the RL agent must discover.

    python scripts/pong_oracle.py [games] [opponent]

Prints one JSON line: {"oracle_return": ..., "games": N, "opponent": ...}.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# The axon sitecustomize force-sets jax_platforms="axon,cpu" via jax.config,
# IGNORING the JAX_PLATFORMS env var (see tests/conftest.py) — and the axon
# client hangs indefinitely while its tunnel is down. This is a pure-analysis
# tool; CPU is always the right backend for it.
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from asyncrl_tpu.envs.pong import (
    AGENT_SPEED,
    AGENT_X,
    BALL_VX,
    MAX_SPIN,
    OPP_SPEED,
    OPP_X,
    PADDLE_HALF,
    PREDICTIVE_SPEED,
    Pong,
)

SIM_STEPS = 80  # > two court crossings at |vx| = 0.03 over 0.9 width
N_CANDIDATES = 63  # lattice offsets -31..31 around the current paddle y
DEADZONE = 0.026  # match reference_policy's hold band


def _deadzone(skip: int) -> float:
    """Hold band for the skip-quantized motion model — ONE definition
    shared by the rally sim and the emitted-action rule (they must agree
    or the oracle scores targets under a motion model it doesn't play):
    half a decision-move (moving when closer overshoots more than
    holding); skip=1 keeps the calibrated DEADZONE."""
    return DEADZONE if skip == 1 else skip * AGENT_SPEED / 2.0


def _sim_rally(ball, agent_y, opp_y, target, opp_speed, skip=1):
    """Exact forward sim of one rally with the agent parked toward
    ``target``: returns (our_miss, opp_miss, margin) where margin is the
    |ball_y - opp_y| - PADDLE_HALF gap at the opponent-plane crossing
    (positive = the tracker cannot reach the return).

    ``skip`` models frame-skip control (the ALE semantics the skip-4
    presets train under): the move/hold decision is recomputed only every
    ``skip`` core steps and held in between, so one decision displaces
    the paddle by skip x AGENT_SPEED — the coarse-control quantization
    whose greedy ceiling this oracle exists to bound. The hold band
    scales to half a decision-move (moving when closer than that
    overshoots more than holding); skip=1 keeps the original DEADZONE."""
    deadzone = _deadzone(skip)

    def body(carry, t):
        ball, ay, oy, adir, our_miss, opp_miss, margin, live = carry
        # Agent: direction re-decided once per DECISION (every skip core
        # steps), frozen in between — exactly what a frame-skipped action
        # stream can express.
        dy = target - ay
        new_dir = jnp.where(jnp.abs(dy) > deadzone, jnp.sign(dy), 0.0)
        adir = jnp.where(t % skip == 0, new_dir, adir)
        ay = jnp.clip(
            ay + adir * AGENT_SPEED,
            PADDLE_HALF,
            1.0 - PADDLE_HALF,
        )
        # Tracker: rate-limited pursuit of the ball's current y. Under
        # frame_skip the env quantizes the rival to one clipped pursuit
        # move per agent decision (envs/pong.py opponent_every) — mirror
        # that exactly or the oracle would bound the wrong game.
        opp_cap = opp_speed * skip
        opp_move = jnp.where(
            t % skip == 0,
            jnp.clip(ball[1] - oy, -opp_cap, opp_cap),
            0.0,
        )
        oy = jnp.clip(oy + opp_move, PADDLE_HALF, 1.0 - PADDLE_HALF)
        # Ball advance + wall fold (envs/pong.py step math).
        x = ball[0] + ball[2]
        y = ball[1] + ball[3]
        vx, vy = ball[2], ball[3]
        vy = jnp.where(y < 0.0, jnp.abs(vy), vy)
        y = jnp.where(y < 0.0, -y, y)
        vy = jnp.where(y > 1.0, -jnp.abs(vy), vy)
        y = jnp.where(y > 1.0, 2.0 - y, y)

        cross_agent = (x >= AGENT_X) & (vx > 0)
        cross_opp = (x <= OPP_X) & (vx < 0)
        agent_hit = cross_agent & (jnp.abs(y - ay) <= PADDLE_HALF)
        opp_hit = cross_opp & (jnp.abs(y - oy) <= PADDLE_HALF)

        our_miss = our_miss | (live & cross_agent & ~agent_hit)
        opp_miss = opp_miss | (live & cross_opp & ~opp_hit)
        margin = jnp.where(
            live & cross_opp, jnp.abs(y - oy) - PADDLE_HALF, margin
        )
        live = live & ~(cross_opp | (cross_agent & ~agent_hit))

        new_vx = jnp.where(
            agent_hit, -BALL_VX, jnp.where(opp_hit, BALL_VX, vx)
        )
        new_vy = jnp.where(
            agent_hit,
            MAX_SPIN * (y - ay) / PADDLE_HALF,
            jnp.where(opp_hit, MAX_SPIN * (y - oy) / PADDLE_HALF, vy),
        )
        new_x = jnp.where(
            agent_hit, 2.0 * AGENT_X - x, jnp.where(opp_hit, 2.0 * OPP_X - x, x)
        )
        ball = jnp.stack([new_x, y, new_vx, new_vy])
        return (ball, ay, oy, adir, our_miss, opp_miss, margin, live), None

    init = (
        ball,
        agent_y,
        opp_y,
        jnp.float32(0.0),
        jnp.asarray(False),
        jnp.asarray(False),
        jnp.float32(-1.0),
        jnp.asarray(True),
    )
    (_, _, _, _, our_miss, opp_miss, margin, _), _ = jax.lax.scan(
        body, init, jnp.arange(SIM_STEPS)
    )
    return our_miss, opp_miss, margin


def oracle_policy(obs: jax.Array, opp_speed: float, skip: int = 1) -> jax.Array:
    """One-ply lookahead: pick the reachable contact point whose return the
    tracker misses by the widest margin (motion model quantized to
    ``skip``-step decisions — see _sim_rally)."""
    ball = jnp.stack(
        [obs[0], obs[1], obs[2] * BALL_VX, obs[3] * MAX_SPIN]
    )
    ay, oy = obs[4], obs[5]
    deadzone = _deadzone(skip)

    ks = jnp.arange(N_CANDIDATES, dtype=jnp.float32) - (N_CANDIDATES // 2)
    targets = jnp.clip(
        ay + AGENT_SPEED * ks, PADDLE_HALF, 1.0 - PADDLE_HALF
    )

    def score(target):
        our_miss, opp_miss, margin = _sim_rally(
            ball, ay, oy, target, opp_speed, skip
        )
        return jnp.where(
            our_miss,
            -1e6 + margin,
            jnp.where(opp_miss, 1e3 + margin, margin),
        )

    scores = jax.vmap(score)(targets)
    best = targets[jnp.argmax(scores)]
    # Ball receding: park at the court center (serve-return readiness).
    target = jnp.where(ball[2] > 0, best, 0.5)
    dy = target - ay
    return jnp.where(
        dy > deadzone, 2, jnp.where(dy < -deadzone, 3, 0)
    ).astype(jnp.int32)


def play(env, policy_fn, n=32, seed=0, max_steps=3000):
    def one(key):
        st = env.init(key)

        def body(carry, k):
            st, total, done = carry
            obs = env.observe(st)
            a = policy_fn(obs, k)
            st2, ts = env.step(st, a, k)
            st2 = jax.tree.map(lambda n_, o: jnp.where(done, o, n_), st2, st)
            total = total + jnp.where(done, 0.0, ts.reward)
            return (st2, total, done | ts.done), None

        keys = jax.random.split(key, max_steps)
        (_, total, _), _ = jax.lax.scan(
            body, (st, 0.0, jnp.asarray(False)), keys
        )
        return total

    keys = jax.random.split(jax.random.PRNGKey(seed), n)
    return np.asarray(jax.jit(jax.vmap(one))(keys))


def main() -> int:
    games = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    opponent = sys.argv[2] if len(sys.argv) > 2 else "tracker"
    skip = int(sys.argv[3]) if len(sys.argv) > 3 else 1
    # Episode cap in DECISIONS. The default 3000 is the repo's strict
    # scoring-rate cap; pass a larger cap to measure the win-margin
    # (ALE-semantics) ceiling — at skip-4 the default truncates after
    # 750 decisions, which conflates truncation with kinematics (the
    # round-5 retirement decision was re-measured at cap 6000, where
    # every game completes).
    cap = int(sys.argv[4]) if len(sys.argv) > 4 else 3000
    opp_speed = OPP_SPEED if opponent == "tracker" else PREDICTIVE_SPEED
    env = Pong(opponent, opponent_every=skip, max_steps=cap * skip)
    if skip > 1:
        # The skip-4 presets' semantics (envs/wrappers.py FrameSkip + the
        # decision-quantized rival the registry configures): each oracle
        # decision repeats for `skip` core steps — the ceiling this
        # measures is the one the pong_t2t_ale4 / pixel arms train under.
        from asyncrl_tpu.envs.wrappers import FrameSkip

        env = FrameSkip(env, skip)
    returns = play(
        env,
        lambda obs, k: oracle_policy(obs, opp_speed, skip),
        n=games,
        max_steps=cap,
    )
    out = {
        "oracle_return": round(float(returns.mean()), 2),
        "min": float(returns.min()),
        "max": float(returns.max()),
        "games": games,
        "opponent": opponent,
        "pong_max_steps": cap,
        **({"frame_skip": skip} if skip > 1 else {}),
    }
    print(json.dumps(out))
    # Evidence trail: the oracle result is the reachability proof for the
    # 18.0 bar — persist it like pong_diagnose's rows (analysis host, not
    # training hardware).
    from asyncrl_tpu.utils import bench_history

    try:
        bench_history.record(
            {
                "kind": "feasibility",
                "name": "pong_oracle_lookahead",
                "analysis_platform": "cpu",
                **out,
            }
        )
    except OSError as e:
        print(f"bench_history: could not persist: {e}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    main()
