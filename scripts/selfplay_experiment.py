"""Self-play payoff experiment (VERDICT round 2, Next #5).

The self-play ladder (Config.selfplay + JaxPongDuel-v0) exists to develop
stronger play than training directly against the scripted tracker. This
script tests that claim head-to-head: train one agent each way with
MATCHED env-frame budgets and identical hyperparameters, then evaluate
BOTH on the same metric — greedy play against the standard scripted
tracker (the 18.0-bar metric; the duel env's single-action ``step``
inherits the scripted opponent, so ``Trainer.evaluate`` measures exactly
this for the self-play agent too).

    python scripts/selfplay_experiment.py [frames] [key=value ...]

Appends a ``kind="experiment"`` entry to BENCH_HISTORY.json with both
scores and prints it. Interpretation guidance (docs/ARCHITECTURE.md):
direct training exploits THE tracker; self-play learns general play that
must transfer — at small budgets direct usually wins the tracker metric,
so the ladder earns its keep only if this experiment shows otherwise.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from bench import cpu_fallback_or_refuse  # noqa: E402

# Paired runs on whatever is alive: the real chip when the tunnel is up
# (matched-budget arms are cheap there), CPU otherwise — the comparison is
# within-platform either way, so both arms always share one device kind.
cpu_fallback_or_refuse(jax, "selfplay_experiment")

from asyncrl_tpu.api.trainer import Trainer
from asyncrl_tpu.configs import presets
from asyncrl_tpu.utils import bench_history
from asyncrl_tpu.utils.config import override


def train_and_eval(cfg, label: str) -> dict:
    t0 = time.perf_counter()
    trainer = Trainer(cfg)
    last = {}

    def cb(m):
        last.update(m)
        line = {
            "arm": label,
            "env_steps": m["env_steps"],
            "episode_return": round(m["episode_return"], 2),
        }
        print(json.dumps(line), file=sys.stderr, flush=True)

    try:
        trainer.train(callback=cb)
        # Both arms score on the SAME metric: greedy vs the scripted
        # tracker (duel env single-action step keeps the scripted rival).
        score = trainer.evaluate(num_episodes=32)
    finally:
        trainer.close()
    return {
        "eval_vs_tracker": round(float(score), 2),
        "train_seconds": round(time.perf_counter() - t0, 1),
    }


def main() -> int:
    frames = 20_000_000
    overrides = []
    for a in sys.argv[1:]:
        if "=" in a:
            overrides.append(a)
        else:
            frames = int(a)

    base = presets.get("pong_impala").replace(
        total_env_steps=frames, updates_per_call=8
    )
    base = override(base, overrides)

    direct = train_and_eval(base, "direct")
    ladder = train_and_eval(
        base.replace(env_id="JaxPongDuel-v0", selfplay=True), "selfplay"
    )

    entry = {
        "kind": "experiment",
        "name": "selfplay_vs_direct",
        **bench_history.device_entry(),
        "env_frames_each": frames,
        "direct": direct,
        "selfplay": ladder,
        "metric": "mean greedy return vs scripted tracker, 32 episodes",
    }
    try:
        entry = bench_history.record(entry)
    except OSError as e:
        print(f"selfplay_experiment: could not persist: {e}", file=sys.stderr)
    print(json.dumps(entry))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
