// Native vectorized environment pool.
//
// The reference reaches its C++ env engine (ALE) through per-thread Python
// workers (SURVEY.md §2.1). The TPU-native framework inverts that: the pool
// itself is C++ and steps ALL envs for one batched policy query, so the
// Sebulba host path does exactly one Python→C call per env-batch step —
// no per-env Python, no GIL contention in the hot loop (the Python side
// releases the GIL around envpool_step via ctypes).
//
// Envs implemented: CartPole-v1 (gymnasium dynamics), Pong, Breakout,
// Freeway (the same rules as their JAX twins, so the native pool and the
// JAX envs are cross-checkable trajectory-for-trajectory in tests), and
// Pendulum — the first CONTINUOUS-action env (float [B, action_dim]
// actions through envpool_step_continuous).
//
// Threading: a persistent worker pool with a generation-counted barrier.
// Each step, workers wake, step their contiguous env slice, and report done.
// For small batches the main thread steps everything itself (threads only
// pay off past a few hundred envs).
//
// C ABI only (ctypes-friendly): create / reset / step / destroy.

#include <atomic>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr float kPi = 3.14159265358979323846f;

// ----------------------------------------------------------------- RNG
// xorshift128+ per env: fast, no allocation, seedable.
struct Rng {
  uint64_t s0, s1;
  void seed(uint64_t seed) {
    // splitmix64 init
    uint64_t z = (seed += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    s0 = z ^ (z >> 31);
    z = (seed += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    s1 = z ^ (z >> 31);
  }
  uint64_t next() {
    uint64_t x = s0;
    const uint64_t y = s1;
    s0 = y;
    x ^= x << 23;
    s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
    return s1 + y;
  }
  // uniform in [lo, hi)
  float uniform(float lo, float hi) {
    return lo + (hi - lo) * (float)((next() >> 11) * (1.0 / 9007199254740992.0));
  }
};

// ----------------------------------------------------------------- envs
struct EnvBase {
  virtual ~EnvBase() = default;
  virtual int obs_dim() const = 0;
  virtual int num_actions() const = 0;
  // Continuous-control envs report their action dimension (> 0) and
  // implement step_continuous; discrete envs report 0 and implement step.
  virtual int action_dim() const { return 0; }
  virtual void reset(Rng& rng, float* obs) = 0;
  // Steps; fills obs (post-reset on episode end), reward, terminated,
  // truncated. Auto-resets internally.
  // The unimplemented variant aborts loudly: a silent default would let a
  // mismatched action_dim()/override pair return uninitialized buffers to
  // Python (heap garbage read as observations) with no error.
  virtual void step(int action, Rng& rng, float* obs, float* reward,
                    uint8_t* terminated, uint8_t* truncated) {
    (void)action; (void)rng; (void)obs; (void)reward; (void)terminated;
    (void)truncated;
    std::fprintf(stderr,
                 "envpool: env reports action_dim()==0 but implements no "
                 "discrete step()\n");
    std::abort();
  }
  virtual void step_continuous(const float* action, Rng& rng, float* obs,
                               float* reward, uint8_t* terminated,
                               uint8_t* truncated) {
    (void)action; (void)rng; (void)obs; (void)reward; (void)terminated;
    (void)truncated;
    std::fprintf(stderr,
                 "envpool: env reports action_dim()>0 but implements no "
                 "step_continuous()\n");
    std::abort();
  }
};

// CartPole-v1, gymnasium dynamics (matches asyncrl_tpu/envs/cartpole.py).
struct CartPoleEnv final : EnvBase {
  static constexpr float kGravity = 9.8f, kMassCart = 1.0f, kMassPole = 0.1f;
  static constexpr float kTotalMass = kMassCart + kMassPole;
  static constexpr float kHalfPole = 0.5f;
  static constexpr float kPoleMassLength = kMassPole * kHalfPole;
  static constexpr float kForceMag = 10.0f, kTau = 0.02f;
  static constexpr float kThetaThresh = 12.0f * 2.0f * kPi / 360.0f;
  static constexpr float kXThresh = 2.4f;
  static constexpr int kMaxSteps = 500;

  float x, x_dot, theta, theta_dot;
  int t;

  int obs_dim() const override { return 4; }
  int num_actions() const override { return 2; }

  void reset(Rng& rng, float* obs) override {
    x = rng.uniform(-0.05f, 0.05f);
    x_dot = rng.uniform(-0.05f, 0.05f);
    theta = rng.uniform(-0.05f, 0.05f);
    theta_dot = rng.uniform(-0.05f, 0.05f);
    t = 0;
    observe(obs);
  }

  void observe(float* obs) const {
    obs[0] = x; obs[1] = x_dot; obs[2] = theta; obs[3] = theta_dot;
  }

  void step(int action, Rng& rng, float* obs, float* reward,
            uint8_t* terminated, uint8_t* truncated) override {
    const float force = action == 1 ? kForceMag : -kForceMag;
    const float cos_t = std::cos(theta), sin_t = std::sin(theta);
    const float temp =
        (force + kPoleMassLength * theta_dot * theta_dot * sin_t) / kTotalMass;
    const float theta_acc =
        (kGravity * sin_t - cos_t * temp) /
        (kHalfPole * (4.0f / 3.0f - kMassPole * cos_t * cos_t / kTotalMass));
    const float x_acc = temp - kPoleMassLength * theta_acc * cos_t / kTotalMass;
    x += kTau * x_dot;
    x_dot += kTau * x_acc;
    theta += kTau * theta_dot;
    theta_dot += kTau * theta_acc;
    t += 1;

    const bool term = std::fabs(x) > kXThresh || std::fabs(theta) > kThetaThresh;
    const bool trunc = !term && t >= kMaxSteps;
    *reward = 1.0f;
    *terminated = term;
    *truncated = trunc;
    if (term || trunc) {
      reset(rng, obs);
    } else {
      observe(obs);
    }
  }
};

// Pong, same rules/constants as asyncrl_tpu/envs/pong.py (vector obs).
struct PongEnv final : EnvBase {
  static constexpr float kAgentX = 0.95f, kOppX = 0.05f;
  static constexpr float kPaddleHalf = 0.08f;
  static constexpr float kAgentSpeed = 0.05f, kOppSpeed = 0.025f;
  static constexpr float kBallVx = 0.03f, kMaxSpin = 0.04f, kServeVy = 0.02f;
  static constexpr int kWinScore = 21, kMaxSteps = 3000;

  float bx, by, bvx, bvy, agent_y, opp_y;
  int score_a, score_o, t;

  int obs_dim() const override { return 6; }
  int num_actions() const override { return 6; }

  void serve(Rng& rng, bool toward_agent) {
    bx = 0.5f; by = 0.5f;
    bvx = toward_agent ? kBallVx : -kBallVx;
    bvy = rng.uniform(-kServeVy, kServeVy);
  }

  void reset(Rng& rng, float* obs) override {
    serve(rng, (rng.next() & 1) != 0);
    agent_y = 0.5f; opp_y = 0.5f;
    score_a = 0; score_o = 0; t = 0;
    observe(obs);
  }

  void observe(float* obs) const {
    obs[0] = bx; obs[1] = by; obs[2] = bvx / kBallVx; obs[3] = bvy / kMaxSpin;
    obs[4] = agent_y; obs[5] = opp_y;
  }

  void step(int action, Rng& rng, float* obs, float* reward,
            uint8_t* terminated, uint8_t* truncated) override {
    // ALE Pong action mapping: {2,4} up, {3,5} down.
    const float dir = (action == 2 || action == 4)   ? 1.0f
                      : (action == 3 || action == 5) ? -1.0f
                                                     : 0.0f;
    agent_y += kAgentSpeed * dir;
    if (agent_y < kPaddleHalf) agent_y = kPaddleHalf;
    if (agent_y > 1.0f - kPaddleHalf) agent_y = 1.0f - kPaddleHalf;

    float track = by - opp_y;
    if (track > kOppSpeed) track = kOppSpeed;
    if (track < -kOppSpeed) track = -kOppSpeed;
    opp_y += track;
    if (opp_y < kPaddleHalf) opp_y = kPaddleHalf;
    if (opp_y > 1.0f - kPaddleHalf) opp_y = 1.0f - kPaddleHalf;

    float x = bx + bvx, y = by + bvy;
    if (y < 0.0f) { y = -y; bvy = std::fabs(bvy); }
    else if (y > 1.0f) { y = 2.0f - y; bvy = -std::fabs(bvy); }

    const bool cross_agent = x >= kAgentX && bvx > 0;
    const bool cross_opp = x <= kOppX && bvx < 0;
    bool agent_scores = false, opp_scores = false;
    if (cross_agent) {
      if (std::fabs(y - agent_y) <= kPaddleHalf) {
        x = 2.0f * kAgentX - x;
        bvx = -kBallVx;
        bvy = kMaxSpin * (y - agent_y) / kPaddleHalf;
      } else {
        opp_scores = true;
      }
    } else if (cross_opp) {
      if (std::fabs(y - opp_y) <= kPaddleHalf) {
        x = 2.0f * kOppX - x;
        bvx = kBallVx;
        bvy = kMaxSpin * (y - opp_y) / kPaddleHalf;
      } else {
        agent_scores = true;
      }
    }
    *reward = agent_scores ? 1.0f : (opp_scores ? -1.0f : 0.0f);
    score_a += agent_scores;
    score_o += opp_scores;
    bx = x; by = y;
    if (agent_scores || opp_scores) {
      // Loser receives (serve travels toward the conceding side).
      serve(rng, opp_scores);
    }
    t += 1;

    const bool term = score_a >= kWinScore || score_o >= kWinScore;
    const bool trunc = !term && t >= kMaxSteps;
    *terminated = term;
    *truncated = trunc;
    if (term || trunc) {
      reset(rng, obs);
    } else {
      observe(obs);
    }
  }
};

// Breakout, same rules/constants as asyncrl_tpu/envs/breakout.py (vector
// obs: ball(4), paddle_x, lives/5, 72 brick bits = 78 dims).
struct BreakoutEnv final : EnvBase {
  static constexpr int kRows = 6, kCols = 12;
  static constexpr float kBrickTop = 0.88f, kRowH = 0.04f;
  static constexpr float kBrickBot = kBrickTop - kRows * kRowH;
  static constexpr float kPaddleY = 0.06f, kPaddleHalf = 0.075f;
  static constexpr float kPaddleSpeed = 0.05f;
  static constexpr float kBallSpeedY = 0.025f, kMaxVx = 0.035f;
  static constexpr int kLives = 5, kAutoServe = 8, kMaxSteps = 3000;

  float bx, by, bvx, bvy, paddle_x;
  bool bricks[kRows][kCols];
  int lives, held, t;

  static float row_points(int r) {
    static constexpr float kPoints[kRows] = {1, 1, 4, 4, 7, 7};
    return kPoints[r];
  }

  int obs_dim() const override { return 4 + 2 + kRows * kCols; }
  int num_actions() const override { return 4; }

  void reset(Rng& rng, float* obs) override {
    (void)rng;
    bx = 0.5f; by = kPaddleY + 0.02f; bvx = 0.0f; bvy = 0.0f;
    paddle_x = 0.5f;
    for (auto& row : bricks)
      for (auto& b : row) b = true;
    lives = kLives; held = 0; t = 0;
    observe(obs);
  }

  void observe(float* obs) const {
    obs[0] = bx; obs[1] = by;
    obs[2] = bvx / kMaxVx; obs[3] = bvy / kBallSpeedY;
    obs[4] = paddle_x; obs[5] = (float)lives / kLives;
    for (int r = 0; r < kRows; ++r)
      for (int c = 0; c < kCols; ++c) obs[6 + r * kCols + c] = bricks[r][c];
  }

  void step(int action, Rng& rng, float* obs, float* reward,
            uint8_t* terminated, uint8_t* truncated) override {
    // ALE Breakout mapping: 1 = FIRE (serve), 2 = RIGHT, 3 = LEFT.
    const float dx = action == 2 ? 1.0f : (action == 3 ? -1.0f : 0.0f);
    paddle_x += kPaddleSpeed * dx;
    if (paddle_x < kPaddleHalf) paddle_x = kPaddleHalf;
    if (paddle_x > 1.0f - kPaddleHalf) paddle_x = 1.0f - kPaddleHalf;

    const bool in_play = bvx != 0.0f || bvy != 0.0f;
    held = in_play ? 0 : held + 1;
    if (!in_play) {
      if (action == 1 || held >= kAutoServe) {
        bx = paddle_x; by = kPaddleY + 0.02f;
        bvx = rng.uniform(-0.5f * kMaxVx, 0.5f * kMaxVx);
        bvy = kBallSpeedY;
      } else {
        bx = paddle_x;  // still held: ride the paddle
      }
    }

    float x = bx + bvx, y = by + bvy;
    if (x < 0.0f) { x = -x; bvx = std::fabs(bvx); }
    else if (x > 1.0f) { x = 2.0f - x; bvx = -std::fabs(bvx); }
    if (y > 1.0f) { y = 2.0f - y; bvy = -std::fabs(bvy); }

    // Brick collision: the cell the ball sits in, if inside the band.
    *reward = 0.0f;
    if (y >= kBrickBot && y < kBrickTop) {
      int r = (int)std::floor((y - kBrickBot) / kRowH);
      if (r < 0) r = 0;
      if (r >= kRows) r = kRows - 1;
      int c = (int)std::floor(x * kCols);
      if (c < 0) c = 0;
      if (c >= kCols) c = kCols - 1;
      if (bricks[r][c]) {
        bricks[r][c] = false;
        *reward = row_points(r);
        bvy = -bvy;
      }
    }

    // Paddle bounce: offset sets outgoing vx (the aiming mechanic).
    const bool at_paddle = y <= kPaddleY && bvy < 0.0f;
    bool lost = false;
    if (at_paddle) {
      const float offset = (x - paddle_x) / kPaddleHalf;
      if (std::fabs(offset) <= 1.0f) {
        bvy = std::fabs(bvy);
        bvx = kMaxVx * offset;
        y = 2.0f * kPaddleY - y;
      } else {
        lost = true;
      }
    }
    if (lost) {
      lives -= 1;
      bx = paddle_x; by = kPaddleY + 0.02f; bvx = 0.0f; bvy = 0.0f;
      held = 0;
    } else {
      bx = x; by = y;
    }
    t += 1;

    bool cleared = true;
    for (auto& row : bricks)
      for (auto& b : row) cleared &= !b;
    const bool term = cleared || lives <= 0;
    const bool trunc = !term && t >= kMaxSteps;
    *terminated = term;
    *truncated = trunc;
    if (term || trunc) {
      reset(rng, obs);
    } else {
      observe(obs);
    }
  }
};

// Pendulum-v1 swing-up, matching asyncrl_tpu/envs/pendulum.py (itself
// gymnasium-exact): g=10, m=1, l=1, dt=0.05, torque clip ±2, speed clip
// ±8, 200-step truncation-only episodes, reward −(θ²+0.1·θ̇²+0.001·u²).
// The first CONTINUOUS-action env in the native pool; observation
// [cosθ, sinθ, θ̇] lets tests reconstruct the state and run the JAX twin
// in lockstep (the step itself is deterministic).
struct PendulumEnv final : EnvBase {
  static constexpr float kG = 10.0f, kMass = 1.0f, kLength = 1.0f;
  static constexpr float kDt = 0.05f, kMaxSpeed = 8.0f, kMaxTorque = 2.0f;
  static constexpr int kMaxSteps = 200;

  float theta, theta_dot;
  int t;

  int obs_dim() const override { return 3; }
  int num_actions() const override { return 0; }
  int action_dim() const override { return 1; }

  static float angle_normalize(float x) {
    const float two_pi = 2.0f * kPi;
    float y = std::fmod(x + kPi, two_pi);
    if (y < 0.0f) y += two_pi;
    return y - kPi;
  }

  void reset(Rng& rng, float* obs) override {
    theta = rng.uniform(-kPi, kPi);
    theta_dot = rng.uniform(-1.0f, 1.0f);
    t = 0;
    observe(obs);
  }

  void observe(float* obs) const {
    obs[0] = std::cos(theta);
    obs[1] = std::sin(theta);
    obs[2] = theta_dot;
  }

  void step_continuous(const float* action, Rng& rng, float* obs,
                       float* reward, uint8_t* terminated,
                       uint8_t* truncated) override {
    float u = action[0];
    if (u > kMaxTorque) u = kMaxTorque;
    if (u < -kMaxTorque) u = -kMaxTorque;

    const float an = angle_normalize(theta);
    *reward = -(an * an + 0.1f * theta_dot * theta_dot + 0.001f * u * u);

    // Semi-implicit Euler (theta advances with the NEW velocity), exactly
    // as the JAX twin.
    theta_dot += (3.0f * kG / (2.0f * kLength) * std::sin(theta) +
                  3.0f / (kMass * kLength * kLength) * u) *
                 kDt;
    if (theta_dot > kMaxSpeed) theta_dot = kMaxSpeed;
    if (theta_dot < -kMaxSpeed) theta_dot = -kMaxSpeed;
    theta += theta_dot * kDt;

    t += 1;
    *terminated = 0;
    *truncated = t >= kMaxSteps ? 1 : 0;
    if (*truncated) {
      reset(rng, obs);
    } else {
      observe(obs);
    }
  }
};

// ----------------------------------------------------------------- pool
struct EnvPool {
  std::vector<EnvBase*> envs;
  std::vector<Rng> rngs;
  int num_envs = 0;
  int obs_dim_ = 0;
  int num_actions_ = 0;
  int action_dim_ = 0;  // > 0: continuous pool (step_continuous path)

  // step-call shared pointers (set by step(), read by workers)
  const int32_t* actions = nullptr;
  const float* actions_f = nullptr;
  float* obs_out = nullptr;
  float* rew_out = nullptr;
  uint8_t* term_out = nullptr;
  uint8_t* trunc_out = nullptr;

  // persistent worker pool
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  uint64_t generation = 0;
  int pending = 0;
  bool shutdown = false;
  int num_threads = 0;

  ~EnvPool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      shutdown = true;
      ++generation;
    }
    cv_work.notify_all();
    for (auto& w : workers) w.join();
    for (auto* e : envs) delete e;
  }

  void worker_loop(int tid) {
    uint64_t seen = 0;
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [&] { return generation != seen || shutdown; });
        if (shutdown) return;
        seen = generation;
      }
      step_slice(tid);
      {
        std::lock_guard<std::mutex> lk(mu);
        if (--pending == 0) cv_done.notify_one();
      }
    }
  }

  void step_one(int i) {
    if (action_dim_ > 0) {
      envs[i]->step_continuous(actions_f + (size_t)i * action_dim_, rngs[i],
                               obs_out + (size_t)i * obs_dim_, rew_out + i,
                               term_out + i, trunc_out + i);
    } else {
      envs[i]->step(actions[i], rngs[i], obs_out + (size_t)i * obs_dim_,
                    rew_out + i, term_out + i, trunc_out + i);
    }
  }

  void step_slice(int tid) {
    const int per = (num_envs + num_threads - 1) / num_threads;
    const int lo = tid * per;
    const int hi = std::min(num_envs, lo + per);
    for (int i = lo; i < hi; ++i) step_one(i);
  }

  // Shared fan-out for both action types; exactly one of acts/acts_f set.
  void run(const int32_t* acts, const float* acts_f, float* obs, float* rew,
           uint8_t* term, uint8_t* trunc) {
    actions = acts; actions_f = acts_f; obs_out = obs; rew_out = rew;
    term_out = term; trunc_out = trunc;
    if (num_threads <= 1) {
      for (int i = 0; i < num_envs; ++i) step_one(i);
      return;
    }
    {
      std::lock_guard<std::mutex> lk(mu);
      pending = num_threads;
      ++generation;
    }
    cv_work.notify_all();
    {
      std::unique_lock<std::mutex> lk(mu);
      cv_done.wait(lk, [&] { return pending == 0; });
    }
  }

  void step(const int32_t* acts, float* obs, float* rew, uint8_t* term,
            uint8_t* trunc) {
    run(acts, nullptr, obs, rew, term, trunc);
  }

  void step_continuous(const float* acts, float* obs, float* rew,
                       uint8_t* term, uint8_t* trunc) {
    run(nullptr, acts, obs, rew, term, trunc);
  }
};

// Freeway (MinAtar-style), matching asyncrl_tpu/envs/minatari.py::Freeway
// rule for rule: 10x10 grid, chicken in column 4 crossing 8 traffic lanes,
// +1 at the top row (back to start), collision sends it back, fixed
// 2500-step episode (truncation only). Observation layout mirrors the JAX
// env's [10, 10, 2] uint8 planes (chicken, cars), flattened row-major, so
// tests can seed the JAX env from a native reset and step both in
// lockstep (the step itself is deterministic).
struct FreewayEnv final : EnvBase {
  static constexpr int kG = 10, kLanes = 8;
  static constexpr int kMaxSteps = 2500, kMoveCooldown = 1;
  // Lane speeds: a car advances one cell every |speed| steps; sign is the
  // direction (matches minatari._LANE_SPEED).
  static constexpr int kSpeed[kLanes] = {1, 2, 3, 4, -1, -2, -3, -4};

  int chicken, move_cd, t;
  int cars[kLanes], timers[kLanes];

  int obs_dim() const override { return kG * kG * 2; }
  int num_actions() const override { return 3; }

  void reset(Rng& rng, float* obs) override {
    chicken = kG - 1;
    move_cd = 0;
    t = 0;
    for (int i = 0; i < kLanes; ++i) {
      cars[i] = static_cast<int>(rng.uniform(0.0f, (float)kG)) % kG;
      timers[i] = kSpeed[i] < 0 ? -kSpeed[i] : kSpeed[i];
    }
    observe(obs);
  }

  void observe(float* obs) const {
    std::memset(obs, 0, sizeof(float) * kG * kG * 2);
    obs[(chicken * kG + 4) * 2 + 0] = 1.0f;
    for (int i = 0; i < kLanes; ++i)
      obs[((i + 1) * kG + cars[i]) * 2 + 1] = 1.0f;
  }

  void step(int action, Rng& rng, float* obs, float* reward,
            uint8_t* terminated, uint8_t* truncated) override {
    const bool can_move = move_cd <= 0;
    const int delta = action == 1 ? -1 : (action == 2 ? 1 : 0);
    if (can_move && delta != 0) {
      chicken += delta;
      if (chicken < 0) chicken = 0;
      if (chicken > kG - 1) chicken = kG - 1;
      move_cd = kMoveCooldown;
    } else {
      move_cd -= 1;
    }

    for (int i = 0; i < kLanes; ++i) {
      if (timers[i] <= 1) {
        const int dir = kSpeed[i] < 0 ? -1 : 1;
        cars[i] = ((cars[i] + dir) % kG + kG) % kG;
        timers[i] = kSpeed[i] < 0 ? -kSpeed[i] : kSpeed[i];
      } else {
        timers[i] -= 1;
      }
    }

    const bool in_traffic = chicken >= 1 && chicken <= kLanes;
    const bool hit = in_traffic && cars[chicken - 1] == 4;
    const bool scored = chicken == 0;
    *reward = scored ? 1.0f : 0.0f;
    if (scored || hit) chicken = kG - 1;

    t += 1;
    *terminated = 0;
    *truncated = t >= kMaxSteps ? 1 : 0;
    if (*truncated) {
      reset(rng, obs);
      return;
    }
    observe(obs);
  }
};

EnvBase* make_env(const std::string& id) {
  if (id == "CartPole-v1") return new CartPoleEnv();
  if (id == "Pong") return new PongEnv();
  if (id == "Breakout") return new BreakoutEnv();
  if (id == "Freeway") return new FreewayEnv();
  if (id == "Pendulum") return new PendulumEnv();
  return nullptr;
}

}  // namespace

extern "C" {

EnvPool* envpool_create(const char* env_id, int num_envs, int num_threads,
                        uint64_t seed) {
  auto* pool = new EnvPool();
  pool->num_envs = num_envs;
  pool->envs.reserve(num_envs);
  pool->rngs.resize(num_envs);
  for (int i = 0; i < num_envs; ++i) {
    EnvBase* e = make_env(env_id);
    if (!e) { delete pool; return nullptr; }
    pool->envs.push_back(e);
    pool->rngs[i].seed(seed * 0x9E3779B97F4A7C15ULL + (uint64_t)i);
  }
  pool->obs_dim_ = pool->envs[0]->obs_dim();
  pool->num_actions_ = pool->envs[0]->num_actions();
  pool->action_dim_ = pool->envs[0]->action_dim();
  pool->num_threads = num_threads;
  if (num_threads > 1) {
    pool->workers.reserve(num_threads);
    for (int tid = 0; tid < num_threads; ++tid) {
      pool->workers.emplace_back(&EnvPool::worker_loop, pool, tid);
    }
  }
  return pool;
}

// Re-seed every per-env RNG exactly as envpool_create did: a pool reused
// across evaluations can restore determinism before each reset.
void envpool_reseed(EnvPool* pool, uint64_t seed) {
  for (int i = 0; i < pool->num_envs; ++i) {
    pool->rngs[i].seed(seed * 0x9E3779B97F4A7C15ULL + (uint64_t)i);
  }
}

void envpool_reset(EnvPool* pool, float* obs_out) {
  for (int i = 0; i < pool->num_envs; ++i) {
    pool->envs[i]->reset(pool->rngs[i],
                         obs_out + (size_t)i * pool->obs_dim_);
  }
}

void envpool_step(EnvPool* pool, const int32_t* actions, float* obs_out,
                  float* rew_out, uint8_t* term_out, uint8_t* trunc_out) {
  pool->step(actions, obs_out, rew_out, term_out, trunc_out);
}

// Continuous pools: actions are [num_envs, action_dim] f32 row-major.
void envpool_step_continuous(EnvPool* pool, const float* actions,
                             float* obs_out, float* rew_out,
                             uint8_t* term_out, uint8_t* trunc_out) {
  pool->step_continuous(actions, obs_out, rew_out, term_out, trunc_out);
}

int envpool_obs_dim(EnvPool* pool) { return pool->obs_dim_; }
int envpool_num_actions(EnvPool* pool) { return pool->num_actions_; }
int envpool_action_dim(EnvPool* pool) { return pool->action_dim_; }
int envpool_num_envs(EnvPool* pool) { return pool->num_envs; }

void envpool_destroy(EnvPool* pool) { delete pool; }

}  // extern "C"
