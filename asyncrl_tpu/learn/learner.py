"""Learner: the reference's ``Learner.update`` (BASELINE.json:5) as a single
donated-buffer ``jit`` of a ``shard_map`` over the device mesh.

One call = one fused XLA program that (per device shard): rolls out
``unroll_len`` steps across the local env batch with the (possibly stale)
actor params, recomputes logits/values under learner params, applies the
algorithm loss (A3C / IMPALA-V-trace / PPO), all-reduces gradients with
``lax.pmean`` over the ``dp`` axis, and applies Adam. Weight "publishing" to
actors (the reference's queue-back channel) is the ``actor_params`` refresh —
a pytree select every ``actor_staleness`` updates, staying entirely in HBM
(SURVEY.md §5.8b, §7.3).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, PartitionSpec as P

from asyncrl_tpu.envs.core import Environment
from asyncrl_tpu.ops.gae import gae
from asyncrl_tpu.ops.normalize import (
    init_stats,
    normalizing_apply,
    update_stats,
)
from asyncrl_tpu.models.networks import is_recurrent, reset_core
from asyncrl_tpu.ops.losses import (
    a3c_loss,
    impala_loss,
    ppo_loss,
    qlearn_loss,
)
from asyncrl_tpu.parallel.mesh import (
    axis_size,
    dp_axes,
    dp_size,
    reduce_grads,
    shard_map,
)
from asyncrl_tpu.rollout.anakin import ActorState, actor_init, unroll
from asyncrl_tpu.rollout.buffer import Rollout
from asyncrl_tpu.utils.config import Config


# Axes-tolerant collectives: the train-step body is also used with
# ``axes=()`` (population mode, api/population.py — members are independent,
# nothing may reduce across them), where each collective degenerates to the
# single-shard identity.
def _pmean(x, axes):
    return x if not axes else jax.lax.pmean(x, axes)


def _psum(x, axes):
    return x if not axes else jax.lax.psum(x, axes)


def _axis_size(axes) -> int:
    return 1 if not axes else axis_size(axes)


def _axis_index(axes):
    return jnp.zeros((), jnp.int32) if not axes else jax.lax.axis_index(axes)


@struct.dataclass
class TrainState:
    """Full training state; the unit of checkpointing (SURVEY.md §5.4).

    ``params`` are the learner weights; ``actor_params`` the stale copy the
    rollout uses (equal for on-policy algos, lagged for IMPALA). ``actor``
    holds env states/obs/keys, sharded over the dp axis. ``obs_stats`` is
    the running observation-normalization state (ops/normalize.py) — None
    (empty subtree) unless ``config.normalize_obs``.
    """

    params: Any
    actor_params: Any
    opt_state: Any
    actor: ActorState
    update_step: jax.Array  # int32 scalar
    obs_stats: Any = None
    # Running scalar stats of the per-env discounted return (reward
    # normalization, config.normalize_returns); None when disabled.
    ret_stats: Any = None
    # Self-play (config.selfplay): the frozen rival snapshot the duel env
    # plays against, refreshed from params every selfplay_refresh updates.
    # None (empty subtree) otherwise — keeps old checkpoints restorable.
    opponent_params: Any = None


def state_partition_spec(axes: tuple[str, ...]) -> TrainState:
    """Pytree-prefix PartitionSpecs for shard_map in/out_specs: params and
    optimizer replicated, actor state sharded on its leading env dim over
    all data-parallel axes (one axis on a single slice, (dcn, dp) on a
    hybrid multi-slice mesh)."""
    return TrainState(
        params=P(),
        actor_params=P(),
        opt_state=P(),
        actor=P(axes),
        update_step=P(),
        obs_stats=P(),
        ret_stats=P(),
        opponent_params=P(),
    )


def _total_optimizer_steps(config: Config) -> int:
    """Projected count of ``optimizer.update`` calls over a full run — the
    LR schedule's horizon. optax schedules tick once per optimizer call, so
    this must model the configured backend and algorithm:

    - Anakin consumes ``num_envs * unroll_len`` frames per learner update;
      the host backends (sebulba/cpu_async) consume one ACTOR's fragment,
      ``(num_envs / actor_threads) * unroll_len``, per update;
    - multipass PPO takes ``ppo_epochs * ppo_minibatches`` optimizer steps
      inside each learner update.
    """
    frames_per_update = config.batch_steps_per_update
    if config.backend in ("sebulba", "cpu_async"):
        frames_per_update //= max(config.actor_threads, 1)
    updates = max(1, config.total_env_steps // max(frames_per_update, 1))
    if config.algo == "ppo":
        updates *= max(1, config.ppo_epochs) * max(1, config.ppo_minibatches)
    return updates


def base_optimizer(config: Config):
    """The per-step transform factory (rate injected later): Adam (the
    reference Learner's optimizer, BASELINE.json:5) or shared-statistics
    RMSProp (the A3C-paper family default, SURVEY.md:143 — "shared" holds
    by construction here: one mesh-wide optimizer state fed by psum'd
    gradients). Returned as a factory so population training can wrap it
    in ``optax.inject_hyperparams`` for per-member rates."""
    if config.optimizer == "adam":
        return optax.adam, {"eps": config.adam_eps}
    if config.optimizer == "rmsprop":
        return optax.rmsprop, {
            "decay": config.rmsprop_decay,
            "eps": config.rmsprop_eps,
        }
    raise ValueError(
        f"unknown optimizer {config.optimizer!r}; expected adam|rmsprop"
    )


def make_optimizer(config: Config) -> optax.GradientTransformation:
    """Global-norm clip + the configured base optimizer, with the configured
    LR schedule. The schedule is indexed by the optimizer's own update
    count; its horizon is the projected optimizer-step total for this
    backend/algorithm (``_total_optimizer_steps``), so "linear" reaches
    zero at the run's step budget — not a fraction of the way through it."""
    if config.lr_schedule == "constant":
        lr = config.learning_rate
    elif config.lr_schedule == "linear":
        lr = optax.linear_schedule(
            config.learning_rate, 0.0, _total_optimizer_steps(config)
        )
    else:
        raise ValueError(
            f"unknown lr_schedule {config.lr_schedule!r}; "
            "expected constant|linear"
        )
    base, kwargs = base_optimizer(config)
    return optax.chain(
        optax.clip_by_global_norm(config.max_grad_norm),
        base(lr, **kwargs),
    )


def resolve_scan_impl(config: Config, mesh: Mesh) -> Config:
    """Resolve ``scan_impl="auto"`` and ``fused_scan="auto"`` to concrete
    implementations. Called by each learner constructor so the per-shard
    loss code sees a fixed choice.

    ``scan_impl`` "auto" -> "associative" everywhere. The plain Pallas
    scan kernel (ops/pallas_scan.py) WAS validated on a real TPU v5lite
    chip (2026-07-30): its Mosaic lowering compiles and runs, and it is
    numerically identical to the associative scan (rtol 2e-5 over
    [128, 1024] fragments). End-to-end it is indistinguishable — the
    reverse scan ALONE is a negligible slice of the train step at RL
    fragment lengths — so it stays opt-in (``scan_impl=pallas``).

    ``fused_scan`` "auto" -> "pallas" on TPU meshes, "lax" elsewhere.
    Unlike the bare scan swap, the fused kernel replaces the WHOLE
    V-trace/GAE tail — five [T, B] elementwise HBM passes plus the
    O(log T) scan rounds collapse into one tile-resident pass — and it
    is bit-identical to the lax reference (sequential schedule), so the
    TPU default changes no training numerics beyond the documented
    sequential-vs-associative rounding split that scan_impl already
    owns. "interpret" (the Pallas interpreter) is the CPU CI surface;
    it is never auto-selected."""
    if config.fused_scan == "auto":
        platform = mesh.devices.flat[0].platform if mesh.devices.size else "cpu"
        config = config.replace(
            fused_scan="pallas" if platform == "tpu" else "lax"
        )
    elif config.fused_scan not in ("pallas", "interpret", "lax"):
        raise ValueError(
            f"unknown fused_scan {config.fused_scan!r}; "
            "expected auto|pallas|interpret|lax"
        )
    if config.smap_check not in ("auto", "off"):
        raise ValueError(
            f"unknown smap_check {config.smap_check!r}; expected auto|off"
        )
    if config.grad_reduce == "auto":
        config = config.replace(grad_reduce="psum")
    elif config.grad_reduce == "ring":
        # Ring gradient sync replaces the EXPLICIT psum of the
        # pre-graduation shard_map path; on jax with top-level shard_map
        # the implicit vma-transpose reduction already ran by the time
        # reduce_grads is called, so a ring there would double-reduce.
        if hasattr(jax, "shard_map"):
            raise ValueError(
                "grad_reduce='ring' requires the explicit-reduction "
                "shard_map path (jax.experimental.shard_map); this jax "
                "reduces gradients implicitly — use grad_reduce='psum'"
            )
        if len(dp_axes(mesh)) != 1:
            raise ValueError(
                "grad_reduce='ring' needs a single data-parallel mesh "
                f"axis, got {dp_axes(mesh)}; use grad_reduce='psum'"
            )
    elif config.grad_reduce != "psum":
        raise ValueError(
            f"unknown grad_reduce {config.grad_reduce!r}; "
            "expected auto|psum|ring"
        )
    if config.scan_impl != "auto":
        return config
    return config.replace(scan_impl="associative")


def fused_smap_opts(config: Config) -> dict:
    """shard_map kwargs for a learner step whose loss tail may contain a
    ``pallas_call``: jax 0.4.x's shard_map has no replication rule for it
    (``NotImplementedError`` at trace time), so fused-kernel configs must
    opt out of the replication checker. Safe here because the learner
    bodies never rely on the checker's transpose rewrite — gradients of
    the replicated params are reduced EXPLICITLY (``reduce_grads``,
    parallel/mesh.py) and every P()-spec'd metric comes out of a
    pmean/psum, i.e. is replicated by construction, checker or not. Lax
    configs keep the checked path (and its free replication proofs)
    unless ``smap_check="off"`` forces the opt-out — the knob A/B
    probes use to compile both arms with the SAME wrapper, since the
    checker's identity collectives move XLA fusion boundaries and can
    shift loss trajectories by a final ULP on multi-device meshes."""
    if config.smap_check == "off":
        return {"check_vma": False}
    if config.fused_scan in ("pallas", "interpret"):
        return {"check_vma": False}
    return {}


def validate_qlearn_config(config: Config) -> None:
    """Shared constructor-time check for the Q-learning family: every
    builder of the train-step body (Learner, PopulationTrainer) must call
    this, since the degenerate configuration fails silently, not loudly."""
    if config.algo == "qlearn" and config.actor_staleness < 2:
        raise ValueError(
            "algo='qlearn' needs actor_staleness >= 2: that field is the "
            "target-network update period for this algo, and at 1 the "
            "bootstrap comes from the net being optimized (double_q "
            "degenerates to max-Q too). The cartpole_qlearn preset "
            "uses 4."
        )


def validate_train_target(config: Config, target: int) -> None:
    """Shared guard for Trainer.train / SebulbaTrainer.train: with an
    annealing LR schedule, training past the configured horizon would
    silently run at lr=0 — refuse instead."""
    if config.lr_schedule != "constant" and target > config.total_env_steps:
        raise ValueError(
            f"train(total_env_steps={target}) exceeds the lr_schedule "
            f"horizon (config.total_env_steps={config.total_env_steps}): "
            "the annealed rate would sit at 0 for the excess steps. Set "
            "config.total_env_steps to the real budget instead."
        )


def validate_selfplay_config(config: Config, env, model) -> None:
    """Eager self-play checks (Anakin Learner only): the env must be a duel
    env, the policy feed-forward (the frozen rival has no core-state
    plumbing in v1), and the backend the fused one."""
    if not config.selfplay:
        return
    if config.backend != "tpu":
        raise NotImplementedError(
            "selfplay is Anakin-only (backend='tpu'): host actor threads "
            "have no opponent-snapshot channel"
        )
    # frame_skip / sticky_actions compose with self-play: the ALE wrappers
    # forward the duel protocol (both paddles' actions repeat across a skip
    # window; each paddle draws its own stick — envs/wrappers.py), so the
    # hasattr check below sees through them.
    if not (
        hasattr(env, "step_duel") and hasattr(env, "observe_opponent")
    ):
        raise ValueError(
            f"selfplay needs a duel env (step_duel + observe_opponent); "
            f"{config.env_id!r} is not one — use JaxPongDuel-v0"
        )


def validate_recurrent_config(config: Config, model) -> None:
    """Shared constructor-time checks for recurrent policies (Anakin and
    host-fragment learners alike). Recurrent multipass PPO is supported
    via sequence-preserving minibatching (see ``_ppo_multipass``); its
    geometry constraint (envs, not samples, divide into minibatches) is
    enforced by ``validate_ppo_geometry(recurrent=True)``."""
    if config.core == "lstm" and not is_recurrent(model):
        raise ValueError(
            "config.core='lstm' but the given model is not recurrent — "
            "pass a RecurrentActorCritic (policy-gradient algos) / "
            "RecurrentQNetwork (qlearn), or use core='ff'"
        )


def _forward_fragment(apply_fn, params, rollout: Rollout):
    """Learner forward over one fragment -> (dist_params, values), both
    [T+1, ...] (final entry is the bootstrap step).

    Feed-forward: one batched apply over the stacked [T+1, B] obs.
    Recurrent (``rollout.init_core`` present): a ``lax.scan`` over time
    carrying the core from the fragment-initial behaviour carry (IMPALA's
    stale-core recipe) and resetting it at episode boundaries, exactly as
    the actor did."""
    if rollout.init_core is None:
        obs_all = jnp.concatenate(
            [rollout.obs, rollout.bootstrap_obs[None]], axis=0
        )
        return apply_fn(params, obs_all)

    def fwd(core, inputs):
        obs_t, done_t = inputs
        dist_params, value, new_core = apply_fn(params, obs_t, core)
        return reset_core(new_core, done_t), (dist_params, value)

    core_end, (logits_t, values_t) = jax.lax.scan(
        fwd, rollout.init_core, (rollout.obs, rollout.done)
    )
    boot_logits, boot_value, _ = apply_fn(
        params, rollout.bootstrap_obs, core_end
    )
    logits = jnp.concatenate([logits_t, boot_logits[None]], axis=0)
    values = jnp.concatenate([values_t, boot_value[None]], axis=0)
    return logits, values


def qlearn_bootstrap(config: Config, online_boot_q, target_boot_q):
    """THE target-network bootstrap selection for the Q-learning family
    (shared by the unsharded and time-sharded loss paths): ``max_a
    Q_target``, or the double-Q selection — argmax under the ONLINE net,
    evaluated under the target — to damp the max bias."""
    target_boot_q = jax.lax.stop_gradient(target_boot_q)
    if config.double_q:
        sel = jnp.argmax(jax.lax.stop_gradient(online_boot_q), axis=-1)
        return jnp.take_along_axis(target_boot_q, sel[..., None], axis=-1)[
            ..., 0
        ]
    return jnp.max(target_boot_q, axis=-1)


def entropy_coef_at(config: Config, update_step) -> jax.Array | float:
    """Effective entropy coefficient at ``update_step`` (traced scalar):
    linear ramp entropy_coef -> entropy_coef_final over
    entropy_anneal_steps updates, constant thereafter — and the plain
    Python float when annealing is off, keeping the non-annealed program
    bit-identical to before the feature existed."""
    if config.entropy_anneal_steps <= 0:
        return config.entropy_coef
    frac = jnp.clip(
        update_step.astype(jnp.float32) / float(config.entropy_anneal_steps),
        0.0,
        1.0,
    )
    return config.entropy_coef + frac * (
        config.entropy_coef_final - config.entropy_coef
    )


def _algo_loss(
    config: Config, apply_fn, params, rollout: Rollout,
    axis_name: str | None = None, dist=None, target_params=None,
    entropy_coef=None,
):
    """Forward the learner net over [T+1, B] obs and apply the configured
    algorithm's loss. Returns (loss, metrics). ``axis_name`` is the dp mesh
    axis when called inside shard_map (for losses needing global batch
    moments, i.e. PPO advantage normalization). ``dist`` interprets the
    policy head (ops.distributions). ``target_params`` is the Q-learning
    family's target network (required for algo='qlearn', unused otherwise).
    ``entropy_coef`` overrides config.entropy_coef (the annealed traced
    value, entropy_coef_at); None = the constant."""
    if entropy_coef is None:
        entropy_coef = config.entropy_coef
    logits, values = _forward_fragment(apply_fn, params, rollout)
    logits_t, values_t = logits[:-1], values[:-1]
    bootstrap_value = values[-1]
    discounts = rollout.discounts(config.gamma)

    if config.algo == "qlearn":
        # ``logits`` ARE the online Q-values here (QNetwork head). The
        # bootstrap comes from the target network (the stale actor_params
        # copy, refreshed every actor_staleness updates — the async-Q target
        # network θ⁻) via the shared ``qlearn_bootstrap`` selection.
        if rollout.init_core is None:
            q_target = apply_fn(target_params, rollout.bootstrap_obs)[0]
        else:
            # DRQN: the target net needs ITS OWN core at the bootstrap
            # step, so re-forward the whole fragment under target params
            # from the stored behaviour-initial carry (the stored-state
            # DRQN recipe; same shape of work as the online re-forward).
            q_target = _forward_fragment(
                apply_fn, target_params, rollout
            )[0][-1]
        boot = qlearn_bootstrap(config, logits[-1], q_target)
        return qlearn_loss(
            logits_t, rollout.actions, rollout.rewards, discounts, boot,
            scan_impl=config.scan_impl, fused_scan=config.fused_scan,
            huber_delta=config.huber_delta,
        )
    if config.algo == "a3c":
        return a3c_loss(
            logits_t, values_t, rollout.actions, rollout.rewards, discounts,
            jax.lax.stop_gradient(bootstrap_value),
            value_coef=config.value_coef, entropy_coef=entropy_coef,
            dist=dist, scan_impl=config.scan_impl,
            fused_scan=config.fused_scan,
            diagnostics=config.introspect,
        )
    if config.algo == "impala":
        return impala_loss(
            logits_t, values_t, rollout.actions, rollout.behaviour_logp,
            rollout.rewards, discounts, jax.lax.stop_gradient(bootstrap_value),
            value_coef=config.value_coef, entropy_coef=entropy_coef,
            rho_clip=config.vtrace_rho_clip, c_clip=config.vtrace_c_clip,
            dist=dist, scan_impl=config.scan_impl,
            fused_scan=config.fused_scan,
            diagnostics=config.introspect,
        )
    if config.algo == "ppo":
        # Single-pass PPO over the fresh fragment (used when
        # ppo_epochs == ppo_minibatches == 1; the multi-epoch minibatched
        # path is _ppo_multipass below).
        adv = gae(
            rollout.rewards, discounts, jax.lax.stop_gradient(values_t),
            jax.lax.stop_gradient(bootstrap_value), config.gae_lambda,
            scan_impl=config.scan_impl, fused=config.fused_scan,
        )
        return ppo_loss(
            logits_t, values_t, rollout.actions, rollout.behaviour_logp,
            adv.advantages, adv.returns,
            clip_eps=config.ppo_clip_eps, value_coef=config.value_coef,
            entropy_coef=entropy_coef, axis_name=axis_name,
            dist=dist, diagnostics=config.introspect,
        )
    raise ValueError(f"unknown algo {config.algo!r}")


def _ppo_multipass(
    config: Config, apply_fn, optimizer, dist, params, opt_state,
    rollout: Rollout, update_step: jax.Array,
    *,
    axes: tuple[str, ...],  # required: () is now a MEANINGFUL value
    # (population mode, no cross-shard reduction) — a silent default here
    # would turn a forgotten-axes call site into unsynchronized params.
    member_seed: jax.Array | None = None,
    time_axis: str | None = None,
):
    """PPO's real update: ``ppo_epochs`` passes over the fragment, each a
    scan of ``ppo_minibatches`` shuffled minibatch Adam steps (the reference's
    Procgen PPO config, BASELINE.json:10).

    Advantages/returns are computed ONCE under the pre-update params (the
    standard PPO recipe); each minibatch recomputes the ratio against the
    progressively-updated params. Runs inside shard_map: each device shuffles
    its local fragment independently (decorrelated minibatches), while
    gradients and advantage-normalization moments ride the implicit/explicit
    psum over the dp axis, so every device applies identical parameter
    updates.

    Recurrent policies (``rollout.init_core`` present) use SEQUENCE-
    PRESERVING minibatching: the shuffle permutes ENVS, never time — each
    minibatch is a [T, B/mb] block of whole fragments re-forwarded by a
    time scan from its slice of the stored fragment-initial carries (with
    episode-boundary resets), so the core always sees the exact temporal
    structure the behaviour policy generated. Feed-forward keeps the flat
    [T*B] sample shuffle (strictly more decorrelated, and cheaper).

    ``time_axis`` (host-fragment learner on an sp mesh): the fragment's T
    dim is sequence-parallel, so GAE runs as the two-level distributed
    reverse scan and every per-sample quantity is a LOCAL [T_local, B]
    slice. Minibatching needs nothing else: PPO's per-sample loss has no
    cross-time coupling (the only time recursion is the one-shot GAE), so
    each (dp, sp) shard shuffles its local samples independently — the
    global minibatch is time-stratified, the same decorrelation argument
    as the dp-local shuffle above. ``axes`` must then be the FULL reduce
    set (dp axes + time axis), making the loss scaling / advantage moments
    / shuffle-key folding span the time shards like any other data axis.
    Recurrent cores stay excluded from sp meshes (rollout_learner's
    eager check; docs/ARCHITECTURE.md).
    """
    if time_axis is None:
        _, values_all = _forward_fragment(apply_fn, params, rollout)
        values_t, bootstrap_value = values_all[:-1], values_all[-1]
        adv = gae(
            rollout.rewards,
            rollout.discounts(config.gamma),
            jax.lax.stop_gradient(values_t),
            jax.lax.stop_gradient(bootstrap_value),
            config.gae_lambda,
            scan_impl=config.scan_impl,
            fused=config.fused_scan,
        )
    else:
        from asyncrl_tpu.parallel.timeshard import gae_timesharded

        # ``bootstrap_obs`` is replicated over the time axis (same calling
        # contract as rollout_learner._algo_loss_timesharded): every shard
        # computes the tiny bootstrap forward, the distributed scan
        # consumes it on the last shard only.
        _, values_t = apply_fn(params, rollout.obs)
        _, bootstrap_value = apply_fn(params, rollout.bootstrap_obs)
        adv = gae_timesharded(
            rollout.rewards,
            rollout.discounts(config.gamma),
            jax.lax.stop_gradient(values_t),
            jax.lax.stop_gradient(bootstrap_value),
            config.gae_lambda,
            axis_name=time_axis,
        )

    T, B = rollout.actions.shape[:2]
    recurrent = rollout.init_core is not None
    validate_ppo_geometry(
        config, B, "trace-time local", unroll=T, recurrent=recurrent
    )
    mb = config.ppo_minibatches

    # Deterministic per-(step, device, epoch) shuffle key; no PRNG state
    # threads through TrainState.
    # ``member_seed`` (population mode) replaces config.seed so member i's
    # shuffle stream equals a STANDALONE run with seed=member_seed — the
    # exact-equivalence invariant tests/test_population.py asserts.
    seed = config.seed if member_seed is None else member_seed
    base_key = jax.random.fold_in(
        jax.random.PRNGKey(seed + 0x5EB), update_step
    )
    base_key = jax.random.fold_in(base_key, _axis_index(axes))

    def minibatch_step_with(forward):
        def minibatch_step(carry, batch):
            params, opt_state = carry

            def scaled_loss(p):
                logits, values = forward(p, batch)
                loss, metrics = ppo_loss(
                    logits, values, batch["actions"],
                    batch["behaviour_logp"],
                    batch["advantages"], batch["returns"],
                    clip_eps=config.ppo_clip_eps,
                    value_coef=config.value_coef,
                    entropy_coef=entropy_coef_at(config, update_step),
                    axis_name=axes or None,
                    dist=dist,
                    diagnostics=config.introspect,
                )
                metrics = dict(metrics, loss=loss)
                return loss / _axis_size(axes), metrics

            grads, metrics = jax.grad(scaled_loss, has_aux=True)(params)
            grads = reduce_grads(grads, axes, impl=config.grad_reduce)
            metrics["grad_norm"] = optax.global_norm(grads)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), metrics

        return minibatch_step

    if recurrent:
        per_env = {
            "obs": rollout.obs,
            "actions": rollout.actions,
            "behaviour_logp": rollout.behaviour_logp,
            "advantages": jax.lax.stop_gradient(adv.advantages),
            "returns": jax.lax.stop_gradient(adv.returns),
            "done": rollout.done,
        }  # every leaf [T, B, ...]

        def forward(p, batch):
            def fwd(core, inputs):
                obs_t, done_t = inputs
                dist_params, value, new_core = apply_fn(p, obs_t, core)
                return reset_core(new_core, done_t), (dist_params, value)

            _, (logits, values) = jax.lax.scan(
                fwd, batch["init_core"], (batch["obs"], batch["done"])
            )
            return logits, values

        def epoch_step(carry, ekey):
            perm = jax.random.permutation(ekey, B)

            def split_envs(x):  # [T, B, ...] -> [mb, T, B/mb, ...]
                x = x[:, perm].reshape(T, mb, B // mb, *x.shape[2:])
                return jnp.moveaxis(x, 1, 0)

            batches = jax.tree.map(split_envs, per_env)
            batches["init_core"] = jax.tree.map(
                lambda c: c[perm].reshape(mb, B // mb, *c.shape[1:]),
                rollout.init_core,
            )
            return jax.lax.scan(minibatch_step_with(forward), carry, batches)

    else:
        n = T * B
        flat = {
            "obs": rollout.obs.reshape(n, *rollout.obs.shape[2:]),
            "actions": rollout.actions.reshape(n, *rollout.actions.shape[2:]),
            "behaviour_logp": rollout.behaviour_logp.reshape(n),
            "advantages": jax.lax.stop_gradient(adv.advantages).reshape(n),
            "returns": jax.lax.stop_gradient(adv.returns).reshape(n),
        }

        def forward(p, batch):
            return apply_fn(p, batch["obs"])

        def epoch_step(carry, ekey):
            perm = jax.random.permutation(ekey, n)
            batches = jax.tree.map(
                lambda x: x[perm].reshape(mb, n // mb, *x.shape[1:]), flat
            )
            return jax.lax.scan(minibatch_step_with(forward), carry, batches)

    epoch_keys = jax.random.split(base_key, config.ppo_epochs)
    (params, opt_state), metrics = jax.lax.scan(
        epoch_step, (params, opt_state), epoch_keys
    )
    # [E, M, ...] scalars -> means; psum-averaged later by the caller.
    metrics = jax.tree.map(jnp.mean, metrics)
    loss = metrics.pop("loss")
    grad_norm = metrics.pop("grad_norm")
    return params, opt_state, loss, grad_norm, metrics


def qlearn_epsilon_schedule(config: Config, global_env_index, env_frames):
    """THE ε schedule for the async Q-learning family — single source of
    truth for every backend (Anakin's in-jit ``qlearn_epsilon`` and the host
    backends' per-thread ``SebulbaTrainer._epsilon_fn`` both call this, so
    the ladder/anneal can never drift between them).

    Each global env slot gets its own final ε on the Ape-X ladder
    ``eps_base ** (1 + alpha * i / (N-1))`` (the vectorized analogue of the
    A3C paper's per-thread sampled ε), annealed from 1.0 over the first
    ``exploration_steps`` global env frames. Accepts np or jnp inputs;
    returns f32 of ``global_env_index``'s shape."""
    frac = global_env_index / max(config.num_envs - 1, 1)
    final_eps = config.eps_base ** (1.0 + config.eps_alpha * frac)
    anneal = jnp.minimum(
        1.0, env_frames / max(config.exploration_steps, 1)
    )
    return (1.0 + anneal * (final_eps - 1.0)).astype(jnp.float32)


def qlearn_epsilon(
    config: Config, update_step: jax.Array, local_envs: int, axes
) -> jax.Array:
    """Anakin per-shard view of ``qlearn_epsilon_schedule``: global env
    indices from the shard's mesh position, global frames from the update
    counter. Returns [local_envs] f32; constant across one fragment (anneal
    granularity = one update)."""
    gidx = _axis_index(axes) * local_envs + jnp.arange(local_envs)
    env_frames = update_step.astype(jnp.float32) * (
        config.num_envs * config.unroll_len
    )
    return qlearn_epsilon_schedule(
        config, gidx.astype(jnp.float32), env_frames
    )


def validate_ppo_geometry(
    config: Config,
    local_envs: int,
    label: str,
    unroll: int | None = None,
    recurrent: bool = False,
) -> None:
    """One rule, three callers (Learner.__init__, PopulationTrainer,
    _ppo_multipass's trace-time check): a multipass-PPO fragment must split
    evenly into minibatches — flat samples for feed-forward, whole-fragment
    ENV groups for recurrent (sequence-preserving minibatching never splits
    the time axis). The trace-time caller passes the ACTUAL fragment length
    as ``unroll`` (host-fed rollouts can differ from config.unroll_len);
    eager callers omit it."""
    if config.algo == "ppo" and (
        config.ppo_epochs > 1 or config.ppo_minibatches > 1
    ):
        if recurrent:
            if local_envs % config.ppo_minibatches:
                raise ValueError(
                    f"{label}: recurrent multipass PPO minibatches over "
                    f"envs (time is never split), but {local_envs} envs "
                    f"are not divisible by "
                    f"ppo_minibatches={config.ppo_minibatches}"
                )
            return
        frag = local_envs * (
            config.unroll_len if unroll is None else unroll
        )
        if frag % config.ppo_minibatches:
            raise ValueError(
                f"{label} fragment of {frag} samples not divisible by "
                f"ppo_minibatches={config.ppo_minibatches}"
            )


def derive_init_keys(key: jax.Array) -> tuple[jax.Array, jax.Array]:
    """The one canonical (params key, actor key) split for a training run.

    Shared by ``Learner.init_state`` AND ``PopulationTrainer._member_init``:
    a population member with seed s must reproduce a standalone run with
    seed s bit-for-bit (tests/test_population.py), so the derivation lives
    in exactly one place.
    """
    return tuple(jax.random.split(key))


def init_params(model, env: Environment, pkey: jax.Array):
    """Canonical model init for a training run (see derive_init_keys)."""
    dummy_obs = jnp.zeros((1, *env.spec.obs_shape), env.spec.obs_dtype)
    if is_recurrent(model):
        return model.init(pkey, dummy_obs, model.initial_core(1))
    return model.init(pkey, dummy_obs)


def fuse_updates(body: Callable, updates_per_call: int) -> Callable:
    """Fuse K sequential train-step updates into ONE XLA program via
    ``lax.scan`` — zero host dispatch between them (the amortization that
    matters on a high-latency device link; bench.py's measured ~8 ms/call
    tunnel round trip). Metrics leaves stack to [K].

    Shared by Learner (single-run) and PopulationTrainer (vmapped members —
    VERDICT r2 Next #4): extra positional args (e.g. the member seed) pass
    through to every fused step unchanged.
    """
    if updates_per_call <= 1:
        return body

    def multi_step(state: TrainState, *args):
        return jax.lax.scan(
            lambda s, _: body(s, *args), state, None,
            length=updates_per_call,
        )

    return multi_step


def _chunk_envs(rollout, n: int):
    """Reshape a fragment into ``n`` env-axis chunks with a leading scan
    axis: time-major leaves [T, B, ...] -> [n, T, B/n, ...], batch-major
    leaves (bootstrap_obs, init_core) [B, ...] -> [n, B/n, ...]. Chunks
    are whole envs — time stays intact, so V-trace/GAE per-env scans are
    untouched; only the batch mean is split (see grad_accum)."""

    def tm(x):
        return jnp.moveaxis(
            x.reshape(x.shape[0], n, -1, *x.shape[2:]), 1, 0
        )

    def bm(x):
        return x.reshape(n, -1, *x.shape[1:])

    return rollout.replace(
        obs=tm(rollout.obs),
        actions=tm(rollout.actions),
        behaviour_logp=tm(rollout.behaviour_logp),
        rewards=tm(rollout.rewards),
        terminated=tm(rollout.terminated),
        truncated=tm(rollout.truncated),
        bootstrap_obs=bm(rollout.bootstrap_obs),
        init_core=jax.tree.map(bm, rollout.init_core),
        disc_returns=jax.tree.map(tm, rollout.disc_returns),
    )


def validate_grad_accum_config(config: Config, envs_per_shard: int) -> None:
    """grad_accum must split the per-shard env axis into equal whole
    chunks (equality of chunk means is what makes the summed gradient
    exact), and is refused for PPO entirely: multipass PPO has
    ppo_minibatches as the same memory lever, and single-pass PPO
    normalizes advantages over the batch — chunk-local moments would
    silently change the gradient, breaking grad_accum's exactness
    contract."""
    if config.grad_accum <= 1:
        return
    if config.algo == "ppo":
        raise ValueError(
            "grad_accum > 1 is not supported for PPO: advantage"
            " normalization computes batch moments, which chunking would"
            " silently localize. Use ppo_minibatches — PPO's native"
            " microbatching knob — instead."
        )
    if envs_per_shard % config.grad_accum != 0:
        raise ValueError(
            f"grad_accum={config.grad_accum} must divide the per-shard env"
            f" count ({envs_per_shard}): unequal chunks would bias the"
            " accumulated gradient."
        )


def accumulate_grads(scaled_loss, params, rollout, n_accum: int):
    """Microbatched gradient: scan over env-axis chunks (``_chunk_envs``),
    summing per-chunk grads of ``scaled_loss(params, chunk)``. Each chunk's
    backward materializes only its own activations, so peak HBM drops
    ~n_accum-fold; the summed gradient equals the full-batch one exactly
    (equal chunks + the caller's 1/n_accum loss scaling). Losses/metrics
    are per-env means, so the chunk mean recovers the batch mean. Chunk
    count is identical on every shard, so per-chunk collectives (e.g.
    time-sharded V-trace psums) stay in lockstep across the mesh.

    Shared by the Anakin train step and the host-fragment RolloutLearner —
    the two must never diverge. Returns ``(grads, loss, metrics)``."""

    def accum_body(g_acc, frag):
        (_, aux), g = jax.value_and_grad(scaled_loss, has_aux=True)(
            params, frag
        )
        return jax.tree.map(jnp.add, g_acc, g), aux

    grads, (loss_k, metrics_k) = jax.lax.scan(
        accum_body,
        jax.tree.map(jnp.zeros_like, params),
        _chunk_envs(rollout, n_accum),
    )
    return (
        grads,
        jnp.mean(loss_k),
        jax.tree.map(lambda m: jnp.mean(m, 0), metrics_k),
    )


def make_train_step(
    config: Config,
    env: Environment,
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axes: tuple[str, ...] | None = None,
) -> Callable[[TrainState], tuple[TrainState, dict[str, jax.Array]]]:
    """Build the per-shard train-step body (to be wrapped in shard_map).

    ``axes`` defaults to the mesh's data-parallel axes; pass ``()`` for a
    fully self-contained body with no cross-shard reduction (population
    mode: each vmapped member is its own training run).
    """
    from asyncrl_tpu.ops import distributions

    dist = distributions.for_config(config, env.spec)

    # Static choice: PPO with epochs/minibatches > 1 takes the multipass
    # update path; everything else is one fused gradient step.
    ppo_multipass = config.algo == "ppo" and (
        config.ppo_epochs > 1 or config.ppo_minibatches > 1
    )
    qlearn = config.algo == "qlearn"

    if axes is None:
        axes = dp_axes(mesh)

    def train_step(state: TrainState, member_seed: jax.Array | None = None):
        # ``member_seed``: population mode only (api/population.py) — the
        # per-member integer seed whose standalone run this member must
        # reproduce exactly. None everywhere else.
        # named_scope: sections show up as labeled blocks in jax.profiler
        # traces (SURVEY.md §5.1; CLI --profile).
        # Observation normalization: behaviour, learner, and (this step's)
        # target forwards all see the SAME pre-update stats; the stats fold
        # in this rollout's observations afterwards, for the next step.
        napply = normalizing_apply(apply_fn, state.obs_stats)
        dist_extra = None
        if qlearn:
            # ε rides the dist_params channel (ops.distributions
            # .EpsilonGreedy): per-env final values, annealed by env frames.
            eps = qlearn_epsilon(
                config, state.update_step, state.actor.keys.shape[0], axes
            )
            dist_extra = eps[:, None]
        with jax.named_scope("rollout"):
            actor, rollout, stats = unroll(
                napply, state.actor_params, env, state.actor,
                config.unroll_len, dist=dist, reward_scale=config.reward_scale,
                step_cost=config.step_cost,
                dist_extra=dist_extra,
                return_discount=(
                    config.gamma if config.normalize_returns else 0.0
                ),
                opponent_params=(
                    state.opponent_params if config.selfplay else None
                ),
            )
        if config.normalize_returns:
            # Scale this fragment's rewards by the PRE-update return std
            # (mean is NOT subtracted — shifting rewards changes the MDP);
            # fold the fragment's discounted-return stream in afterwards.
            ret_var = state.ret_stats.m2 / state.ret_stats.count
            rollout = rollout.replace(
                rewards=rollout.rewards
                * jax.lax.rsqrt(jnp.maximum(ret_var, 1e-8))
            )

        if ppo_multipass:
            with jax.named_scope("ppo_multipass"):
                params, opt_state, loss, grad_norm, metrics = _ppo_multipass(
                    config, napply, optimizer, dist,
                    state.params, state.opt_state, rollout, state.update_step,
                    axes=axes, member_seed=member_seed,
                )
        else:
            # shard_map autodiff semantics (jax>=0.8 vma tracking): the
            # gradient of a REPLICATED input (params) w.r.t. a device-varying
            # loss is automatically psum'd across the mesh axis during
            # transposition. So we scale the per-shard loss by 1/axis_size —
            # the implicit psum of local-mean gradients then yields exactly
            # the global-batch-mean gradient, with no explicit pmean(grads)
            # (which would double-count: verified 8x inflation on the
            # 8-device CPU mesh, tests/test_learner).
            n_accum = max(config.grad_accum, 1)

            def scaled_loss(p, frag):
                loss, metrics = _algo_loss(
                    config, napply, p, frag,
                    axis_name=axes or None, dist=dist,
                    target_params=state.actor_params,
                    entropy_coef=entropy_coef_at(config, state.update_step),
                )
                return loss / (_axis_size(axes) * n_accum), (loss, metrics)

            if n_accum == 1:
                with jax.named_scope("loss_and_grad"):
                    (_, (loss, metrics)), grads = jax.value_and_grad(
                        scaled_loss, has_aux=True
                    )(state.params, rollout)
            else:
                with jax.named_scope("loss_and_grad_accum"):
                    grads, loss, metrics = accumulate_grads(
                        scaled_loss, state.params, rollout, n_accum
                    )
            grads = reduce_grads(grads, axes, impl=config.grad_reduce)
            with jax.named_scope("optimizer"):
                grad_norm = optax.global_norm(grads)
                updates, opt_state = optimizer.update(
                    grads, state.opt_state, state.params
                )
                params = optax.apply_updates(state.params, updates)

        metrics = _pmean(metrics, axes)
        loss = _pmean(loss, axes)

        step = state.update_step + 1
        if (
            config.algo in ("impala", "qlearn")
            and config.actor_staleness > 1
        ):
            # IMPALA: the stale behaviour-policy copy. Q-learning: the SAME
            # stale copy doubles as the target network θ⁻ (and the ε-greedy
            # behaviour net), so actor_staleness is the target-update period.
            refresh = (step % config.actor_staleness) == 0
            actor_params = jax.tree.map(
                lambda new, old: jnp.where(refresh, new, old),
                params, state.actor_params,
            )
        else:
            # On-policy (and staleness<=1 IMPALA): actors always see the
            # newest weights next fragment — one full update of lag, the
            # minimum true-IMPALA staleness.
            actor_params = params

        obs_stats = state.obs_stats
        if obs_stats is not None:
            with jax.named_scope("obs_stats"):
                obs_stats = update_stats(obs_stats, rollout.obs, axes)
        ret_stats = state.ret_stats
        if ret_stats is not None:
            ret_stats = update_stats(ret_stats, rollout.disc_returns, axes)

        if config.selfplay:
            # Ladder refresh: the frozen rival becomes the CURRENT policy
            # every selfplay_refresh updates (same select pattern as the
            # actor_params staleness refresh).
            promote = (step % max(config.selfplay_refresh, 1)) == 0
            opponent_params = jax.tree.map(
                lambda new, old: jnp.where(promote, new, old),
                params, state.opponent_params,
            )
            if actor.opp_core is not None:
                # The rival's recurrent carry belongs to the OLD snapshot;
                # on promotion zero it (mid-episode amnesia beats feeding
                # the new params a foreign hidden state).
                keep = 1.0 - promote.astype(jnp.float32)
                actor = actor.replace(
                    opp_core=jax.tree.map(
                        lambda c: c * keep, actor.opp_core
                    )
                )
        else:
            opponent_params = state.opponent_params  # None subtree

        metrics = dict(metrics)
        metrics["loss"] = loss
        metrics["grad_norm"] = grad_norm
        metrics["episode_return_sum"] = _psum(stats.completed_return_sum, axes)
        metrics["episode_length_sum"] = _psum(stats.completed_length_sum, axes)
        metrics["episode_count"] = _psum(stats.completed_count, axes)

        new_state = TrainState(
            params=params,
            actor_params=actor_params,
            opt_state=opt_state,
            actor=actor,
            update_step=step,
            obs_stats=obs_stats,
            ret_stats=ret_stats,
            opponent_params=opponent_params,
        )
        return new_state, metrics

    return train_step


class Learner:
    """Owns the compiled train step and the train state lifecycle.

    Name parity with the reference's ``Learner`` (BASELINE.json:5); its
    ``update`` method is one mesh-wide fused step.
    """

    def __init__(
        self,
        config: Config,
        env: Environment,
        model,
        mesh: Mesh,
    ):
        config = resolve_scan_impl(config, mesh)
        self.config = config
        self.env = env
        self.model = model
        self.mesh = mesh
        self.optimizer = make_optimizer(config)

        # Eager geometry validation (clearer than a trace-time failure).
        validate_recurrent_config(config, model)
        validate_qlearn_config(config)
        validate_selfplay_config(config, env, model)
        if config.updates_per_call < 1:
            raise ValueError(
                f"updates_per_call={config.updates_per_call} must be >= 1"
            )
        dp = dp_size(mesh)
        if config.num_envs % dp:
            raise ValueError(
                f"num_envs={config.num_envs} not divisible by dp={dp}"
            )
        validate_ppo_geometry(
            config, config.num_envs // dp, "per-device",
            recurrent=is_recurrent(model),
        )
        validate_grad_accum_config(config, config.num_envs // dp)

        spec = state_partition_spec(dp_axes(mesh))
        body = make_train_step(config, env, model.apply, self.optimizer, mesh)

        wrapped = fuse_updates(body, config.updates_per_call)

        self._step = jax.jit(
            shard_map(
                wrapped, mesh=mesh, in_specs=(spec,), out_specs=(spec, P()),
                **fused_smap_opts(config),
            ),
            donate_argnums=(0,) if config.donate_buffers else (),
        )

    def init_state(self, seed: int) -> TrainState:
        """Build the initial TrainState with proper shardings."""
        cfg = self.config
        dp = dp_size(self.mesh)
        if cfg.num_envs % dp:
            raise ValueError(
                f"num_envs={cfg.num_envs} not divisible by dp={dp}"
            )
        key = jax.random.PRNGKey(seed)
        pkey, akey = derive_init_keys(key)
        params = init_params(self.model, self.env, pkey)
        opt_state = self.optimizer.init(params)

        # Per-device actor init inside shard_map so env states are born
        # sharded (no host-side giant arrays for big env batches).
        local_envs = cfg.num_envs // dp
        axes = dp_axes(self.mesh)

        def shard_actor_init(keys):
            return actor_init(
                self.env, local_envs, keys[0], model=self.model,
                track_returns=cfg.normalize_returns,
                selfplay=cfg.selfplay,
            )

        per_device_keys = jax.random.split(akey, dp)
        actor = jax.jit(
            shard_map(
                shard_actor_init,
                mesh=self.mesh,
                in_specs=(P(axes),),
                out_specs=P(axes),
            )
        )(per_device_keys)

        obs_stats = (
            init_stats(self.env.spec.obs_shape) if cfg.normalize_obs else None
        )
        ret_stats = init_stats(()) if cfg.normalize_returns else None
        # Place replicated leaves explicitly on the mesh.
        from jax.sharding import NamedSharding

        rep = NamedSharding(self.mesh, P())
        return TrainState(
            params=jax.device_put(params, rep),
            actor_params=jax.device_put(params, rep),
            opt_state=jax.device_put(opt_state, rep),
            actor=actor,
            update_step=jax.device_put(jnp.zeros((), jnp.int32), rep),
            obs_stats=(
                None if obs_stats is None else jax.device_put(obs_stats, rep)
            ),
            ret_stats=(
                None if ret_stats is None else jax.device_put(ret_stats, rep)
            ),
            opponent_params=(
                jax.device_put(params, rep) if cfg.selfplay else None
            ),
        )

    def update(self, state: TrainState):
        """One train step: rollout + loss + pmean(grads) + Adam. Donates
        ``state``."""
        return self._step(state)
