from asyncrl_tpu.learn.learner import (
    Learner,
    TrainState,
    make_optimizer,
    make_train_step,
    state_partition_spec,
)

__all__ = [
    "Learner",
    "TrainState",
    "make_optimizer",
    "make_train_step",
    "state_partition_spec",
]
