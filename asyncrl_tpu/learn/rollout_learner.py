"""Learner that consumes host-produced rollout fragments (Sebulba path).

The Anakin ``Learner`` (learn/learner.py) fuses rollout + update into one XLA
program because its envs live in HBM. The Sebulba and ``cpu_async`` backends
instead produce ``Rollout`` fragments on the host (C++ env pools / gymnasium /
Python actor threads — SURVEY.md §7.2 M3-M4), so this learner exposes the
other half only: ``update(state, rollout)`` — one jitted ``shard_map`` over
the mesh that recomputes learner logits/values, applies the configured
algorithm loss (A3C / IMPALA V-trace / PPO), all-reduces gradients over the
``dp`` axis, and steps Adam. The rollout arrives batch-sharded (``[T, B]``
with B split over dp), mirroring how the reference's learner consumed
queue-batched fragments (BASELINE.json:5; SURVEY.md §3.2).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from asyncrl_tpu.envs.core import EnvSpec
from asyncrl_tpu.learn.learner import (
    _algo_loss,
    _ppo_multipass,
    accumulate_grads,
    entropy_coef_at,
    fused_smap_opts,
    make_optimizer,
    qlearn_bootstrap,
    resolve_scan_impl,
    validate_grad_accum_config,
    validate_qlearn_config,
    validate_recurrent_config,
)
from asyncrl_tpu.learn.replay import validate_replay_config
from asyncrl_tpu.models.networks import is_recurrent
from asyncrl_tpu.obs import introspect
from asyncrl_tpu.obs import spans as span_names
from asyncrl_tpu.obs import trace
from asyncrl_tpu.ops import distributions
from asyncrl_tpu.ops.losses import (
    a3c_loss,
    impala_loss,
    ppo_loss,
    qlearn_loss,
)
from asyncrl_tpu.ops.normalize import (
    init_stats,
    normalizing_apply,
    update_stats,
)
from asyncrl_tpu.parallel.mesh import (
    TIME_AXIS,
    axis_size,
    dp_axes,
    dp_size,
    reduce_grads,
    shard_map,
)
from asyncrl_tpu.parallel.timeshard import (
    gae_timesharded,
    n_step_returns_timesharded,
    vtrace_timesharded,
)
from asyncrl_tpu.rollout.buffer import Rollout
from asyncrl_tpu.utils.config import Config


@struct.dataclass
class LearnerState:
    """Learner-side train state for host-rollout backends.

    Unlike the Anakin ``TrainState`` there is no ``actor`` (env states live
    on the host) and no ``actor_params`` (weight publishing to host actors
    goes through ``rollout.sebulba.ParamStore``). ``target_params`` is the
    Q-learning family's target network θ⁻ (None — an empty subtree — for
    the policy-gradient algos): unlike Anakin, where the in-program
    actor_params copy doubles as the target, the host path's behaviour
    params live outside the jit, so the target needs its own slot.
    """

    params: Any
    opt_state: Any
    update_step: jax.Array  # int32 scalar
    target_params: Any = None
    # Running observation-normalization stats (ops/normalize.py); None
    # unless config.normalize_obs. Published to host actors alongside the
    # params (SebulbaTrainer bundles them through the ParamStore).
    obs_stats: Any = None
    # Running scalar stats of the per-env discounted return (reward
    # normalization, config.normalize_returns); None when disabled.
    ret_stats: Any = None


def learner_state_spec() -> LearnerState:
    return LearnerState(
        params=P(), opt_state=P(), update_step=P(), target_params=P(),
        obs_stats=P(), ret_stats=P(),
    )


def rollout_partition_spec(
    axes: tuple[str, ...], time_axis: str | None = None, stacked: bool = False
) -> Rollout:
    """Time-major [T, B, ...] fragments, batch dim sharded over all
    data-parallel axes; with ``time_axis`` set (sequence parallelism,
    SURVEY.md §5.7) the T dim shards over it too. ``stacked`` prepends an
    unsharded leading axis for [K, T, B, ...] fused-update stacks
    (``updates_per_call``). ``init_core``'s P is a pytree PREFIX: it
    applies to every leaf of the recurrent (c, h) carry when present, and
    to nothing for feed-forward fragments (None = empty subtree)."""
    lead = (None,) if stacked else ()
    tm = P(*lead, time_axis, axes)
    bf = P(*lead, axes)
    return Rollout(
        obs=tm,
        actions=tm,
        behaviour_logp=tm,
        rewards=tm,
        terminated=tm,
        truncated=tm,
        bootstrap_obs=bf,
        init_core=bf,
        disc_returns=tm,
    )


def rollout_sharding(
    mesh: Mesh, rollout: Rollout, stacked: bool = False
) -> Rollout:
    """NamedShardings for ``jax.device_put`` of one host fragment (or a
    [K, ...] fused stack) — built against the fragment's own pytree
    structure (device_put needs an exact structural match, unlike
    shard_map's prefix specs)."""
    axes = dp_axes(mesh)
    time_axis = TIME_AXIS if TIME_AXIS in mesh.axis_names else None
    lead = (None,) if stacked else ()
    time_major = NamedSharding(mesh, P(*lead, time_axis, axes))
    batch_first = NamedSharding(mesh, P(*lead, axes))
    return Rollout(
        obs=time_major,
        actions=time_major,
        behaviour_logp=time_major,
        rewards=time_major,
        terminated=time_major,
        truncated=time_major,
        bootstrap_obs=batch_first,
        init_core=(
            None
            if rollout.init_core is None
            else jax.tree.map(lambda _: batch_first, rollout.init_core)
        ),
        disc_returns=(
            None if rollout.disc_returns is None else time_major
        ),
    )


def _algo_loss_timesharded(
    config: Config, apply_fn, params, rollout: Rollout, *, reduce_axes, dist,
    target_params=None, entropy_coef=None,
):
    """Time-sharded variant of ``learner._algo_loss``: runs inside shard_map
    with the fragment's T dim sharded over ``TIME_AXIS`` (SURVEY.md §5.7).
    Every input is the LOCAL [T_local, B_local] segment; the reverse
    recurrences run as two-level distributed scans with one-hop ``ppermute``
    boundary exchanges (parallel/timeshard.py). Returned loss/metrics are
    local means — the caller pmean's them over ``reduce_axes`` (which
    includes the time axis), and equal-sized shards make that the global
    mean."""
    if entropy_coef is None:
        entropy_coef = config.entropy_coef
    logits_t, values_t = apply_fn(params, rollout.obs)
    # ``bootstrap_obs`` is replicated over the time axis; every shard
    # computes the (tiny) bootstrap forward, only the last consumes it.
    boot_logits, bootstrap_value = apply_fn(params, rollout.bootstrap_obs)
    bootstrap_value = jax.lax.stop_gradient(bootstrap_value)
    discounts = rollout.discounts(config.gamma)

    if config.algo == "qlearn":
        # Same construction as the unsharded branch, via the same shared
        # pieces: online Q locally per time shard, the shared
        # ``qlearn_bootstrap`` target selection, the distributed
        # n-step-return solve, and the canonical ``qlearn_loss`` fed the
        # precomputed returns (its ``returns=`` kwarg, like a3c's).
        q_target = apply_fn(target_params, rollout.bootstrap_obs)[0]
        boot = qlearn_bootstrap(config, boot_logits, q_target)
        returns = n_step_returns_timesharded(
            rollout.rewards, discounts, boot
        )
        return qlearn_loss(
            logits_t, rollout.actions, rollout.rewards, discounts, boot,
            returns=returns, huber_delta=config.huber_delta,
        )
    if config.algo == "a3c":
        returns = n_step_returns_timesharded(
            rollout.rewards, discounts, bootstrap_value
        )
        return a3c_loss(
            logits_t, values_t, rollout.actions, rollout.rewards, discounts,
            bootstrap_value, value_coef=config.value_coef,
            entropy_coef=entropy_coef, dist=dist, returns=returns,
            diagnostics=config.introspect,
        )
    if config.algo == "impala":
        target_logp = dist.logp(logits_t, rollout.actions)
        vt = vtrace_timesharded(
            rollout.behaviour_logp, target_logp, rollout.rewards, discounts,
            jax.lax.stop_gradient(values_t), bootstrap_value,
            rho_clip=config.vtrace_rho_clip, c_clip=config.vtrace_c_clip,
        )
        # The clip fractions come back already pmean'd over the time axis
        # (sp-invariant); re-mark them sp-varying so the caller's uniform
        # pmean over (dp axes + sp) is legal under vma tracking.
        vt = vt._replace(
            rho_clip_frac=jax.lax.pcast(
                vt.rho_clip_frac, TIME_AXIS, to="varying"
            ),
            c_clip_frac=jax.lax.pcast(
                vt.c_clip_frac, TIME_AXIS, to="varying"
            ),
        )
        return impala_loss(
            logits_t, values_t, rollout.actions, rollout.behaviour_logp,
            rollout.rewards, discounts, bootstrap_value,
            value_coef=config.value_coef, entropy_coef=entropy_coef,
            rho_clip=config.vtrace_rho_clip, c_clip=config.vtrace_c_clip,
            dist=dist, vtrace_out=vt,
            diagnostics=config.introspect,
        )
    if config.algo == "ppo":
        adv = gae_timesharded(
            rollout.rewards, discounts, jax.lax.stop_gradient(values_t),
            bootstrap_value, config.gae_lambda,
        )
        return ppo_loss(
            logits_t, values_t, rollout.actions, rollout.behaviour_logp,
            adv.advantages, adv.returns, clip_eps=config.ppo_clip_eps,
            value_coef=config.value_coef, entropy_coef=entropy_coef,
            axis_name=reduce_axes, dist=dist,
            diagnostics=config.introspect,
        )
    raise ValueError(f"unknown algo {config.algo!r} for time sharding")


class RolloutLearner:
    """Compiled ``update(state, rollout)`` step + state lifecycle.

    Same loss/optimizer machinery as the Anakin learner (single source of
    truth in learn/learner.py), minus the on-device unroll.
    """

    def __init__(self, config: Config, spec: EnvSpec, model, mesh: Mesh):
        validate_recurrent_config(config, model)
        validate_qlearn_config(config)
        validate_replay_config(config)
        # IMPACT mode (learn/replay.py; arXiv:1912.00167): with the
        # device replay ring armed, every update — fresh or replayed —
        # runs under the clipped-target-network importance anchor, and
        # the target net refreshes every target_update_period updates.
        # Off (the default) traces NONE of it: bit-identical program.
        replay_mode = config.replay_slabs > 0
        # Host fragments arrive with the FULL env batch on the sharded-in
        # time/batch layout; the per-shard env count the chunker sees is
        # num_envs / (product of dp axes).
        validate_grad_accum_config(
            config, config.num_envs // max(dp_size(mesh), 1)
        )
        if config.selfplay:
            raise NotImplementedError(
                "selfplay is Anakin-only (backend='tpu'): host actor "
                "threads have no opponent-snapshot channel"
            )
        ppo_multipass = config.algo == "ppo" and (
            config.ppo_epochs > 1 or config.ppo_minibatches > 1
        )
        time_sharded = TIME_AXIS in mesh.axis_names and mesh.shape[TIME_AXIS] > 1
        if time_sharded:
            sp = mesh.shape[TIME_AXIS]
            if config.unroll_len % sp:
                raise ValueError(
                    f"unroll_len={config.unroll_len} not divisible by the "
                    f"time-shard axis sp={sp}"
                )
            if is_recurrent(model):
                raise NotImplementedError(
                    "recurrent cores cannot be time-sharded: an LSTM carry "
                    "composes nonlinearly, so unlike the affine V-trace/GAE "
                    "recurrences it has no exact parallel decomposition — "
                    "a time-sharded LSTM degenerates to a pipeline that "
                    "re-serializes the sp axis (full rationale: "
                    "docs/ARCHITECTURE.md, 'Recurrent cores are "
                    "deliberately NOT time-shardable'). Use a dp-only mesh "
                    "for core='lstm'"
                )
            # Multipass PPO time-shards fine (PPO's per-sample loss has no
            # cross-time coupling; only the one-shot GAE recurses —
            # _ppo_multipass's time_axis path). Minibatch geometry is NOT
            # eager-checked here: this learner never knows the fragment's
            # env batch (SebulbaTrainer feeds per-actor fragments) — the
            # trainer runs the sp-aware eager check with the real B, and
            # _ppo_multipass re-validates the local slice at trace time.
            # (qlearn time-shards via n_step_returns_timesharded; its
            # recurrent DRQN variant is excluded by the is_recurrent check
            # above like every recurrent core.)
        config = resolve_scan_impl(config, mesh)
        self.config = config
        self.spec = spec
        self.model = model
        self.mesh = mesh
        self.optimizer = make_optimizer(config)
        dist = distributions.for_config(config, spec)
        apply_fn = model.apply
        optimizer = self.optimizer

        axes = dp_axes(mesh)
        # Gradient/metric reduction spans every axis the fragment is
        # sharded over: batch axes always, plus the time axis when the
        # fragment's T dim is sequence-parallel.
        reduce_axes = axes + ((TIME_AXIS,) if time_sharded else ())
        # Divergence NaN-guard (runtime/durability.py rollback policy):
        # armed with the policy, a non-finite loss/grad_norm HOLDS the
        # entire state — params, opt state, target net, normalization
        # stats, and the update counter — via a device-side select, so a
        # poisoned update never lands and the guard costs no host sync.
        # The metrics still report the bad loss (the nonfinite_loss
        # detector must fire) plus a ``nonfinite_skip`` flag the trainer
        # accumulates into the cumulative ``nonfinite_skips`` counter.
        # Off (the default) the select never traces: bit-identical
        # program to the pre-rollback learner.
        nan_guard = config.rollback_bad_windows > 0

        def update_body(state: LearnerState, rollout: Rollout):
            # Observation normalization (ops/normalize.py): this step's
            # forwards all use the pre-update stats; the fragment's obs
            # fold in afterwards. Reward normalization likewise scales this
            # fragment by the PRE-update return std.
            napply = normalizing_apply(apply_fn, state.obs_stats)
            if config.normalize_returns:
                ret_var = state.ret_stats.m2 / state.ret_stats.count
                rollout = rollout.replace(
                    rewards=rollout.rewards
                    * jax.lax.rsqrt(jnp.maximum(ret_var, 1e-8))
                )
            target_kl = None
            if replay_mode:
                # IMPACT-style ratio anchoring: the slowly-updated
                # target network's log-probs FLOOR the behaviour
                # log-prob, so the V-trace importance ratio rho = pi/mu
                # never exceeds replay_rho_clip * pi/pi_target — a slab
                # reused across many updates (its mu frozen ever further
                # in the past) keeps a bounded correction anchored to a
                # policy at most target_update_period updates old,
                # instead of an unbounded one anchored to a dead mu.
                # Constant w.r.t. the differentiated params (target
                # forward under stop_gradient, applied before the loss).
                t_logits, _ = napply(state.target_params, rollout.obs)
                target_logp = jax.lax.stop_gradient(
                    dist.logp(t_logits, rollout.actions)
                )
                # Behaviour-vs-target divergence proxy E_mu[log mu -
                # log pi_target] (the existing ``kl`` aux's recipe, with
                # the target net in the learner's seat): it bounds how
                # much anchoring the clip below is actually doing.
                target_kl = jnp.mean(
                    rollout.behaviour_logp - target_logp
                )
                rollout = rollout.replace(
                    behaviour_logp=jnp.maximum(
                        rollout.behaviour_logp,
                        target_logp - math.log(config.replay_rho_clip),
                    )
                )
            if ppo_multipass:
                # ``axes=reduce_axes``: on an sp mesh the shuffle keys,
                # loss scaling, and advantage moments must span the time
                # shards too (== axes on a dp-only mesh).
                params, opt_state, loss, grad_norm, metrics = _ppo_multipass(
                    config, napply, optimizer, dist,
                    state.params, state.opt_state, rollout, state.update_step,
                    axes=reduce_axes,
                    time_axis=TIME_AXIS if time_sharded else None,
                )
            else:
                # Same implicit-psum gradient scaling as the Anakin step:
                # replicated-param grads are psum'd across every sharded
                # axis during transposition, so local loss is scaled by
                # 1/axis_size of ALL of them.
                n_accum = max(config.grad_accum, 1)

                def scaled_loss(p, frag):
                    ec = entropy_coef_at(config, state.update_step)
                    # fused_scan reaches the non-timesharded branch through
                    # _algo_loss/config; the timesharded variants keep the
                    # two-level lax decomposition — the fused kernel's
                    # whole-T recurrence has no sp-sharded form, so
                    # fused_scan applies only to an unsharded time axis.
                    if time_sharded:
                        loss, metrics = _algo_loss_timesharded(
                            config, napply, p, frag,
                            reduce_axes=reduce_axes, dist=dist,
                            target_params=state.target_params,
                            entropy_coef=ec,
                        )
                    else:
                        loss, metrics = _algo_loss(
                            config, napply, p, frag,
                            axis_name=axes, dist=dist,
                            target_params=state.target_params,
                            entropy_coef=ec,
                        )
                    return (
                        loss / (axis_size(reduce_axes) * n_accum),
                        (loss, metrics),
                    )

                if n_accum == 1:
                    (_, (loss, metrics)), grads = jax.value_and_grad(
                        scaled_loss, has_aux=True
                    )(state.params, rollout)
                else:
                    grads, loss, metrics = accumulate_grads(
                        scaled_loss, state.params, rollout, n_accum
                    )
                grads = reduce_grads(grads, reduce_axes, impl=config.grad_reduce)
                grad_norm = optax.global_norm(grads)
                updates, opt_state = optimizer.update(
                    grads, state.opt_state, state.params
                )
                params = optax.apply_updates(state.params, updates)

            metrics = dict(jax.lax.pmean(metrics, reduce_axes))
            metrics["loss"] = jax.lax.pmean(loss, reduce_axes)
            metrics["grad_norm"] = grad_norm
            if target_kl is not None:
                metrics["target_kl"] = jax.lax.pmean(
                    target_kl, reduce_axes
                )
            step = state.update_step + 1
            if config.algo == "qlearn":
                # Target-network refresh every actor_staleness updates
                # (same recipe as the Anakin learner's actor_params).
                refresh = (step % config.actor_staleness) == 0
                target_params = jax.tree.map(
                    lambda new, old: jnp.where(refresh, new, old),
                    params, state.target_params,
                )
            elif replay_mode:
                # The IMPACT anchor refreshes on its own period — the
                # qlearn recipe with the replay knob, so the anchor is
                # never more than target_update_period updates stale.
                refresh = (step % config.target_update_period) == 0
                target_params = jax.tree.map(
                    lambda new, old: jnp.where(refresh, new, old),
                    params, state.target_params,
                )
            else:
                target_params = state.target_params  # None subtree
            obs_stats = state.obs_stats
            if obs_stats is not None:
                obs_stats = update_stats(
                    obs_stats, rollout.obs, reduce_axes
                )
            ret_stats = state.ret_stats
            if ret_stats is not None:
                ret_stats = update_stats(
                    ret_stats, rollout.disc_returns, reduce_axes
                )
            new_state = LearnerState(
                params=params,
                opt_state=opt_state,
                update_step=step,
                target_params=target_params,
                obs_stats=obs_stats,
                ret_stats=ret_stats,
            )
            if nan_guard:
                finite = jnp.isfinite(metrics["loss"]) & jnp.isfinite(
                    metrics["grad_norm"]
                )
                new_state = jax.tree.map(
                    lambda new, old: jnp.where(finite, new, old),
                    new_state, state,
                )
                metrics["nonfinite_skip"] = 1.0 - finite.astype(jnp.float32)
            return new_state, metrics

        K = config.updates_per_call
        if K < 1:
            raise ValueError(f"updates_per_call={K} must be >= 1")
        if K > 1:
            # Fuse K sequential updates into ONE dispatch: the trainer
            # stacks K queued fragments [K, T, B, ...] and the scan applies
            # them in arrival order — identical training semantics, one
            # host->device round trip instead of K (the dominant cost on a
            # high-latency device link; VERDICT.md round 1, Weak #4).
            # Metrics come back stacked [K].
            single_body = update_body

            def update_body(state: LearnerState, stacked: Rollout):
                return jax.lax.scan(single_body, state, stacked)

        sspec = learner_state_spec()
        # NEVER donate the STATE, regardless of config.donate_buffers: the
        # params in it are published to concurrently-running actor threads
        # via ParamStore; donation would delete buffers mid-inference
        # ("Array has been deleted" in every actor). The Anakin learner can
        # donate because its params never escape the update loop.
        # The ROLLOUT argument is donatable under config.donate_buffers:
        # it is consumed exactly once, and the trainer's drain never
        # touches the device fragment after dispatching the update (the
        # staging ring gates host-slab reuse on the update's OUTPUT, so
        # deletion of the consumed input is invisible to it).
        self._step = jax.jit(
            shard_map(
                update_body,
                mesh=mesh,
                in_specs=(
                    sspec,
                    rollout_partition_spec(
                        axes, TIME_AXIS if time_sharded else None,
                        stacked=K > 1,
                    ),
                ),
                out_specs=(sspec, P()),
                **fused_smap_opts(config),
            ),
            donate_argnums=(1,) if config.donate_buffers else (),
        )
        if config.introspect:
            # Compile accounting (obs/introspect.py): the learner's entry
            # point compiles once per fragment geometry — any further
            # compile is a silent recompile the bench numbers would
            # otherwise hide. The state argument's shapes are fixed, so
            # only the rollout argument is signature-walked. Reads the
            # RESOLVED flag (the trainers fold ASYNCRL_INTROSPECT in at
            # construction) — never re-consults the environment.
            self._step = introspect.instrument(
                self._step, "learner.update",
                counters=("compiles", "learner_recompile"),
                ignore_argnums=(0,),
            )
        # Fragment structure is fixed for this trainer (ff vs recurrent), so
        # the device_put sharding pytree is built once, not per update.
        template = Rollout(
            obs=None, actions=None, behaviour_logp=None, rewards=None,
            terminated=None, truncated=None, bootstrap_obs=None,
            init_core=model.initial_core(1) if is_recurrent(model) else None,
            # Placeholder non-None leaf: the stream must get its time-major
            # sharding like every other fragment field (a None here would
            # device_put it uncommitted).
            disc_returns=0.0 if config.normalize_returns else None,
        )
        self._rollout_sharding = rollout_sharding(mesh, template, stacked=K > 1)

    # ---------------------------------------------------------------- state

    def init_state(self, seed: int) -> LearnerState:
        key = jax.random.PRNGKey(seed)
        dummy_obs = jnp.zeros((1, *self.spec.obs_shape), self.spec.obs_dtype)
        if is_recurrent(self.model):
            params = self.model.init(
                key, dummy_obs, self.model.initial_core(1)
            )
        else:
            params = self.model.init(key, dummy_obs)
        opt_state = self.optimizer.init(params)
        rep = NamedSharding(self.mesh, P())
        params = jax.device_put(params, rep)
        return LearnerState(
            params=params,
            opt_state=jax.device_put(opt_state, rep),
            update_step=jax.device_put(jnp.zeros((), jnp.int32), rep),
            # qlearn — and the IMPACT replay anchor — start the target
            # net equal to the online net (device arrays are immutable,
            # so sharing the reference is safe).
            target_params=(
                params
                if self.config.algo == "qlearn"
                or self.config.replay_slabs > 0
                else None
            ),
            obs_stats=(
                jax.device_put(init_stats(self.spec.obs_shape), rep)
                if self.config.normalize_obs
                else None
            ),
            ret_stats=(
                jax.device_put(init_stats(()), rep)
                if self.config.normalize_returns
                else None
            ),
        )

    # --------------------------------------------------------------- update

    def put_rollout(self, rollout: Rollout) -> Rollout:
        """Transfer a host (numpy) fragment to the mesh, batch-sharded.

        The span is the DISPATCH cost only (device_put is async); the
        unhidden transfer time shows up in the trainer's
        ``learner.h2d_wait`` span around its explicit barrier."""
        with trace.span(span_names.LEARNER_H2D):
            return jax.device_put(rollout, self._rollout_sharding)

    def update(self, state: LearnerState, rollout: Rollout):
        """One gradient step on a device-resident fragment. The span
        covers the jitted dispatch (plus, on the CPU backend where
        dispatch is effectively synchronous, the compute itself)."""
        with trace.span(span_names.LEARNER_UPDATE):
            return self._step(state, rollout)
