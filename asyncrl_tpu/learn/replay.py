"""Device-resident replay ring: IMPACT-style sample reuse (ROADMAP 3).

The Sebulba learner consumes each staged slab roughly once, so learner
FLOPs are rate-limited by actor throughput — ``learner_stall_frac`` is
the dominant wait in every traced run. IMPACT (arXiv:1912.00167) shows
that multiple SGD passes per sample, with importance weights clipped
against a slowly-updated target network, recover the sample-efficiency
loss of reuse; "Parallel Actors and Learners" (arXiv:2110.01101) is the
decoupling argument a replay tier completes. This module is the data
half: a small circular replay of the most recent consumed slabs kept in
DEVICE memory (HBM — the hand-off back to the learner never crosses the
host link), reusing the staging-ring generation/lease discipline
(rollout/staging.py):

- Preallocated ``[R, T, B, ...]`` device buffers, one leaf per
  ``Rollout`` field, allocated once for the trainer's lifetime with the
  fragment's own sharding (leading ring axis unsharded).
- ``publish`` lands a fresh (already-transferred) slab into the cursor
  row via a jitted ``dynamic_update_index_in_dim`` — the existing
  donation/overlap path's device copy, optionally donating the old
  buffer for in-place reuse. Eviction is oldest-generation by
  construction (the cursor is the ring order).
- The learner **leases** a row to replay (:meth:`DeviceReplayRing.
  lease_sample`, least-reused-first — a fresh slab is always sampled
  before an already-replayed one) and ``consume``\\s it; eviction or a
  rollback quarantine *voids* outstanding leases, so a zombie read
  raises :class:`ReplayStaleError` instead of returning a NEWER slab's
  rows — the staging generation fence, applied to device data.
- A rollback quarantine (runtime/durability.py, the PR-10 path) empties
  the ring: replayed data produced under (or poisoned by) a diverging
  policy must never reach the learner again.

The update-side half — the clipped target network whose log-probs
anchor the importance ratio — lives in learn/rollout_learner.py; the
per-sample reuse-count/target-lag telemetry drains through
:class:`ReuseWindow` into the PR-8 staleness ledger's window keys.

Thread contract: single-thread by design, like ``introspect.
StalenessWindow`` — the trainer's learner-drain thread publishes,
leases, consumes, AND quarantines (the rollback policy runs at window
close on that same thread), so there is no lock and no cross-thread
visibility question.
"""

# protocol: replay-lease mint=DeviceReplayRing.lease_sample,DeviceReplayRing._outstanding,lease_sample ops=consume:held->consumed,void:held->voided open=held terminal=voided initial=held

from __future__ import annotations

import numpy as np

import jax

from asyncrl_tpu.rollout.buffer import Rollout
from asyncrl_tpu.rollout.staging import StaleLeaseError


class ReplayStaleError(StaleLeaseError):
    """A voided replay lease was consumed: its row was evicted by a
    newer publish (oldest-generation eviction) or the ring was
    quarantined by the rollback policy. The reader must drop the pass —
    the row's device memory now holds (or is about to hold) a NEWER
    slab's data, and returning it would silently train on the wrong
    sample."""


def validate_replay_config(config) -> None:
    """Constructor-time replay checks, shared by every builder of the
    host-fragment update step (RolloutLearner today): the degenerate
    configurations fail silently mid-train, so they must fail loudly
    here instead."""
    if config.replay_slabs <= 0:
        return
    if config.algo != "impala":
        raise ValueError(
            f"replay_slabs={config.replay_slabs} requires algo='impala': "
            "the IMPACT-mode update anchors the V-trace importance ratio "
            "against the clipped target network, which only the V-trace "
            f"loss consumes (got algo={config.algo!r})"
        )
    if config.updates_per_call != 1:
        raise ValueError(
            "replay_slabs > 0 requires updates_per_call=1: the ring "
            "stores single [T, B, ...] fragments, and a fused [K>1] "
            "stack would replay K stale fragments as one indivisible "
            "unit"
        )
    if config.core != "ff":
        raise ValueError(
            "replay_slabs > 0 requires core='ff': the target-network "
            "anchor forward has no carry channel for a recurrent core "
            "(the staging fragment's init_core belongs to the ORIGINAL "
            "behaviour rollout, not a replayed re-forward)"
        )
    if config.normalize_obs or config.normalize_returns:
        raise ValueError(
            "replay_slabs > 0 does not compose with normalize_obs/"
            "normalize_returns: the jitted step folds every consumed "
            "fragment into the running stats, and it cannot tell a "
            "fresh fragment from a replayed one — each slab would fold "
            "in replay_passes times, inflating the sample count and "
            "biasing the mean/var (and the reward-scaling denominator) "
            "toward reused slabs"
        )
    if config.replay_passes < 1:
        raise ValueError(
            f"replay_passes={config.replay_passes} must be >= 1 "
            "(1 = fresh pass only; the ring still fills for later "
            "windows)"
        )
    if config.target_update_period < 1:
        raise ValueError(
            f"target_update_period={config.target_update_period} must "
            "be >= 1"
        )
    if config.replay_rho_clip < 1.0:
        raise ValueError(
            f"replay_rho_clip={config.replay_rho_clip} must be >= 1: a "
            "cap below 1 would down-weight perfectly on-policy data"
        )


class ReplayLease:
    """One replay read permit for one ring row, generation-stamped.

    Mirrors ``staging.SlabLease`` at the device tier: ``consume`` is the
    single read+release op (the obligation window stays one statement
    wide on the drain thread), ``void`` is the eviction/quarantine
    fence. Single-thread contract (see module docstring)."""

    __slots__ = ("ring", "row", "gen", "_voided")

    def __init__(self, ring: "DeviceReplayRing", row: int, gen: int):
        self.ring = ring
        self.row = row
        self.gen = gen
        self._voided = False

    def valid(self) -> bool:
        return (
            not self._voided
            and self.ring._row_gen[self.row] == self.gen
        )

    def consume(self) -> tuple[Rollout, int, int]:
        """Read the leased row and release the lease in one step:
        ``(slab, reuse_count, behaviour_update)`` — the device pytree, the
        row's cumulative consumption count (fresh pass included), and
        the learner-update count its behaviour params were published at
        (the staleness ledger's lag base). Raises
        :class:`ReplayStaleError` if the row was evicted or quarantined
        since the lease was minted."""
        ring = self.ring
        ring._release(self)
        if not self.valid():
            raise ReplayStaleError(
                f"replay lease gen {self.gen} on row {self.row} was "
                "voided (evicted by a newer publish, or quarantined by "
                "the rollback policy); refusing to return the row"
            )
        ring._row_reuse[self.row] += 1
        reuse = ring._row_reuse[self.row]
        behaviour = ring._row_behaviour[self.row]
        # Adopted rows (publish ref=True) hand back the adopted pytree
        # itself — zero-copy on the replay read path too; installed rows
        # gather a fresh copy out of the stacked buffer (which is what
        # keeps the LEARNER's donation of replayed fragments safe there).
        ref = ring._row_ref[self.row]
        slab = (
            ref
            if ref is not None
            else ring._take(ring._buf, np.int32(self.row))
        )
        return slab, reuse, behaviour

    def void(self) -> None:
        """Fence this lease (eviction/quarantine path): any later
        ``consume`` raises. Idempotent."""
        self._voided = True
        self.ring._release(self)


class DeviceReplayRing:
    """The preallocated ``[R, T, B, ...]`` device ring + its row ledger.

    ``template`` is the one-fragment ``jax.ShapeDtypeStruct`` pytree
    (``staging.fragment_template`` — the same single source of slab
    geometry the host ring uses); ``sharding`` is the STACKED pytree of
    ``NamedSharding``\\s (``rollout_learner.rollout_sharding(mesh,
    template, stacked=True)`` — leading ring axis unsharded) or None
    for default single-device placement (unit tests). ``donate=True``
    (the default) donates the old ring buffer into each install — the
    donate-and-rebind idiom on a buffer that is PRIVATE to the ring, so
    the write is in-place and a publish never pays an R-fold buffer
    copy. This is independent of ``config.donate_buffers``: that flag
    is off for the axon plugin's FULL-train-step aliasing table, while
    an identity-aliased single-buffer install is the "subsets work"
    case its note records (and ``consume`` always hands out a fresh
    gather, so the LEARNER's donation of replayed fragments stays
    safe either way)."""

    def __init__(
        self,
        template: Rollout,
        sharding: Rollout | None = None,
        rows: int = 2,
        donate: bool = True,
    ):
        if rows < 1:
            raise ValueError(f"replay rows={rows} must be >= 1")
        self._rows = rows
        if sharding is None:
            self._buf = jax.tree.map(
                lambda sds: jax.device_put(
                    np.zeros((rows, *sds.shape), np.dtype(sds.dtype))
                ),
                template,
            )
        else:
            self._buf = jax.tree.map(
                lambda sds, sh: jax.device_put(
                    np.zeros((rows, *sds.shape), np.dtype(sds.dtype)), sh
                ),
                template,
                sharding,
            )
        def _install(buf, slab, row):
            return jax.tree.map(
                lambda b, s: jax.lax.dynamic_update_index_in_dim(
                    b, s, row, 0
                ),
                buf,
                slab,
            )

        def _take(buf, row):
            return jax.tree.map(
                lambda b: jax.lax.dynamic_index_in_dim(
                    b, row, 0, keepdims=False
                ),
                buf,
            )

        # The row index is a TRACED scalar (np.int32 at the call sites):
        # one compile serves every row, so the ring can never be the
        # recompile storm the introspect counters watch for.
        self._install = jax.jit(
            _install, donate_argnums=(0,) if donate else ()
        )
        self._take = jax.jit(_take)
        self._gen = 0
        self._cursor = 0
        self._row_gen = [0] * rows  # 0 = empty row
        self._row_reuse = [0] * rows
        self._row_behaviour = [0] * rows
        # Zero-copy adoptions (rollout/device_queue.py): a row published
        # with ref=True stores the caller's device pytree here instead
        # of installing into the stacked buffer; None = the row lives in
        # self._buf (the install path).
        self._row_ref: list[Rollout | None] = [None] * rows
        self._out: dict[int, ReplayLease] = {}  # row -> outstanding lease

    # ------------------------------------------------------------ facade

    @property
    def rows(self) -> int:
        return self._rows

    def fill_frac(self) -> float:
        """Filled rows / ring depth — the ``replay_fill_frac`` window
        gauge (and the elastic scale-down signal's input)."""
        return sum(1 for g in self._row_gen if g > 0) / self._rows

    def _outstanding(self, row: int) -> ReplayLease | None:
        """The row's outstanding (leased, not yet consumed) lease."""
        return self._out.get(row)

    def _release(self, lease: ReplayLease) -> None:
        if self._out.get(lease.row) is lease:
            del self._out[lease.row]

    # ----------------------------------------------------------- publish

    def publish(
        self, slab: Rollout, behaviour_update: int = 0, ref: bool = False
    ) -> None:
        """Land a fresh DEVICE slab into the cursor row (oldest-
        generation eviction: the cursor is the ring order). Called with
        the just-transferred fragment BEFORE the learner update can
        donate it; the install is a device-to-device copy (or in-place
        under donation). ``behaviour_update`` is the learner-update
        count the slab's behaviour params were published at — replayed
        consumptions report staleness against it.

        ``ref=True`` ADOPTS the slab by reference — the zero-copy
        publish path for fragments already resident in HBM behind the
        device rollout queue's ledger (rollout/device_queue.py): no row
        install, no install barrier (the slab is a committed device
        array with no host alias to tear), and ``consume`` later hands
        back the SAME pytree instead of a gathered copy. jax arrays are
        immutable, so queue slot reuse can never corrupt the adoption;
        the caller's one obligation is that the consuming updates do NOT
        donate the fragment (the trainer enables ref publishing only
        with ``config.donate_buffers`` off — a donating update would
        delete the adopted buffers under the ring)."""
        row = self._cursor
        lease = self._outstanding(row)
        if lease is not None:
            # Eviction fences zombies: the displaced row's in-flight
            # lease voids, so its consume raises instead of returning
            # the NEWER slab's rows.
            lease.void()
        self._gen += 1
        self._row_gen[row] = self._gen
        # The fresh pass consumes the slab once, directly (the trainer
        # feeds it to the learner without a ring round-trip), so a
        # published row starts at reuse 1, not 0.
        self._row_reuse[row] = 1
        self._row_behaviour[row] = int(behaviour_update)
        self._cursor = (row + 1) % self._rows
        if ref:
            # Dropping a previous adoption (or shadowing a stacked-buffer
            # row) is pure ledger work: the old reference frees when the
            # last holder drops it.
            self._row_ref[row] = slab
            return
        self._row_ref[row] = None
        self._buf = self._install(self._buf, slab, np.int32(row))
        # Barrier: the install is an INDEPENDENT async reader of the
        # fresh slab, and the staging ring's retire gate only waits for
        # the learner UPDATE's output — on a backend where the device
        # fragment zero-copy aliases the host staging slab (the CPU
        # client), the slab could otherwise be reclaimed and rewritten
        # while the install still reads the alias, landing a torn slab
        # in the ring. Blocking here closes that window before the
        # caller can even dispatch the consuming update (one device-
        # local row write under donation — microseconds, and the drain
        # already barriers the H2D of these same bytes).
        jax.block_until_ready(self._buf)

    # ------------------------------------------------------------ sample

    def lease_sample(self, rng: np.random.Generator) -> ReplayLease | None:
        """Lease the least-reused filled row (fresh-first: a slab the
        learner has seen fewer times always samples before a more-reused
        one; ties break by ``rng`` draw). None when the ring holds no
        leasable row (empty, or every filled row already leased)."""
        candidates = [
            r
            for r in range(self._rows)
            if self._row_gen[r] > 0 and r not in self._out
        ]
        if not candidates:
            return None
        low = min(self._row_reuse[r] for r in candidates)
        pool = [r for r in candidates if self._row_reuse[r] == low]
        row = pool[int(rng.integers(len(pool)))] if len(pool) > 1 else pool[0]
        lease = ReplayLease(self, row, self._row_gen[row])
        self._out[row] = lease
        return lease

    # -------------------------------------------------------- quarantine

    def quarantine(self) -> int:
        """Void every outstanding lease and empty the ring (the PR-10
        rollback path extended to the replay tier, and the trainer's
        ``stop()`` hygiene): a diverging policy's replayed tail must
        never feed another update, and a new cohort starts on an empty
        ring. Returns the number of filled rows dropped. Device buffers
        keep their storage — the ledger emptying alone makes every row
        unreachable until re-published."""
        for lease in list(self._out.values()):
            lease.void()
        dropped = sum(1 for g in self._row_gen if g > 0)
        self._gen += 1
        self._cursor = 0
        self._row_gen = [0] * self._rows
        self._row_reuse = [0] * self._rows
        self._row_behaviour = [0] * self._rows
        # Adopted references drop with the ledger: quarantined HBM frees
        # as soon as the device queue's slot binding also moves on.
        self._row_ref = [None] * self._rows
        return dropped


class ReuseWindow:
    """Per-window sample-reuse aggregation, the PR-8 ``StalenessWindow``
    pattern (same single-thread contract, same absent-not-zero key
    rule): the trainer observes one ``(reuse_count, target_lag)`` pair
    per consumed sample — fresh passes at reuse 1, replayed passes at
    the row's cumulative count, target_lag in learner updates since the
    last target-network refresh — and drains ``reuse_p50`` /
    ``reuse_p95`` / ``reuse_max`` / ``target_lag_mean`` at window
    close."""

    def __init__(self) -> None:
        self._reuse: list[float] = []
        self._lag: list[float] = []

    def observe(self, reuse: float, target_lag: float) -> None:
        self._reuse.append(float(reuse))
        self._lag.append(float(target_lag))

    def drain(self) -> dict[str, float]:
        if not self._reuse:
            return {}
        reuse = np.asarray(self._reuse, np.float64)
        lag = np.asarray(self._lag, np.float64)
        self._reuse, self._lag = [], []
        return {
            "reuse_p50": float(np.percentile(reuse, 50)),
            "reuse_p95": float(np.percentile(reuse, 95)),
            "reuse_max": float(reuse.max()),
            "target_lag_mean": float(lag.mean()),
        }
