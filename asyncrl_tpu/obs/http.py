"""Exposition endpoint: ``/metrics``, ``/healthz``, ``/timeseries``.

A stdlib-only background HTTP server — the per-host surface a multi-host
launcher, an autoscaler, or a plain ``curl`` scrapes while a run is live:

- ``GET /metrics`` — Prometheus text exposition rendered from the
  counters/histograms registry merged over the latest window sample
  (every key the metric sinks see, as ``asyncrl_<key>`` gauges).
- ``GET /healthz`` — the :class:`~asyncrl_tpu.obs.health.HealthMonitor`
  verdict as JSON: overall status, per-component status for
  actors/server/learner/serve-core, and the events behind it. HTTP 200
  while ``ok``, 503 once degraded/critical — load balancers and
  autoscalers key off the code without parsing the body.
- ``GET /timeseries?key=fps&n=240`` — recent ``[t, value]`` points for
  one metric key (dashboards); ``GET /timeseries`` lists available keys.

Off by default: the server exists only when ``config.obs_http_port`` (or
``ASYNCRL_OBS_PORT``, which wins) asks for it — endpoint off means zero
threads and zero per-request surface. Port semantics: ``0`` = off,
``-1`` = bind an OS-assigned ephemeral port (tests, smoke scripts; read
it back from :attr:`ObsHTTPServer.port`), positive = bind exactly there.
Binds 127.0.0.1 by default — exposing beyond the host is a deliberate
operator decision (bind_host="0.0.0.0"), not a default.

The serving thread is named ``obs-http`` (one more named thread for the
watchdog/analysis thread-identity discipline); per-request handlers run
on ThreadingHTTPServer's daemon threads and only ever READ snapshot-
consistent state (registry window, store snapshots, monitor verdict) —
the handler never mutates pipeline state, so no lock discipline crosses
this boundary.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Mapping
from urllib.parse import parse_qs, urlparse

from asyncrl_tpu.obs import registry

ENV_PORT = "ASYNCRL_OBS_PORT"
ENV_HOST = "ASYNCRL_OBS_HOST"
_METRIC_NAME = re.compile(r"[^a-zA-Z0-9_]")


def env_port(config_port: int) -> int:
    """The effective port: ``ASYNCRL_OBS_PORT`` (when set and non-empty)
    wins over ``config.obs_http_port`` — the no-code-change knob, the
    ASYNCRL_TRACE precedence."""
    raw = os.environ.get(ENV_PORT, "").strip()
    if raw:
        try:
            return int(raw)
        except ValueError:
            raise ValueError(
                f"{ENV_PORT}={raw!r} is not an integer port "
                "(0=off, -1=ephemeral)"
            )
    return config_port


def env_host(config_host: str, env_var: str = ENV_HOST) -> str:
    """The effective bind host: the env var (when set and non-empty) wins
    over the config value — the same precedence as the port. Loopback
    stays the default everywhere; binding wider (``0.0.0.0``) is a
    deliberate operator decision made through exactly these two knobs.
    ``env_var`` defaults to ``ASYNCRL_OBS_HOST``; the gateway reuses this
    one precedence definition with ``ASYNCRL_GATEWAY_HOST``."""
    raw = os.environ.get(env_var, "").strip()
    return raw if raw else config_host


def render_prometheus(values: Mapping[str, Any]) -> str:
    """Prometheus text exposition (gauge-typed) for a flat metrics dict.
    Keys sanitize to ``asyncrl_<name>`` metric names; non-numeric values
    (e.g. the ``health_status`` string) are skipped — ``/healthz`` owns
    the categorical story.

    A key may carry a label suffix — ``fleet_replica_staleness
    {replica="r0"}`` (no space) — in which case only the base sanitizes
    and the labels pass through, rendering a labeled series; one TYPE
    line is emitted per family, so the ``{replica=...}`` series of one
    base share it."""
    lines: list[str] = []
    typed: set[str] = set()
    for key in sorted(values):
        value = values[key]
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        key = str(key)
        labels = ""
        if "{" in key and key.endswith("}"):
            base, raw = key.split("{", 1)
            labels = "{" + raw
        else:
            base = key
        name = "asyncrl_" + _METRIC_NAME.sub("_", base)
        value = float(value)
        if math.isfinite(value):
            rendered = f"{value:g}"
        else:
            # The exposition format's canonical non-finite spellings (a
            # diverging run's loss=NaN must scrape, not corrupt).
            rendered = "NaN" if math.isnan(value) else (
                "+Inf" if value > 0 else "-Inf"
            )
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{labels} {rendered}")
    return "\n".join(lines) + "\n"


class ObsHTTPServer:
    """The background exposition server (see module docstring).

    Construction BINDS the socket (so a taken port fails loudly at setup,
    where the operator reads it); :meth:`start` spawns the ``obs-http``
    serving thread; :meth:`stop` shuts it down and closes the socket.
    """

    def __init__(
        self,
        port: int = 0,
        store=None,
        monitor=None,
        bind_host: str = "127.0.0.1",
    ):
        self.store = store
        self.monitor = monitor
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            # Per-request daemon threads: READ-ONLY consumers of snapshot-
            # consistent state (see module docstring).
            def log_message(self, fmt, *args):  # silence stderr chatter
                pass

            def do_GET(self):  # noqa: N802 (stdlib handler contract)
                try:
                    outer._route(self)
                # lint: broad-except-ok(exposition must never take down the run it observes; a failed render answers 500 and the next scrape retries)
                except Exception as e:
                    try:
                        outer._send(self, 500, "text/plain",
                                    f"obs-http error: {e}\n".encode())
                    except OSError:
                        pass  # client hung up mid-error — nothing to do

        # port -1 => 0 at the socket layer (OS-assigned ephemeral).
        self._httpd = ThreadingHTTPServer(
            (bind_host, max(0, port)), _Handler
        )
        self._httpd.daemon_threads = True
        self.port: int = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------- routes

    @staticmethod
    def _send(handler, code: int, ctype: str, body: bytes) -> None:
        handler.send_response(code)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _send_json(self, handler, code: int, doc: Any) -> None:
        # Strict JSON on the wire: json.dumps' bare NaN/Infinity literals
        # are a Python dialect every RFC-compliant consumer (JS dashboards,
        # jq, Go autoscalers) rejects — and a NaN loss in a health event is
        # exactly when this surface matters. Encode them as strings (the
        # timeseries.jsonl spelling).
        from asyncrl_tpu.obs.timeseries import encode_tree

        self._send(
            handler, code, "application/json",
            (json.dumps(encode_tree(doc), default=str,
                        allow_nan=False) + "\n").encode(),
        )

    def _route(self, handler) -> None:
        url = urlparse(handler.path)
        if url.path == "/metrics":
            values: dict[str, Any] = {}
            latest = self.store.latest() if self.store is not None else None
            if latest:
                values.update(latest)
            # Registry second: its counters/histograms are fresher than
            # the window-close snapshot of the same keys.
            values.update(registry.window())
            self._send(
                handler, 200,
                "text/plain; version=0.0.4; charset=utf-8",
                render_prometheus(values).encode(),
            )
        elif url.path == "/healthz":
            if self.monitor is None:
                self._send_json(
                    handler, 200,
                    {"status": "unknown", "detail": "no health monitor"},
                )
                return
            verdict = self.monitor.verdict()
            self._send_json(
                handler, 200 if verdict["status"] == "ok" else 503, verdict
            )
        elif url.path == "/timeseries":
            if self.store is None:
                self._send_json(
                    handler, 404, {"error": "no timeseries store mounted"}
                )
                return
            query = parse_qs(url.query)
            key = (query.get("key") or [""])[0]
            if not key:
                self._send_json(
                    handler, 200,
                    {"keys": self.store.keys(),
                     "samples": self.store.idx,
                     "dropped": self.store.dropped},
                )
                return
            try:
                n = int((query.get("n") or ["240"])[0])
            except ValueError:
                self._send_json(
                    handler, 400, {"error": "n must be an integer"}
                )
                return
            self._send_json(
                handler, 200,
                {"key": key, "points": self.store.series(key, last_n=n)},
            )
        elif url.path == "/":
            self._send_json(
                handler, 200,
                {"endpoints": ["/metrics", "/healthz",
                               "/timeseries?key=<metric>&n=<count>"]},
            )
        else:
            self._send_json(handler, 404, {"error": f"no route {url.path}"})

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "ObsHTTPServer":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._serve, name="obs-http", daemon=True
            )
            self._thread.start()
        return self

    def _serve(self) -> None:  # thread-entry: obs-http@obs
        self._httpd.serve_forever(poll_interval=0.2)

    def stop(self) -> None:
        """Shut down the serving loop and close the socket (idempotent)."""
        thread, self._thread = self._thread, None
        if thread is not None and thread.is_alive():
            self._httpd.shutdown()
            thread.join(timeout=2.0)
        self._httpd.server_close()
