"""Counters/histograms registry the metric window sinks drain from.

Before this module every pipeline metric was hand-plumbed: an attribute on
the owning object, a snapshot field on the trainer, and a bespoke line in
the window-aggregation block (``h2d_wait_s``, ``slab_reuse_waits``,
``infer_coalesce_batch``, ``faults.counters()`` — each with its own
delta/cumulative convention). This registry is the common sink for new
instrumentation: any thread increments named counters or observes into
named histograms; the trainer's window close calls :func:`window` once and
merges the result next to the legacy keys; the flight recorder dumps
:func:`dump` wholesale. Legacy metrics keep their existing keys (nothing
breaks downstream greps) — they are not migrated, new ones simply stop
needing trainer plumbing.

Counters are cumulative (like ``actor_restarts``); gauges are last-value
levels (like the serve gate's rolling p95); histograms export
``<name>_p50`` / ``<name>_p95`` / ``<name>_p99`` / ``<name>_max`` /
``<name>_count``
summaries over everything observed so far. Thread-safety: one registry
lock around the name->instrument map; each instrument carries its own
lock (observations are per-update/per-event, not per-env-frame — never a
hot-path cost).
"""

from __future__ import annotations

import threading

# Log-spaced default bucket upper bounds (milliseconds-friendly: spans
# from 10µs to ~2 minutes when observations are in seconds ×1e3).
_DEFAULT_BUCKETS = tuple(
    round(base * 10.0 ** exp, 6)
    for exp in range(-2, 6)
    for base in (1.0, 2.5, 5.0)
)


class Counter:
    """A named cumulative counter (monotone under normal use)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0  # guarded-by: _lock

    def inc(self, delta: float = 1.0) -> None:
        with self._lock:
            self._value += delta

    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """A named last-value instrument (set, not accumulated): the shape
    for state that is a LEVEL, not a count — the serve gate's rolling p95
    and its in-breach flag (serve/slo.py), queue depths. Exported in the
    window under its bare name, like a counter."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0  # guarded-by: _lock

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with quantile summaries.

    Quantiles come from bucket upper bounds (the Prometheus estimate):
    exact enough for stall diagnosis, allocation-free in steady state.
    """

    def __init__(self, name: str, buckets: tuple[float, ...] = _DEFAULT_BUCKETS):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"histogram buckets must be sorted: {buckets!r}")
        self.name = name
        self.buckets = buckets
        self._lock = threading.Lock()
        self._counts = [0] * (len(buckets) + 1)  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._max = 0.0  # guarded-by: _lock
        # Per-bucket last exemplar (a request trace id): populated lazily
        # only when an observation carries one, so histograms without
        # exemplars stay allocation-free and the window shape unchanged.
        self._exemplars: dict[int, str] = {}  # guarded-by: _lock

    def observe(self, value: float, exemplar: str | None = None) -> None:
        i = 0
        for i, bound in enumerate(self.buckets):  # noqa: B007
            if value <= bound:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += value
            if value > self._max:
                self._max = value
            if exemplar:
                self._exemplars[i] = exemplar

    def exemplars(self) -> dict[float, str]:
        """Bucket upper bound -> last exemplar observed into that bucket
        (the overflow bucket keys on +inf). Empty unless observations
        carried exemplars — a p99 breach in the summary links here to a
        concrete request journal."""
        with self._lock:
            items = list(self._exemplars.items())
        return {
            (self.buckets[i] if i < len(self.buckets) else float("inf")): ex
            for i, ex in items
        }

    def _quantile_locked(self, q: float) -> float:  # holds: _lock
        if self._count == 0:
            return 0.0
        rank = q * self._count
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= rank:
                return (
                    self.buckets[i] if i < len(self.buckets) else self._max
                )
        return self._max

    def summary(self) -> dict[str, float]:
        with self._lock:
            return {
                f"{self.name}_count": float(self._count),
                f"{self.name}_p50": self._quantile_locked(0.50),
                f"{self.name}_p95": self._quantile_locked(0.95),
                f"{self.name}_p99": self._quantile_locked(0.99),
                f"{self.name}_max": self._max,
            }


class Registry:
    """Name -> instrument map. One process-wide instance (module level)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}  # guarded-by: _lock
        self._gauges: dict[str, Gauge] = {}  # guarded-by: _lock
        self._histograms: dict[str, Histogram] = {}  # guarded-by: _lock

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    def window(self) -> dict[str, float]:
        """The metrics-window view: every counter value and histogram
        summary, flat-keyed — what the trainer merges into each window."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            histograms = list(self._histograms.values())
        out: dict[str, float] = {}
        for c in counters:
            out[c.name] = c.value()
        for g in gauges:
            out[g.name] = g.value()
        for h in histograms:
            out.update(h.summary())
        return out

    def reset(self) -> None:
        """Drop every instrument (tests; a fresh trainer's obs setup)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_REGISTRY = Registry()


def registry() -> Registry:
    """The process-wide registry."""
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return _REGISTRY.histogram(name)


def window() -> dict[str, float]:
    return _REGISTRY.window()
