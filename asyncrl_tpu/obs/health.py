"""Run-health detectors: the machine that watches a run from the outside.

Everything upstream of this module explains a run after the fact (trace
export, flight recorder, stall report); nothing *watched* it. The
:class:`HealthMonitor` closes that gap: evaluated once per metrics window
(on the trainer's window-close thread — no detector thread exists), each
:class:`Detector` inspects the window sample plus a little trailing state
and fires a structured :class:`HealthEvent` when its condition holds.

Every firing event:

- increments the ``health_events_total`` and ``health_<detector>``
  registry counters (so the NEXT window's sample records the anomaly),
- annotates the time-series store (so ``obs doctor`` and the
  ``/timeseries`` endpoint see the anomaly inline with the metrics), and
- triggers the flight recorder with ``reason=health.<detector>`` — every
  anomaly gets an automatic forensic dump of the pipeline's last seconds.

Detector taxonomy (thresholds from :class:`Thresholds`, i.e. the
``health_*`` config fields):

===================== ========== ========= =================================
detector              component  severity  fires when
===================== ========== ========= =================================
nonfinite_loss        learner    critical  loss / grad_norm is NaN or inf
grad_explosion        learner    warn      grad_norm > health_grad_norm_max
learner_stall         (blamed)   warn      learner_stall_frac >
                                           health_stall_frac — the event
                                           names the bottleneck stage via
                                           the WAIT_SPANS attribution
admission_saturation  serve-core warn      serve gate overloads/sheds grew
                                           this window
fps_collapse          pipeline   warn      fps < health_fps_collapse x the
                                           run's own trailing median
slo_breach            serve-core warn      rolling p95 over SLO target for
                                           2+ consecutive windows
restart_storm         actors/    critical  >= 2 supervised restarts in ONE
                      server               window (storm proximity)
eval_regression       learner    warn      eval_return fell more than
                                           health_eval_drop below the
                                           run's best (0 = off)
entropy_collapse      learner    warn      policy entropy below
                                           health_entropy_floor (0 = off)
staleness_runaway     pipeline   warn      staleness_max (behaviour-params
                                           lag, learner updates) above
                                           health_staleness_max (0 = off)
rho_clip_saturation   learner    warn      rho_clip_frac above
                                           health_rho_clip_frac (0 = off)
recompile_storm       pipeline   warn      `compiles` grew >=
                                           health_recompile_storm in one
                                           window (0 = off)
memory_growth         pipeline   warn      memory watermark grew more than
                                           health_mem_growth x the run's
                                           first watermark (0 = off)
gateway_error_rate    gateway    warn      >= half of a window's gateway
                                           requests errored (>= 4 reqs)
breaker_open          gateway    warn      a client-side circuit breaker
                                           is sitting open
replica_staleness_-   fleet      warn      worst replica's weight-sync lag
runaway                                    reached the fleet's staleness
                                           cap (fleet_staleness_max vs
                                           fleet_staleness_cap gauges)
replica_flap          fleet      warn      >= 3 replica readmissions in
                                           the flap horizon (eject/
                                           readmit oscillation)
===================== ========== ========= =================================

The last five (ISSUE 8) watch the *learning* and the *device* — fed by
``obs/introspect.py`` and the loss-aux diagnostics — where everything
above watches the system.

The ``learner_stall`` verdict reuses the span taxonomy's causal table
(:data:`asyncrl_tpu.obs.spans.WAIT_CAUSES`): when tracing is armed the
detector sums the last window's wait spans across all rings and blames
the component the dominant wait points at (``learner.queue_wait`` means
the ACTORS are the bottleneck, not the learner) — the same attribution
the offline report computes, inlined into the live verdict.
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from typing import Any, Callable

from asyncrl_tpu.obs import flightrec, registry
from asyncrl_tpu.obs import spans as span_names

COMPONENTS = (
    "actors", "server", "learner", "serve-core", "gateway", "fleet",
    "pipeline",
)
_STATUS_RANK = {"ok": 0, "degraded": 1, "critical": 2}

# Which component a dominant WAIT span indicts (the causal reading of
# spans.WAIT_CAUSES, folded to the /healthz component vocabulary): the
# learner starving on its queue blames the ACTORS that feed it, actors
# blocked on the queue/slab blame the LEARNER that drains it.
_BLAME = {
    span_names.LEARNER_QUEUE_WAIT: "actors",
    span_names.LEARNER_H2D_WAIT: "learner",
    span_names.ACTOR_QUEUE_PUT: "learner",
    span_names.ACTOR_LEASE_WAIT: "learner",
    span_names.STAGING_REUSE_WAIT: "learner",
    span_names.SERVER_COLLECT_WAIT: "actors",
    span_names.SERVE_ADMIT_WAIT: "serve-core",
    span_names.SERVE_BATCH_FILL: "actors",
    span_names.SERVE_SWAP_DRAIN: "serve-core",
    span_names.GATEWAY_ADMIT_WAIT: "gateway",
}


def blame_component(stage: str | None) -> str | None:
    """The /healthz component a dominant wait span indicts (the public
    view of the blame table): what the ``learner_stall`` detector reports
    and what the elastic controller refines its scale-up verdict with —
    a stall the spans blame on H2D or the serve core is not fixed by
    growing the actor fleet."""
    if stage is None:
        return None
    return _BLAME.get(stage)


@dataclasses.dataclass(frozen=True)
class Thresholds:
    """Detector thresholds — one frozen bundle so the live monitor and the
    offline doctor replay the SAME conditions (the doctor reads these back
    from the timeseries meta line)."""

    stall_frac: float = 0.9
    fps_collapse: float = 0.5
    grad_norm_max: float = 0.0   # 0 = detector off
    eval_drop: float = 0.0       # 0 = detector off
    window_ttl: int = 3          # windows an event degrades the verdict
    # Learning-health / device-behavior detectors (ISSUE 8; 0 = off):
    entropy_floor: float = 0.0
    staleness_max: float = 0.0
    rho_clip_frac: float = 0.0
    recompile_storm: int = 0
    mem_growth: float = 0.0

    @classmethod
    def from_config(cls, config: Any) -> "Thresholds":
        return cls(
            stall_frac=config.health_stall_frac,
            fps_collapse=config.health_fps_collapse,
            grad_norm_max=config.health_grad_norm_max,
            eval_drop=config.health_eval_drop,
            window_ttl=config.health_window_ttl,
            entropy_floor=config.health_entropy_floor,
            staleness_max=config.health_staleness_max,
            rho_clip_frac=config.health_rho_clip_frac,
            recompile_storm=config.health_recompile_storm,
            mem_growth=config.health_mem_growth,
        )

    @classmethod
    def from_meta(cls, meta: dict[str, Any]) -> "Thresholds":
        raw = meta.get("thresholds") or {}
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})


@dataclasses.dataclass
class HealthEvent:
    """One detector firing for one window (JSONL- and UI-facing)."""

    detector: str
    component: str
    severity: str  # "warn" | "critical"
    message: str
    window_idx: int
    env_steps: float
    t_unix: float
    data: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Detector:
    """One named condition: ``fn(monitor, sample)`` returns None (quiet)
    or ``(message, data)``; ``data`` may carry a ``component`` override
    (the stall detector blames the attributed stage, not itself)."""

    name: str
    component: str
    severity: str
    fn: Callable[["HealthMonitor", dict[str, Any]], Any]


def _nonfinite(monitor: "HealthMonitor", sample: dict[str, Any]):
    for key in ("loss", "grad_norm"):
        value = sample.get(key)
        if isinstance(value, float) and not math.isfinite(value):
            return f"{key} is {value!r}: the update diverged", {"key": key}
    return None


def _grad_explosion(monitor: "HealthMonitor", sample: dict[str, Any]):
    limit = monitor.thresholds.grad_norm_max
    value = sample.get("grad_norm")
    if limit > 0 and isinstance(value, float) and math.isfinite(value):
        if value > limit:
            return (
                f"grad_norm {value:.3g} exceeds health_grad_norm_max "
                f"{limit:.3g}",
                {"grad_norm": value},
            )
    return None


def _learner_stall(monitor: "HealthMonitor", sample: dict[str, Any]):
    frac = sample.get("learner_stall_frac")
    if not isinstance(frac, float) or frac <= monitor.thresholds.stall_frac:
        return None
    stage, cause = monitor.bottleneck()
    message = (
        f"learner stalled {100.0 * frac:.0f}% of the window"
        + (f"; dominant wait {stage}: {cause}" if stage else "")
    )
    data = {"learner_stall_frac": frac}
    if stage:
        data["stage"] = stage
        data["component"] = _BLAME.get(stage, "learner")
    return message, data


def _admission_saturation(monitor: "HealthMonitor", sample: dict[str, Any]):
    overloads = monitor.delta(sample, "server_overload")
    sheds = monitor.delta(sample, "serve_shed")
    if overloads + sheds <= 0:
        return None
    return (
        f"serve admission gate saturated this window "
        f"({overloads:.0f} overloaded admissions, {sheds:.0f} shed)",
        {"overloads": overloads, "sheds": sheds},
    )


def _fps_collapse(monitor: "HealthMonitor", sample: dict[str, Any]):
    fps = sample.get("fps")
    hist = monitor.fps_history
    if not isinstance(fps, float) or len(hist) < 4:
        return None
    ordered = sorted(hist)
    median = ordered[len(ordered) // 2]
    floor = monitor.thresholds.fps_collapse * median
    if median <= 0 or fps >= floor:
        return None
    return (
        f"fps collapsed to {fps:,.0f} — below {floor:,.0f} "
        f"({monitor.thresholds.fps_collapse:.0%} of the run's trailing "
        f"median {median:,.0f})",
        {"fps": fps, "trailing_median": median},
    )


def _slo_breach(monitor: "HealthMonitor", sample: dict[str, Any]):
    breached = sample.get("serve_slo_breached")
    if not breached:
        monitor.slo_breach_run = 0
        return None
    monitor.slo_breach_run += 1
    if monitor.slo_breach_run < 2:
        return None  # one breached window is noise; persistence is signal
    p95 = sample.get("serve_p95_rolling_ms", 0.0)
    return (
        f"serve p95 over SLO target for {monitor.slo_breach_run} "
        f"consecutive windows (rolling p95 {p95:.1f}ms)",
        {"windows": monitor.slo_breach_run, "p95_rolling_ms": p95},
    )


def _restart_storm(monitor: "HealthMonitor", sample: dict[str, Any]):
    actors = monitor.delta(sample, "actor_restarts")
    servers = monitor.delta(sample, "server_restarts")
    if actors + servers < 2:
        return None
    return (
        f"{actors + servers:.0f} supervised restarts in one window "
        f"({actors:.0f} actor, {servers:.0f} server): restart-storm "
        "proximity (the supervisor aborts past its storm threshold)",
        {
            "actor_restarts": actors,
            "server_restarts": servers,
            "component": "actors" if actors >= servers else "server",
        },
    )


def _eval_regression(monitor: "HealthMonitor", sample: dict[str, Any]):
    drop = monitor.thresholds.eval_drop
    value = sample.get("eval_return")
    if drop <= 0 or not isinstance(value, float):
        return None
    best = monitor.eval_best
    monitor.eval_best = value if best is None else max(best, value)
    if best is None or value >= best - drop:
        return None
    return (
        f"eval_return {value:.2f} fell {best - value:.2f} below the "
        f"run's best {best:.2f} (health_eval_drop={drop:g})",
        {"eval_return": value, "best": best},
    )


def _finite_number(value: Any) -> bool:
    return (
        isinstance(value, (int, float))
        and not isinstance(value, bool)
        and math.isfinite(value)
    )


def _entropy_collapse(monitor: "HealthMonitor", sample: dict[str, Any]):
    floor = monitor.thresholds.entropy_floor
    value = sample.get("entropy")
    if floor <= 0 or not _finite_number(value) or value >= floor:
        return None
    return (
        f"policy entropy {value:.4g} below health_entropy_floor "
        f"{floor:g}: exploration collapsed (the policy went deterministic)",
        {"entropy": float(value)},
    )


def _staleness_runaway(monitor: "HealthMonitor", sample: dict[str, Any]):
    limit = monitor.thresholds.staleness_max
    value = sample.get("staleness_max")
    if limit <= 0 or not _finite_number(value) or value <= limit:
        return None
    p95 = sample.get("staleness_p95")
    return (
        f"behaviour-params staleness ran away: max lag {value:.0f} learner "
        f"updates (p95 {p95 if p95 is not None else '?'}) exceeds "
        f"health_staleness_max {limit:g} — actors are consuming weights "
        "far behind the learner",
        {"staleness_max": float(value), "staleness_p95": p95},
    )


def _rho_clip_saturation(monitor: "HealthMonitor", sample: dict[str, Any]):
    limit = monitor.thresholds.rho_clip_frac
    value = sample.get("rho_clip_frac")
    if limit <= 0 or not _finite_number(value) or value <= limit:
        return None
    return (
        f"V-trace rho-clip saturated: {100.0 * value:.0f}% of importance "
        f"weights pinned at the cap (> health_rho_clip_frac "
        f"{limit:g}) — the learner has drifted too far off-policy for "
        "the correction to be meaningful",
        {"rho_clip_frac": float(value)},
    )


def _recompile_storm(monitor: "HealthMonitor", sample: dict[str, Any]):
    limit = monitor.thresholds.recompile_storm
    if limit <= 0:
        return None
    if monitor._prev is None:
        # First window: delta() would return the whole cumulative counter,
        # which always includes the EXPECTED cold-start compilations
        # (learner step + first inference batches) — not a storm.
        return None
    grew = monitor.delta(sample, "compiles")
    if grew < limit:
        return None
    infer = monitor.delta(sample, "infer_recompile")
    learner = monitor.delta(sample, "learner_recompile")
    return (
        f"{grew:.0f} compilation(s) in one window (>= "
        f"health_recompile_storm {limit}): recompiles are taxing the hot "
        f"path ({infer:.0f} inference, {learner:.0f} learner — unstable "
        "batch shapes?)",
        {"compiles": grew, "infer_recompile": infer,
         "learner_recompile": learner},
    )


def _gateway_error_rate(monitor: "HealthMonitor", sample: dict[str, Any]):
    """The wire boundary's failure-fraction detector: fires when at least
    half of a window's gateway requests errored (500s, netfault-enacted
    disconnects, backend failures) over a minimum request floor — a
    handful of errors in a busy window is retry fodder, half the window
    failing is an outage. Quiet (and key-free) when the gateway is off:
    no ``gateway_requests`` key, no evaluation."""
    requests = monitor.delta(sample, "gateway_requests")
    if "gateway_requests" not in sample or requests < 4:
        return None
    errors = monitor.delta(sample, "gateway_errors")
    frac = errors / requests
    if frac < 0.5:
        return None
    return (
        f"gateway error rate {100.0 * frac:.0f}% this window "
        f"({errors:.0f}/{requests:.0f} requests failed)",
        {"errors": errors, "requests": requests, "error_frac": frac},
    )


def _breaker_open(monitor: "HealthMonitor", sample: dict[str, Any]):
    """A client-side circuit breaker sitting open means an endpoint is
    being refused without even trying — the load generator (or any
    in-process GatewayClient) exports its breaker states as gauges, and
    an open one degrades the gateway component until it re-closes."""
    value = sample.get("gateway_breaker_open")
    if not _finite_number(value) or value <= 0:
        return None
    return (
        f"{value:.0f} gateway circuit breaker(s) open: calls are refused "
        "client-side until a half-open probe succeeds",
        {"breakers_open": float(value)},
    )


def _replica_staleness_runaway(
    monitor: "HealthMonitor", sample: dict[str, Any]
):
    """The fleet's bounded-staleness contract, watched from the outside:
    fires when the worst replica's weight-sync lag reached the fleet's
    configured cap (at which point the fleet has ejected it — the event
    is the operator-visible record that the bound did its job, or that
    it keeps being hit). Quiet (and key-free) when no fleet is mounted:
    no ``fleet_staleness_max`` gauge, no evaluation."""
    value = sample.get("fleet_staleness_max")
    cap = sample.get("fleet_staleness_cap")
    if not _finite_number(value) or not _finite_number(cap) or cap <= 0:
        return None
    if value < cap:
        return None
    return (
        f"replica weight-sync staleness hit the cap: worst replica is "
        f"{value:.0f} version(s) behind its target (cap {cap:.0f}) — "
        "the fleet ejects at the bound; a persistent hit means a replica "
        "cannot keep up with the learner's publish rate",
        {"staleness_max": float(value), "staleness_cap": float(cap)},
    )


def _replica_flap(monitor: "HealthMonitor", sample: dict[str, Any]):
    """Repeated eject/readmit cycles: a replica oscillating through the
    half-open probe door is sick in a way neither steady ejection nor
    steady serving shows. Threshold 3 readmissions inside the fleet's
    60s flap horizon — one readmission is recovery, three is a flap."""
    value = sample.get("fleet_replica_flaps")
    if not _finite_number(value) or value < 3:
        return None
    return (
        f"{value:.0f} replica readmission(s) inside the flap horizon: a "
        "replica is cycling eject → probe → readmit — failing under "
        "load, recovering when drained",
        {"flaps": float(value)},
    )


def _memory_growth(monitor: "HealthMonitor", sample: dict[str, Any]):
    limit = monitor.thresholds.mem_growth
    if limit <= 0:
        return None
    value = sample.get("mem_device_bytes_in_use")
    key = "mem_device_bytes_in_use"
    if not _finite_number(value):
        value, key = sample.get("mem_host_rss_bytes"), "mem_host_rss_bytes"
    if not _finite_number(value) or value <= 0:
        return None
    baseline = monitor.mem_baseline
    if baseline is None or baseline <= 0:
        monitor.mem_baseline = float(value)
        return None
    if value <= baseline * (1.0 + limit):
        return None
    return (
        f"{key} grew to {value:,.0f} bytes — "
        f"{value / baseline - 1.0:+.0%} over the run's first watermark "
        f"{baseline:,.0f} (health_mem_growth {limit:g}): possible leak or "
        "unbounded cache",
        {"key": key, "bytes": float(value), "baseline": baseline},
    )


def default_detectors() -> list[Detector]:
    return [
        Detector("nonfinite_loss", "learner", "critical", _nonfinite),
        Detector("grad_explosion", "learner", "warn", _grad_explosion),
        Detector("learner_stall", "learner", "warn", _learner_stall),
        Detector(
            "admission_saturation", "serve-core", "warn",
            _admission_saturation,
        ),
        Detector("fps_collapse", "pipeline", "warn", _fps_collapse),
        Detector("slo_breach", "serve-core", "warn", _slo_breach),
        Detector("restart_storm", "actors", "critical", _restart_storm),
        Detector("eval_regression", "learner", "warn", _eval_regression),
        # Learning-health / device-behavior detectors (ISSUE 8), fed by
        # the loss-aux diagnostics and obs/introspect.py:
        Detector("entropy_collapse", "learner", "warn", _entropy_collapse),
        Detector(
            "staleness_runaway", "pipeline", "warn", _staleness_runaway
        ),
        Detector(
            "rho_clip_saturation", "learner", "warn", _rho_clip_saturation
        ),
        Detector("recompile_storm", "pipeline", "warn", _recompile_storm),
        Detector("memory_growth", "pipeline", "warn", _memory_growth),
        # Wire-boundary detectors (the external gateway, serve/gateway.py);
        # both quiet unless gateway keys are present in the window.
        Detector(
            "gateway_error_rate", "gateway", "warn", _gateway_error_rate
        ),
        Detector("breaker_open", "gateway", "warn", _breaker_open),
        # Replicated-fleet detectors (serve/fleet.py); both quiet unless
        # fleet gauges are present in the window.
        Detector(
            "replica_staleness_runaway", "fleet", "warn",
            _replica_staleness_runaway,
        ),
        Detector("replica_flap", "fleet", "warn", _replica_flap),
    ]


class HealthMonitor:
    """Evaluates the detector set at each window close and keeps the
    trailing state the verdict needs. Runs entirely on the trainer's
    window-close thread (no thread of its own); the HTTP endpoint reads
    :meth:`verdict` cross-thread, which only touches append-only /
    GIL-atomic state (the events deque and the window counter)."""

    def __init__(
        self,
        thresholds: Thresholds | None = None,
        store=None,
        tracer=None,
        detectors: list[Detector] | None = None,
        emit: bool = True,
        recorder: Any = flightrec,
        replica_probe: Callable[[], dict[str, Any]] | None = None,
    ):
        self.thresholds = thresholds or Thresholds()
        self.store = store
        self.tracer = tracer
        self.detectors = (
            detectors if detectors is not None else default_detectors()
        )
        # emit=False (the doctor's offline replay): pure evaluation — no
        # registry counters, no flight-recorder dumps.
        self.emit = emit
        # THE recorder this monitor's setup armed (the PipelineObs
        # isolation contract): a later trainer re-arming the process
        # globals must never redirect THIS trainer's health forensics
        # into its run_dir — nor resurrect dumps its setup disarmed.
        # Default is the module (process-global) for standalone use;
        # obs.setup always binds explicitly (its recorder, or None for
        # never-dump when it armed none).
        self.recorder = recorder
        # Per-replica health source (ServeFleet.replica_verdicts when a
        # fleet is mounted): surfaced verbatim in the /healthz payload
        # next to the aggregate components.
        self.replica_probe = replica_probe
        # Detector trailing state (window-close thread only).
        self.fps_history: deque[float] = deque(maxlen=32)
        self.slo_breach_run = 0
        self.eval_best: float | None = None
        # memory_growth's reference: the run's first recorded watermark.
        self.mem_baseline: float | None = None
        self._prev: dict[str, Any] | None = None
        self._prev_t = 0.0
        # Duration of the last CLOSED window — the span horizon for
        # post-close bottleneck() callers (the elastic blame veto runs
        # right after on_window has advanced _prev_t to now, so
        # time.time() - _prev_t would clamp to ~1s there).
        self.last_window_s = 60.0
        # lint: thread-shared-ok(GIL-atomic int; single-writer window counter, verdict() readers see the latest or previous window — both coherent)
        self.window_idx = 0
        # lint: thread-shared-ok(deque appends are GIL-atomic and verdict() iterates a list() copy; events are frozen after construction)
        self._events: deque[HealthEvent] = deque(maxlen=256)

    # ---------------------------------------------------- detector helpers

    def delta(self, sample: dict[str, Any], key: str) -> float:
        """This window's increase of a CUMULATIVE counter key."""
        now = sample.get(key, 0.0)
        if not isinstance(now, (int, float)) or isinstance(now, bool):
            return 0.0
        prev = (self._prev or {}).get(key, 0.0)
        if not isinstance(prev, (int, float)) or isinstance(prev, bool):
            prev = 0.0
        return float(now) - float(prev)

    def bottleneck(
        self, elapsed: float | None = None
    ) -> tuple[str | None, str | None]:
        """(dominant wait-span name, causal reading) over roughly the last
        window's spans, from the armed tracer's rings — (None, None) when
        tracing is off or nothing waited. Computed only when a detector is
        about to fire, never per window. The default horizon (time since
        the last window close) is right for detectors firing DURING
        ``on_window``; a caller running after the close (the elastic
        blame veto) must pass ``elapsed=monitor.last_window_s`` or the
        horizon collapses to the 1s clamp."""
        if self.tracer is None:
            return None, None
        if elapsed is None:
            elapsed = (
                max(1.0, time.time() - self._prev_t)
                if self._prev_t
                else 60.0
            )
        cutoff = time.perf_counter() - elapsed
        totals: dict[str, float] = {}
        for snap in self.tracer.snapshots():
            for name, start, end in snap["spans"]:
                if end >= cutoff and span_names.is_wait(name):
                    totals[name] = totals.get(name, 0.0) + (end - start)
        if not totals:
            return None, None
        stage = max(totals, key=totals.get)
        return stage, span_names.WAIT_CAUSES.get(stage, "")

    # ----------------------------------------------------------- evaluate

    def on_window(self, sample: dict[str, Any]) -> list[HealthEvent]:
        """Evaluate every detector against one window sample. Mutates the
        sample with ``health_events`` / ``health_status`` (so every sink
        and the store see the verdict inline), records the sample + any
        events into the store, and fires the flight recorder per event."""
        self.window_idx += 1
        env_steps = float(sample.get("env_steps", 0) or 0)
        now = time.time()
        events: list[HealthEvent] = []
        for det in self.detectors:
            try:
                result = det.fn(self, sample)
            # lint: broad-except-ok(a buggy detector must degrade to a counter, never take down the training loop it watches)
            except Exception:
                if self.emit:
                    registry.counter("health_detector_errors").inc()
                continue
            if not result:
                continue
            message, data = result
            events.append(
                HealthEvent(
                    detector=det.name,
                    component=data.pop("component", det.component),
                    severity=det.severity,
                    message=message,
                    window_idx=self.window_idx,
                    env_steps=env_steps,
                    t_unix=now,
                    data=data,
                )
            )
        fps = sample.get("fps")
        if isinstance(fps, float) and math.isfinite(fps):
            self.fps_history.append(fps)
        for event in events:
            self._events.append(event)
        sample["health_events"] = float(len(events))
        sample["health_status"] = self.status()
        if self.store is not None:
            self.store.append(sample)
            for event in events:
                self.store.annotate(event.to_dict())
        if self.emit:
            for event in events:
                registry.counter("health_events_total").inc()
                registry.counter(f"health_{event.detector}").inc()
                if self.recorder is not None:
                    self.recorder.record(
                        f"health.{event.detector}",
                        detail=event.message,
                        extra={"health_event": event.to_dict()},
                    )
        self._prev = sample
        if self._prev_t:
            self.last_window_s = max(1.0, now - self._prev_t)
        self._prev_t = now
        return events

    # ------------------------------------------------------------ verdict

    def recent_events(self) -> list[HealthEvent]:
        """Events still inside the verdict TTL (any thread)."""
        horizon = self.window_idx - self.thresholds.window_ttl
        return [e for e in list(self._events) if e.window_idx > horizon]

    def status(self) -> str:
        worst = "ok"
        for event in self.recent_events():
            status = "critical" if event.severity == "critical" else "degraded"
            if _STATUS_RANK[status] > _STATUS_RANK[worst]:
                worst = status
        return worst

    def verdict(self) -> dict[str, Any]:
        """The ``/healthz`` document: overall status + per-component
        status + the events that caused it (any thread)."""
        components = {c: "ok" for c in COMPONENTS}
        recent = self.recent_events()
        for event in recent:
            status = "critical" if event.severity == "critical" else "degraded"
            current = components.get(event.component, "ok")
            if _STATUS_RANK[status] > _STATUS_RANK[current]:
                components[event.component] = status
        worst = "ok"
        for status in components.values():
            if _STATUS_RANK[status] > _STATUS_RANK[worst]:
                worst = status
        latest = self.store.latest() if self.store is not None else None
        doc = {
            "status": worst,
            "window": self.window_idx,
            "env_steps": (latest or {}).get("env_steps", 0),
            "components": components,
            "recent_events": [e.to_dict() for e in recent],
            "detectors": [d.name for d in self.detectors],
            "ttl_windows": self.thresholds.window_ttl,
        }
        if self.replica_probe is not None:
            try:
                doc["replicas"] = self.replica_probe()
            # lint: broad-except-ok(a torn-down fleet must not 500 the health endpoint; the per-replica section just vanishes)
            except Exception:
                pass
        return doc


def replay(
    samples: list[dict[str, Any]],
    thresholds: Thresholds | None = None,
    detectors: list[Detector] | None = None,
) -> list[HealthEvent]:
    """Offline re-evaluation of the detector set over recorded samples
    (the doctor's path): the same conditions the live monitor ran, minus
    the tracer attribution and the flight-recorder side effects."""
    monitor = HealthMonitor(
        thresholds=thresholds, detectors=detectors, emit=False
    )
    events: list[HealthEvent] = []
    for sample in samples:
        # Copy: on_window mutates its sample, and replay must not scribble
        # health keys onto the caller's recorded history.
        events.extend(monitor.on_window(dict(sample)))
    return events
