"""Trace analysis: per-stage time shares, wait-vs-compute, stall attribution.

Consumes the ``trace_event`` document ``obs.export`` writes (or the
``trace`` section of a flight-recorder dump) and computes, per thread
group:

- **self time** per span name (nested spans subtract their children, so
  ``actor.lease_wait`` and the ``staging.reuse_wait`` inside it never
  double-count a second);
- the **wait vs compute** split (``obs.spans.is_wait``);
- a **stall-attribution table**: what fraction of each group's wall time
  each wait span owns, with the taxonomy's causal reading
  (``obs.spans.WAIT_CAUSES``) — the "learner idle 34% waiting on staging
  slab reuse" line the ISSUE asks for.

Spans within one thread are properly nested (context managers unwind
LIFO), so a single stack pass per thread attributes self time exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from asyncrl_tpu.obs import spans as span_names

_EPS_US = 1e-3  # float slack when testing span containment (µs)


@dataclasses.dataclass
class StageStat:
    name: str
    group: str
    count: int = 0
    total_us: float = 0.0  # full durations (mean span cost)
    self_us: float = 0.0   # minus child spans (time-share accounting)

    @property
    def is_wait(self) -> bool:
        return span_names.is_wait(self.name)


@dataclasses.dataclass
class GroupStat:
    group: str
    threads: int = 0
    wall_us: float = 0.0
    busy_us: float = 0.0

    @property
    def idle_us(self) -> float:
        return max(0.0, self.wall_us - self.busy_us)


def _thread_events(doc: dict[str, Any]):
    """tid -> (thread_name, group, [(ts, dur, name), ...])."""
    threads: dict[int, tuple[str, str]] = {}
    events: dict[int, list[tuple[float, float, str]]] = {}
    for ev in doc.get("traceEvents", []):
        tid = ev.get("tid", 0)
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            args = ev.get("args", {})
            threads[tid] = (
                args.get("name", f"tid-{tid}"),
                args.get("group", f"tid-{tid}"),
            )
        elif ev.get("ph") == "X":
            # Tolerate truncated/hand-edited documents: an event missing
            # its fields is skipped, not a raw KeyError traceback (the
            # validate subcommand names the exact violation).
            ts, dur, name = ev.get("ts"), ev.get("dur"), ev.get("name")
            if ts is None or dur is None or not name:
                continue
            events.setdefault(tid, []).append((float(ts), float(dur), name))
    out = {}
    for tid, evs in events.items():
        name, group = threads.get(tid, (f"tid-{tid}", f"tid-{tid}"))
        out[tid] = (name, group, sorted(evs, key=lambda e: (e[0], -e[1])))
    return out


def _self_times(evs: list[tuple[float, float, str]]):
    """Stack pass over one thread's sorted events: per-span self time.

    Yields (name, dur_us, self_us)."""
    stack: list[list] = []  # [ts, end, name, child_us]
    for ts, dur, name in evs:
        end = ts + dur
        while stack and stack[-1][1] <= ts + _EPS_US:
            yield _pop(stack)
        if stack and ts >= stack[-1][0] - _EPS_US and end <= stack[-1][1] + _EPS_US:
            stack[-1][3] += dur
        elif stack:
            # Overlap without containment (snapshot edge tear): close out.
            while stack:
                yield _pop(stack)
        stack.append([ts, end, name, 0.0])
    while stack:
        yield _pop(stack)


def _pop(stack):
    ts, end, name, child = stack.pop()
    dur = end - ts
    return name, dur, max(0.0, dur - child)


def analyze(doc: dict[str, Any]) -> dict[str, Any]:
    """Structured analysis of a trace document (see module docstring)."""
    stages: dict[tuple[str, str], StageStat] = {}
    groups: dict[str, GroupStat] = {}
    total_spans = 0
    t_min, t_max = float("inf"), 0.0
    for _tid, (_tname, group, evs) in sorted(_thread_events(doc).items()):
        if not evs:
            continue
        g = groups.setdefault(group, GroupStat(group))
        g.threads += 1
        start = evs[0][0]
        end = max(ts + dur for ts, dur, _ in evs)
        g.wall_us += end - start
        t_min, t_max = min(t_min, start), max(t_max, end)
        for name, dur, self_us in _self_times(evs):
            total_spans += 1
            st = stages.setdefault(
                (group, name), StageStat(name=name, group=group)
            )
            st.count += 1
            st.total_us += dur
            st.self_us += self_us
            g.busy_us += self_us
    waits = []
    for (group, name), st in stages.items():
        if st.is_wait and groups[group].wall_us > 0:
            waits.append(
                (st.self_us / groups[group].wall_us, group, name, st)
            )
    waits.sort(reverse=True, key=lambda w: w[0])
    return {
        "stages": sorted(
            stages.values(), key=lambda s: (s.group, -s.self_us)
        ),
        "groups": sorted(groups.values(), key=lambda g: g.group),
        "waits": waits,
        "total_spans": total_spans,
        "window_s": max(0.0, (t_max - t_min)) / 1e6 if total_spans else 0.0,
    }


def render(analysis: dict[str, Any]) -> str:
    """The human-readable report (the ``obs report`` CLI's output)."""
    lines: list[str] = []
    groups: list[GroupStat] = analysis["groups"]
    lines.append(
        f"pipeline report: {sum(g.threads for g in groups)} thread(s) in "
        f"{len(groups)} group(s), {analysis['total_spans']} spans, "
        f"window {analysis['window_s']:.2f}s"
    )
    lines.append("")
    lines.append("== per-stage time shares (self time) ==")
    header = (
        f"{'stage':<24} {'group':<10} {'count':>7} {'total_s':>9} "
        f"{'mean_ms':>9} {'share%':>7}  kind"
    )
    lines.append(header)
    for st in analysis["stages"]:
        wall = next(g.wall_us for g in groups if g.group == st.group)
        share = 100.0 * st.self_us / wall if wall else 0.0
        mean_ms = st.total_us / st.count / 1e3 if st.count else 0.0
        lines.append(
            f"{st.name:<24} {st.group:<10} {st.count:>7} "
            f"{st.self_us / 1e6:>9.3f} {mean_ms:>9.3f} {share:>7.1f}  "
            f"{'wait' if st.is_wait else 'compute'}"
        )
    lines.append("")
    lines.append("== wait vs compute ==")
    for g in groups:
        stage_list = [s for s in analysis["stages"] if s.group == g.group]
        wait_us = sum(s.self_us for s in stage_list if s.is_wait)
        compute_us = sum(s.self_us for s in stage_list if not s.is_wait)
        wall = g.wall_us or 1.0
        lines.append(
            f"{g.group}: busy {100.0 * compute_us / wall:5.1f}% | "
            f"waiting {100.0 * wait_us / wall:5.1f}% | "
            f"untraced {100.0 * g.idle_us / wall:5.1f}%   "
            f"(wall {g.wall_us / 1e6:.2f}s across {g.threads} thread(s))"
        )
    lines.append("")
    lines.append("== stall attribution ==")
    if not analysis["waits"]:
        lines.append("no wait spans recorded")
    for share, group, name, _st in analysis["waits"]:
        cause = span_names.WAIT_CAUSES.get(name, "")
        lines.append(
            f"{group} idle {100.0 * share:5.1f}% in {name}"
            + (f" — {cause}" if cause else "")
        )
    if analysis["waits"]:
        share, group, name, _ = analysis["waits"][0]
        lines.append("")
        lines.append(
            f"dominant stall: {name} ({100.0 * share:.1f}% of {group} "
            "wall time)"
        )
    return "\n".join(lines)
