"""CLI: ``python -m asyncrl_tpu.obs <report|validate|doctor> ...``.

``report`` prints the per-stage time shares, wait-vs-compute breakdown,
and stall-attribution table for an exported trace (``trace-*.json``) or a
flight-recorder dump (``flightrec-*.json`` — its embedded ``trace``
section is analyzed). ``validate`` checks the trace_event schema
(``obs.export.validate_trace``) and exits 1 on any violation — the gate
``scripts/trace_smoke.sh`` runs. ``doctor`` replays a recorded run_dir's
timeseries + forensics into a health report (detector timeline,
bottleneck attribution, BENCH_HISTORY regression verdict) and exits 1 on
a throughput regression — the gate ``scripts/health_smoke.sh`` runs.
``explain`` renders request hop journals from a run_dir's
``requests.jsonl`` as budget waterfalls — one journal by trace id, or
the ``--worst N`` set (non-200 verdicts first, then by latency); exits 2
when the file or the trace id is missing.
"""

from __future__ import annotations

import argparse
import json
import sys

from asyncrl_tpu.obs import doctor as doctor_mod
from asyncrl_tpu.obs import export as export_mod
from asyncrl_tpu.obs import flightrec, report
from asyncrl_tpu.obs import requests as requests_mod


def _load_trace_doc(path: str) -> tuple[dict, bool]:
    """(trace document, came-from-flightrec) for ``path``."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise SystemExit(f"{path}: cannot read trace file — {e}")
    except json.JSONDecodeError as e:
        raise SystemExit(f"{path}: not valid JSON — {e}")
    if isinstance(doc, dict) and doc.get("schema") == flightrec.SCHEMA:
        trace_doc = doc.get("trace")
        if not trace_doc:
            raise SystemExit(
                f"{path}: flight-recorder dump has no trace section "
                "(tracing was disabled when it was recorded)"
            )
        return trace_doc, True
    return doc, False


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m asyncrl_tpu.obs",
        description="pipeline-trace reporting and schema validation",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_report = sub.add_parser(
        "report",
        help="per-stage time shares + stall attribution for a trace or "
        "flight-recorder JSON",
    )
    p_report.add_argument("file", help="trace-*.json or flightrec-*.json")
    p_validate = sub.add_parser(
        "validate", help="validate a trace export against the schema"
    )
    p_validate.add_argument("file", help="trace-*.json or flightrec-*.json")
    p_doctor = sub.add_parser(
        "doctor",
        help="offline run-health report for a recorded run_dir "
        "(detector timeline + bottleneck attribution + BENCH_HISTORY "
        "regression verdict; exits 1 on regression)",
    )
    p_doctor.add_argument(
        "run_dir", help="run directory holding timeseries.jsonl"
    )
    p_doctor.add_argument(
        "--preset", default=None,
        help="BENCH_HISTORY preset to compare against (default: inferred "
        "from the run's env_id/algo)",
    )
    p_doctor.add_argument(
        "--fps-tolerance", type=float,
        default=doctor_mod.DEFAULT_FPS_TOLERANCE,
        help="regression bar: run best fps must reach this fraction of "
        "the baseline row (default %(default)s)",
    )
    p_doctor.add_argument(
        "--bench-history", default=None,
        help="ledger path (default: BENCH_HISTORY.json, or "
        "ASYNCRL_BENCH_HISTORY when set)",
    )
    p_explain = sub.add_parser(
        "explain",
        help="request budget waterfalls from a run_dir's requests.jsonl "
        "(one trace id, or --worst N; exits 2 when missing)",
    )
    p_explain.add_argument(
        "trace_id", nargs="?", default=None,
        help="wire trace id (X-Trace-Id) of the journal to render; omit "
        "with --worst to rank instead",
    )
    p_explain.add_argument(
        "run_dir", help="run directory holding requests.jsonl"
    )
    p_explain.add_argument(
        "--worst", type=int, default=0,
        help="render the N worst journals (non-200 first, then by "
        "latency) instead of one trace id",
    )
    args = parser.parse_args(argv)

    if args.cmd == "explain":
        text, code = requests_mod.explain(
            args.run_dir, trace_id=args.trace_id, worst=args.worst
        )
        print(text, file=sys.stderr if code == 2 else sys.stdout)
        return code

    if args.cmd == "doctor":
        text, code = doctor_mod.diagnose(
            args.run_dir,
            preset=args.preset,
            tolerance=args.fps_tolerance,
            history_path=args.bench_history,
        )
        print(text, file=sys.stderr if code == 2 else sys.stdout)
        return code

    doc, from_flightrec = _load_trace_doc(args.file)
    if args.cmd == "validate":
        # A flight dump with a quiet lookback window legitimately holds
        # zero spans; only a full run export must contain them.
        errors = export_mod.validate_trace(
            doc, require_spans=not from_flightrec
        )
        for err in errors:
            print(f"{args.file}: {err}", file=sys.stderr)
        if errors:
            print(
                f"{args.file}: INVALID ({len(errors)} schema violation(s))",
                file=sys.stderr,
            )
            return 1
        events = len(doc.get("traceEvents", []))
        print(f"{args.file}: valid {export_mod.SCHEMA} ({events} events)")
        return 0

    print(report.render(report.analyze(doc)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
