"""Flight recorder: crash-time forensics for the async host path.

On any fault, watchdog retirement, or supervisor restart, the pipeline's
last seconds — every thread's recent spans, the counters registry, the
fault counters, and the run config — dump to
``<run_dir>/flightrec-<seq>-<reason>.json``. The snapshot is taken on the
*reporting* thread at the moment of the event (so it is the state AT the
fault); serialization and disk I/O happen on a dedicated daemon writer
thread (``flightrec-writer``), so a dump never adds latency to the
supervisor's recovery path.

Debounce: at most one dump per reason per ``min_interval_s`` — a crash
storm produces a bounded number of files plus a ``flightrec_suppressed``
counter, never a disk flood. The dump's ``trace`` section is a regular
``obs.export`` trace document (filtered to the last ``window_s``), so
``python -m asyncrl_tpu.obs report flightrec-*.json`` and Perfetto both
open it.

Arming is explicit (``obs.setup`` arms it alongside tracing); the module
-level :func:`record` is a cheap no-op when unarmed, which is what the
``utils.faults`` and supervisor call sites rely on.
"""

from __future__ import annotations

import json
import os
import queue
import re
import threading
import time
from typing import Any

from asyncrl_tpu.obs import export, registry, trace
from asyncrl_tpu.obs import requests as requests_mod

SCHEMA = "asyncrl-flightrec-v1"

# Wire-facing failure reasons whose dumps embed the recent request hop
# journals (obs/requests.py): the forensics for "which requests were in
# flight and why did they end that way" live next to the spans.
_REQUEST_REASONS = ("netfault", "replica", "gateway")

_STOP = object()
_SAFE_REASON = re.compile(r"[^A-Za-z0-9_.-]+")


class FlightRecorder:
    """One run's dump sink. Thread-safe: any thread may ``record``."""

    def __init__(
        self,
        out_dir: str,
        window_s: float = 10.0,
        min_interval_s: float = 2.0,
        config: Any = None,
    ):
        self.out_dir = out_dir
        self.window_s = window_s
        self.min_interval_s = min_interval_s
        self._config = _config_dict(config)
        self._lock = threading.Lock()
        self._last: dict[str, float] = {}  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._pending = 0  # guarded-by: _lock
        self._thread: threading.Thread | None = None  # guarded-by: _lock
        # lint: thread-shared-ok(queue.Queue is internally synchronized; the reference itself is never rebound)
        self._q: "queue.Queue[Any]" = queue.Queue()
        self.paths: list[str] = []  # guarded-by: _lock

    def record(
        self, reason: str, detail: str = "", extra: dict | None = None
    ) -> bool:
        """Snapshot now, enqueue the dump. Returns False when debounced."""
        now = time.monotonic()
        with self._lock:
            last = self._last.get(reason)
            if last is not None and now - last < self.min_interval_s:
                suppressed = True
            else:
                suppressed = False
                self._last[reason] = now
                self._seq += 1
                seq = self._seq
                self._pending += 1
        if suppressed:
            registry.counter("flightrec_suppressed").inc()
            return False
        registry.counter("flightrec_dumps").inc()
        tracer = trace.active()
        doc = {
            "schema": SCHEMA,
            "reason": reason,
            "detail": detail,
            "ts_unix": time.time(),
            "pid": os.getpid(),
            "window_s": self.window_s,
            "thread": threading.current_thread().name,
            "config": self._config,
            "counters": _all_counters(),
            "extra": extra or {},
        }
        if any(k in reason for k in _REQUEST_REASONS):
            # [] when request journaling is disarmed — the off-is-off
            # discipline leaves the dump shape stable but empty.
            doc["requests"] = requests_mod.recent()
        if tracer is not None:
            cutoff = time.perf_counter() - self.window_s
            snaps = tracer.snapshots()
            for snap in snaps:
                snap["spans"] = [
                    s for s in snap["spans"] if s[2] >= cutoff
                ]
            doc["thread_groups"] = sorted(
                {s["group"] for s in snaps if s["spans"]}
            )
            doc["trace"] = export.to_trace_events(
                snaps, tracer.anchor_perf, tracer.anchor_unix
            )
        else:
            doc["thread_groups"] = []
            doc["trace"] = None
        self._ensure_writer()
        self._q.put_nowait((seq, reason, doc))
        return True

    def _ensure_writer(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._writer, name="flightrec-writer", daemon=True
            )
            self._thread.start()

    def _writer(self) -> None:  # thread-entry: flightrec-writer@flightrec
        while True:
            item = self._q.get()
            if item is _STOP:
                return
            seq, reason, doc = item
            try:
                path = self._write(seq, reason, doc)
                with self._lock:
                    self.paths.append(path)
            # lint: broad-except-ok(best-effort forensics: a full disk or unwritable run dir must never take down the writer, let alone the pipeline)
            except Exception as e:
                registry.counter("flightrec_write_errors").inc()
                print(f"flightrec: dump failed: {e}", flush=True)
            finally:
                with self._lock:
                    self._pending -= 1

    def _write(self, seq: int, reason: str, doc: dict) -> str:
        os.makedirs(self.out_dir, exist_ok=True)
        slug = _SAFE_REASON.sub("-", reason)[:64] or "event"
        # pid in the name: two processes sharing a run_dir both start
        # their seq at 1 — forensics must never overwrite each other.
        path = os.path.join(
            self.out_dir,
            f"flightrec-{os.getpid()}-{seq:03d}-{slug}.json",
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    def drain(self, timeout: float = 5.0) -> bool:
        """Block until every enqueued dump is on disk (tests; shutdown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._pending == 0:
                    return True
            time.sleep(0.01)
        with self._lock:
            return self._pending == 0

    def close(self) -> None:
        """Flush pending dumps and stop the writer thread."""
        self.drain()
        with self._lock:
            thread = self._thread
            self._thread = None
        if thread is not None and thread.is_alive():
            self._q.put_nowait(_STOP)
            thread.join(timeout=2.0)


def _config_dict(config: Any) -> dict | None:
    """A JSON-dumpable view of the run config (json serializes tuples as
    arrays on its own, so plain ``asdict`` suffices)."""
    if config is None:
        return None
    if isinstance(config, dict):
        return config
    import dataclasses

    if dataclasses.is_dataclass(config):
        return dataclasses.asdict(config)
    return {"repr": repr(config)}


def _all_counters() -> dict[str, float]:
    """Registry + fault + trace counters, one flat dict (the same keys
    the metrics window carries, so forensics and JSONL line up)."""
    from asyncrl_tpu.utils import faults

    out: dict[str, float] = {}
    out.update(registry.window())
    out.update(faults.counters())
    out.update(trace.stats())
    return out


_ARM_LOCK = threading.Lock()
# lint: thread-shared-ok(single reference swap under _ARM_LOCK; lock-free readers see None or a fully-constructed recorder)
_RECORDER: FlightRecorder | None = None


def arm(
    out_dir: str,
    window_s: float = 10.0,
    min_interval_s: float = 2.0,
    config: Any = None,
) -> FlightRecorder:
    """Arm the process-wide recorder (replacing any previous one)."""
    global _RECORDER
    with _ARM_LOCK:
        old, _RECORDER = _RECORDER, FlightRecorder(
            out_dir, window_s=window_s, min_interval_s=min_interval_s,
            config=config,
        )
    if old is not None:
        old.close()
    return _RECORDER


def disarm() -> None:
    global _RECORDER
    with _ARM_LOCK:
        old, _RECORDER = _RECORDER, None
    if old is not None:
        old.close()


def active() -> FlightRecorder | None:
    return _RECORDER


def record(reason: str, detail: str = "", extra: dict | None = None) -> bool:
    """The call-site entry point (faults, supervisor): no-op when unarmed."""
    recorder = _RECORDER
    if recorder is None:
        return False
    return recorder.record(reason, detail=detail, extra=extra)
