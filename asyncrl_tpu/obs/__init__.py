"""``asyncrl_tpu.obs``: pipeline tracing, metrics registry, flight recorder.

The observability subsystem for the async host path (ISSUE 5):

- :mod:`asyncrl_tpu.obs.trace` — per-thread lock-free span rings behind
  ``trace.span("actor.env_step")`` context managers (near-zero cost when
  disabled).
- :mod:`asyncrl_tpu.obs.spans` — the span taxonomy + wait/compute
  classification + stall causes.
- :mod:`asyncrl_tpu.obs.registry` — the counters/histograms registry the
  metric window sinks drain from.
- :mod:`asyncrl_tpu.obs.export` — Chrome/Perfetto ``trace_event`` JSON
  export and its schema validator.
- :mod:`asyncrl_tpu.obs.report` — per-stage time shares, wait-vs-compute
  breakdown, stall attribution (the ``python -m asyncrl_tpu.obs report``
  CLI).
- :mod:`asyncrl_tpu.obs.flightrec` — crash-time span/counter dumps to
  ``runs/<run>/flightrec-*.json``.

:func:`setup` is the trainer-facing entry point: it arms tracing and the
flight recorder per ``config.trace`` (``ASYNCRL_TRACE`` wins when set,
mirroring ``utils.faults``) and returns the handle the trainer's window
aggregation and teardown drive.
"""

from __future__ import annotations

import itertools
import os
import time

from asyncrl_tpu.obs import export, flightrec, registry, trace

# Process-wide export sequence: two agents sharing a run_dir (A/B
# harnesses) must never overwrite each other's same-second export.
# lint: thread-shared-ok(itertools.count.__next__ is GIL-atomic)
_EXPORT_SEQ = itertools.count(1)

__all__ = [
    "PipelineObs", "setup", "export", "flightrec", "registry", "trace",
]


def _default_run_dir(config) -> str:
    slug = "".join(
        ch if ch.isalnum() else "-" for ch in str(config.env_id)
    ).strip("-").lower()
    stamp = time.strftime("%Y%m%d-%H%M%S")
    return os.path.join(
        "runs", f"{slug}-{config.algo}-s{config.seed}-{stamp}-{os.getpid()}"
    )


class PipelineObs:
    """One trainer's observability handle (always constructed; inert when
    tracing is disabled — ``window()`` still drains the registry, which is
    the one metrics path that runs unconditionally). The handle holds THE
    tracer/recorder its setup armed: a later trainer re-arming the globals
    must never redirect this trainer's export or stats to its own rings."""

    def __init__(self, enabled: bool, run_dir: str | None, recorder,
                 tracer=None):
        self.enabled = enabled
        self.run_dir = run_dir
        self._recorder = recorder
        self._tracer = tracer

    def window(self) -> dict[str, float]:
        """Counters/histograms + this trainer's trace stats for one
        metrics window."""
        out = registry.window()
        if self._tracer is not None:
            out.update(self._tracer.stats())
        return out

    def export_trace(self) -> str | None:
        """Write THIS trainer's rings as a Perfetto export into the run
        dir (None when tracing is off); called from close()."""
        if not self.enabled or self.run_dir is None or self._tracer is None:
            return None
        seq = next(_EXPORT_SEQ)
        # stamp + pid + per-process seq: unique across agents in one
        # process AND across processes sharing a run_dir.
        path = os.path.join(
            self.run_dir,
            f"trace-{time.strftime('%Y%m%d-%H%M%S')}"
            f"-{os.getpid()}-{seq:03d}.json",
        )
        doc = export.to_trace_events(
            self._tracer.snapshots(),
            self._tracer.anchor_perf,
            self._tracer.anchor_unix,
        )
        return export.write_document(doc, path)

    def close(self) -> None:
        """Flush this trainer's flight recorder (only if it is still the
        armed one — a newer trainer's recorder is not ours to close)."""
        if self._recorder is not None and flightrec.active() is self._recorder:
            self._recorder.drain()


def setup(config) -> PipelineObs:
    """Arm tracing + flight recorder for a trainer, per config/env.

    ``ASYNCRL_TRACE`` (when present) wins over ``config.trace`` — the
    no-code-change knob, exactly the ``ASYNCRL_FAULTS`` precedence. The
    registry resets so a fresh agent never reports a predecessor's
    counters (same semantics as re-arming faults).
    """
    registry.registry().reset()
    env = trace.env_requests()
    enabled = bool(config.trace) if env is None else env
    # Always RE-ARM (even under env arming): a fresh agent gets fresh
    # rings — its export/dumps/stats must never include a predecessor's
    # spans. Env arming keeps the env's ring capacity; config arming
    # uses config.trace_ring.
    tracer = trace.configure(
        enabled, capacity=config.trace_ring if env is None else None
    )
    if not enabled:
        # Disarm any predecessor's flight recorder too: a trace=False
        # agent must never dump forensics into an OLD agent's run_dir
        # with the old agent's config embedded (faults.arm("") precedent).
        flightrec.disarm()
        return PipelineObs(False, None, None)
    run_dir = (
        os.environ.get("ASYNCRL_RUN_DIR")
        or config.run_dir
        or _default_run_dir(config)
    )
    recorder = flightrec.arm(
        run_dir, window_s=config.trace_window_s, config=config
    )
    return PipelineObs(True, run_dir, recorder, tracer=tracer)
