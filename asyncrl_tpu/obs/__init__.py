"""``asyncrl_tpu.obs``: tracing, metrics registry, run-health telemetry.

The observability subsystem for the async host path (ISSUE 5 + ISSUE 7):

- :mod:`asyncrl_tpu.obs.trace` — per-thread lock-free span rings behind
  ``trace.span("actor.env_step")`` context managers (near-zero cost when
  disabled).
- :mod:`asyncrl_tpu.obs.spans` — the span taxonomy + wait/compute
  classification + stall causes.
- :mod:`asyncrl_tpu.obs.registry` — the counters/gauges/histograms
  registry the metric window sinks drain from.
- :mod:`asyncrl_tpu.obs.export` — Chrome/Perfetto ``trace_event`` JSON
  export and its schema validator.
- :mod:`asyncrl_tpu.obs.report` — per-stage time shares, wait-vs-compute
  breakdown, stall attribution (the ``python -m asyncrl_tpu.obs report``
  CLI).
- :mod:`asyncrl_tpu.obs.flightrec` — crash-time span/counter dumps to
  ``runs/<run>/flightrec-*.json``.
- :mod:`asyncrl_tpu.obs.timeseries` — the bounded per-window sample ring
  persisted to ``runs/<run>/timeseries.jsonl``.
- :mod:`asyncrl_tpu.obs.health` — the detector framework evaluated at
  each window close (NaN loss, stall attribution, fps collapse, SLO
  breach persistence, restart storms, eval regression), each firing a
  flight-recorder dump with ``reason=health.<detector>``.
- :mod:`asyncrl_tpu.obs.http` — the ``/metrics`` / ``/healthz`` /
  ``/timeseries`` exposition endpoint (``config.obs_http_port`` /
  ``ASYNCRL_OBS_PORT``; off by default — zero threads when off).
- :mod:`asyncrl_tpu.obs.introspect` — training introspection (ISSUE 8):
  off-policy staleness aggregation, compile/recompile accounting with
  static-shape blame on the learner/inference entry points, and memory
  watermarks (``config.introspect`` / ``ASYNCRL_INTROSPECT``; on by
  default).
- :mod:`asyncrl_tpu.obs.doctor` — offline run diagnosis
  (``python -m asyncrl_tpu.obs doctor <run_dir>``).

:func:`setup` is the trainer-facing entry point: it arms tracing and the
flight recorder per ``config.trace`` (``ASYNCRL_TRACE`` wins when set,
mirroring ``utils.faults``), mounts the time-series store + health
monitor (+ the HTTP endpoint when a port is configured), and returns the
handle the trainer's window aggregation and teardown drive.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import sys
import time

from asyncrl_tpu.obs import export, flightrec, introspect, registry, trace
from asyncrl_tpu.obs import health as health_mod
from asyncrl_tpu.obs import http as http_mod
from asyncrl_tpu.obs import requests as requests_mod
from asyncrl_tpu.obs import timeseries as timeseries_mod

# Process-wide export sequence: two agents sharing a run_dir (A/B
# harnesses) must never overwrite each other's same-second export.
# lint: thread-shared-ok(itertools.count.__next__ is GIL-atomic)
_EXPORT_SEQ = itertools.count(1)

__all__ = [
    "PipelineObs", "setup", "export", "flightrec", "introspect",
    "registry", "trace",
]


def _default_run_dir(config) -> str:
    slug = "".join(
        ch if ch.isalnum() else "-" for ch in str(config.env_id)
    ).strip("-").lower()
    stamp = time.strftime("%Y%m%d-%H%M%S")
    return os.path.join(
        "runs", f"{slug}-{config.algo}-s{config.seed}-{stamp}-{os.getpid()}"
    )


def _arm_requests(config, run_dir: str | None) -> None:
    """Arm/disarm request hop journaling (obs/requests.py) per
    ``config.request_trace``, ``ASYNCRL_REQUEST_TRACE`` winning when set
    — the trace-arming precedence. Off DISARMS any predecessor's store
    (fresh-agent semantics); on with no run_dir keeps the recent ring and
    span emission but persists no ``requests.jsonl``."""
    env = requests_mod.env_requests()
    on = bool(config.request_trace) if env is None else env
    if on:
        requests_mod.arm(
            run_dir=run_dir,
            cap=config.request_journal_cap,
            slow_ms=config.request_sample_slow_ms,
            meta={"env_id": config.env_id, "algo": config.algo,
                  "seed": config.seed},
        )
    else:
        requests_mod.disarm()


def _platform() -> str | None:
    """The JAX backend platform for the timeseries meta (doctor matches
    BENCH_HISTORY rows on it). Lazy + failure-tolerant: obs must stay
    importable (and setup must succeed) without a working jax install."""
    # lint: broad-except-ok(metadata enrichment only; a broken jax backend must not break observability setup)
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return None


class PipelineObs:
    """One trainer's observability handle (always constructed; inert when
    everything is disabled — ``window()`` still drains the registry, which
    is the one metrics path that runs unconditionally). The handle holds
    THE tracer/recorder/store its setup mounted: a later trainer re-arming
    the globals must never redirect this trainer's export, stats, or
    health telemetry to its own rings."""

    def __init__(self, enabled: bool, run_dir: str | None, recorder,
                 tracer=None, store=None, monitor=None, http=None,
                 introspect_on: bool = False):
        self.enabled = enabled
        self.run_dir = run_dir
        self._recorder = recorder
        self._tracer = tracer
        self.store = store
        self.monitor = monitor
        self.http = http
        # Training introspection (obs/introspect.py): when on, the window
        # drain samples the memory watermarks (registry gauges) and
        # persists pending compile events into the time-series store.
        self.introspect_on = introspect_on

    def window(self) -> dict[str, float]:
        """Counters/gauges/histograms + this trainer's trace stats for one
        metrics window."""
        out = registry.window()
        if self._tracer is not None:
            out.update(self._tracer.stats())
        return out

    def observe_window(self, agg: dict) -> dict:
        """THE per-window drain: merges :meth:`window` (ONE registry
        snapshot) into ``agg``, then runs the health detectors and records
        the sample into the time-series store. Every downstream consumer —
        stdout, JSONL, TensorBoard, the timeseries, ``/metrics`` — sees
        this identical dict: no sink can drift on which keys a window
        carries. Returns ``agg`` (mutated in place)."""
        if self.introspect_on:
            # Memory watermarks FIRST (they publish as registry gauges),
            # so the one registry snapshot below already carries them.
            introspect.sample_memory()
        agg.update(self.window())
        if self.monitor is not None:
            # The monitor owns the store.append (sample + annotations in
            # order); setup() never mounts a store without a monitor.
            self.monitor.on_window(agg)
        if self.store is not None:
            # Compile events recorded since the last window (any thread)
            # persist as kind=event annotations AFTER the sample, on this
            # (the writer) thread — the store's single-writer contract.
            for event in introspect.drain_compile_events():
                self.store.annotate(event)
        return agg

    def export_trace(self) -> str | None:
        """Write THIS trainer's rings as a Perfetto export into the run
        dir (None when tracing is off); called from close()."""
        if not self.enabled or self.run_dir is None or self._tracer is None:
            return None
        seq = next(_EXPORT_SEQ)
        # stamp + pid + per-process seq: unique across agents in one
        # process AND across processes sharing a run_dir.
        path = os.path.join(
            self.run_dir,
            f"trace-{time.strftime('%Y%m%d-%H%M%S')}"
            f"-{os.getpid()}-{seq:03d}.json",
        )
        doc = export.to_trace_events(
            self._tracer.snapshots(),
            self._tracer.anchor_perf,
            self._tracer.anchor_unix,
        )
        return export.write_document(doc, path)

    def close(self) -> None:
        """Flush this trainer's flight recorder (only if it is still the
        armed one — a newer trainer's recorder is not ours to close).
        Non-destructive and re-callable: ``train()`` calls it at the end
        of EVERY call, and the agent may train again."""
        if self._recorder is not None and flightrec.active() is self._recorder:
            self._recorder.drain()

    def shutdown(self) -> None:
        """Final teardown (the agent's ``close()``): stop the exposition
        endpoint, close the time-series JSONL, flush forensics.
        Idempotent."""
        if self.http is not None:
            self.http.stop()
            self.http = None
        if self.store is not None:
            self.store.close()
        self.close()


def setup(config) -> PipelineObs:
    """Arm tracing + flight recorder + run-health telemetry per config/env.

    ``ASYNCRL_TRACE`` (when present) wins over ``config.trace``, and
    ``ASYNCRL_OBS_PORT`` over ``config.obs_http_port`` — the
    no-code-change knobs, exactly the ``ASYNCRL_FAULTS`` precedence. The
    registry resets so a fresh agent never reports a predecessor's
    counters (same semantics as re-arming faults).

    The health layer (store + detectors) mounts when tracing is on OR an
    exposition port is configured; with both off the handle is inert and
    the per-window cost is exactly one registry snapshot. The HTTP server
    thread exists only when a port is configured (endpoint off ⇒ zero
    threads).
    """
    registry.registry().reset()
    # A fresh agent must never persist a predecessor's compile events
    # into its own run_dir (the registry-reset semantics).
    introspect.reset()
    intro = introspect.enabled(config)
    env = trace.env_requests()
    enabled = bool(config.trace) if env is None else env
    # Always RE-ARM (even under env arming): a fresh agent gets fresh
    # rings — its export/dumps/stats must never include a predecessor's
    # spans. Env arming keeps the env's ring capacity; config arming
    # uses config.trace_ring.
    tracer = trace.configure(
        enabled, capacity=config.trace_ring if env is None else None
    )
    port = http_mod.env_port(config.obs_http_port)
    if not enabled and port == 0:
        # Disarm any predecessor's flight recorder too: a trace=False
        # agent must never dump forensics into an OLD agent's run_dir
        # with the old agent's config embedded (faults.arm("") precedent).
        flightrec.disarm()
        # Request journaling is orthogonal to span tracing: a serving
        # deployment may want hop journals without paying for ring
        # tracing, so it arms even on this early-return path.
        _arm_requests(
            config,
            os.environ.get("ASYNCRL_RUN_DIR") or config.run_dir or None,
        )
        return PipelineObs(False, None, None, introspect_on=intro)
    if enabled:
        run_dir = (
            os.environ.get("ASYNCRL_RUN_DIR")
            or config.run_dir
            or _default_run_dir(config)
        )
        recorder = flightrec.arm(
            run_dir, window_s=config.trace_window_s, config=config
        )
    else:
        # Endpoint without tracing: live exposition only. No flight
        # recorder (nothing armed to dump spans), and the timeseries
        # persists only if the operator named a run_dir explicitly.
        flightrec.disarm()
        recorder = None
        run_dir = os.environ.get("ASYNCRL_RUN_DIR") or config.run_dir or None
    _arm_requests(config, run_dir)
    thresholds = health_mod.Thresholds.from_config(config)
    store = timeseries_mod.TimeSeriesStore(
        capacity=config.obs_timeseries_cap,
        persist_path=(
            os.path.join(run_dir, timeseries_mod.FILENAME) if run_dir else None
        ),
        meta={
            "env_id": config.env_id,
            "algo": config.algo,
            "backend": config.backend,
            "seed": config.seed,
            "num_envs": config.num_envs,
            "unroll_len": config.unroll_len,
            "platform": _platform(),
            "thresholds": dataclasses.asdict(thresholds),
        },
    )
    # The monitor binds THE recorder this setup armed (None when tracing
    # is off): a later trainer re-arming the global flight recorder must
    # never receive — or redirect — this trainer's health forensics.
    monitor = health_mod.HealthMonitor(
        thresholds=thresholds, store=store, tracer=tracer,
        recorder=recorder,
    )
    server = None
    if port != 0:
        try:
            server = http_mod.ObsHTTPServer(
                port=port, store=store, monitor=monitor,
                bind_host=http_mod.env_host(config.obs_http_host),
            ).start()
        except OSError as e:
            # A taken/forbidden port must not kill training — the run is
            # the product, the endpoint is the window onto it.
            print(
                f"asyncrl_tpu.obs: could not bind exposition endpoint on "
                f"port {port}: {e} (continuing without /metrics)",
                file=sys.stderr,
            )
    return PipelineObs(
        enabled, run_dir, recorder, tracer=tracer,
        store=store, monitor=monitor, http=server, introspect_on=intro,
    )
