"""The span taxonomy: every instrumented stage of the async host path.

One module owns the vocabulary so the instrumentation sites, the report's
stall attribution, and the flight recorder can never drift on what a span
name means. Names are ``<stage>.<what>``; the stage prefix groups spans in
the Perfetto export (``cat``) and the report tables.

Wait vs compute: a span is a WAIT span when the thread is blocked on
another pipeline stage (queue empty/full, slab reuse, device readiness) —
the report attributes thread idleness to these by name. Everything else is
compute. The classification is by exact name first, then by the
``*_wait`` suffix convention, so a new wait span is classified correctly
even before it is added to the cause table.
"""

from __future__ import annotations

# Actor threads (rollout/sebulba.py ActorThread._run).
ACTOR_INFERENCE = "actor.inference"      # batched action selection + sync
ACTOR_ENV_STEP = "actor.env_step"        # host env pool step
ACTOR_LEASE_WAIT = "actor.lease_wait"    # staging-slab row acquisition
ACTOR_QUEUE_PUT = "actor.queue_put"      # fragment hand-off (incl. backpressure)

# Staging ring internals (rollout/staging.py).
STAGING_REUSE_WAIT = "staging.reuse_wait"  # blocked on in-flight slab readiness

# Shared inference server (rollout/inference_server.py).
SERVER_COLLECT_WAIT = "server.collect_wait"  # waiting for client requests
SERVER_SERVE = "server.serve"                # coalesce + batched device call

# Serving core (asyncrl_tpu/serve/): continuous batching + zero-drain swaps.
SERVE_ADMIT_WAIT = "serve.admit_wait"    # client blocked at the admission gate
SERVE_BATCH_FILL = "serve.batch_fill"    # scheduler holding a partial batch open
SERVE_DISPATCH = "serve.dispatch"        # coalesce + batched device call
SERVE_SWAP_DRAIN = "serve.swap_drain"    # waiting for old-generation batches

# External gateway (serve/gateway.py): the wire boundary over the serve core.
GATEWAY_ADMIT_WAIT = "gateway.admit_wait"  # request held at tenant admission
GATEWAY_SERVE = "gateway.serve"            # backend call (act/evaluate)

# Elastic runtime (asyncrl_tpu/runtime/elastic.py): the save → reconfigure
# → restore barrier around a fleet-scale action. Runs on the learner
# (window-close) thread; a COMPUTE span — its cost is the price of a scale
# event, not a wait on another stage.
ELASTIC_RECONFIGURE = "elastic.reconfigure"

# Learner drain (api/sebulba_trainer.py train loop + learn/rollout_learner.py).
LEARNER_QUEUE_WAIT = "learner.queue_wait"    # fragment queue empty (starved)
LEARNER_H2D = "learner.h2d"                  # device_put dispatch
LEARNER_H2D_WAIT = "learner.h2d_wait"        # unhidden transfer barrier
LEARNER_UPDATE = "learner.update"            # jitted update dispatch
LEARNER_METRICS = "learner.metrics_drain"    # device_get of pending metrics
LEARNER_EVAL = "learner.eval"                # in-training greedy evaluation

# Spans where the thread is blocked on ANOTHER stage of the pipeline.
WAIT_SPANS = frozenset({
    ACTOR_LEASE_WAIT,
    ACTOR_QUEUE_PUT,
    STAGING_REUSE_WAIT,
    SERVER_COLLECT_WAIT,
    SERVE_ADMIT_WAIT,
    SERVE_BATCH_FILL,
    SERVE_SWAP_DRAIN,
    GATEWAY_ADMIT_WAIT,
    LEARNER_QUEUE_WAIT,
    LEARNER_H2D_WAIT,
})

# What a high share in each wait span MEANS — the stall-attribution table's
# causal reading, kept next to the names so instrumentation and diagnosis
# cannot drift apart.
WAIT_CAUSES = {
    LEARNER_QUEUE_WAIT: (
        "learner starved for fragments: actors (env stepping / inference) "
        "are the bottleneck"
    ),
    LEARNER_H2D_WAIT: (
        "host->device transfer time not hidden behind the previous "
        "update's compute"
    ),
    ACTOR_LEASE_WAIT: (
        "no free staging slab row: waiting on slab reuse — the learner/"
        "device side is the bottleneck or the ring is too shallow"
    ),
    STAGING_REUSE_WAIT: (
        "waiting on an in-flight slab's device readiness (slab reuse): "
        "deepen staging_slabs or speed up the consuming update"
    ),
    ACTOR_QUEUE_PUT: (
        "fragment queue full (backpressure): the learner drain is the "
        "bottleneck"
    ),
    SERVER_COLLECT_WAIT: (
        "inference server idle between requests: actors are busy stepping "
        "envs (healthy) or dead/restarting (check supervisor counters)"
    ),
    SERVE_ADMIT_WAIT: (
        "clients held at the serve admission gate (SLO backpressure or "
        "inflight cap): the server is the bottleneck — it cannot keep "
        "latency inside target at the offered load"
    ),
    SERVE_BATCH_FILL: (
        "scheduler holding partial batches open for more requests: clients "
        "are slow to submit (healthy under light load); a high share paired "
        "with mostly deadline-flush dispatches means the deadline budget is "
        "long relative to client cadence — tighten serve_deadline_ms"
    ),
    SERVE_SWAP_DRAIN: (
        "waiting for in-flight batches pinned to an old param generation "
        "to retire: dispatches are long relative to the publish cadence "
        "(teardown/barrier paths only — the swap itself never blocks)"
    ),
    GATEWAY_ADMIT_WAIT: (
        "external requests held at the gateway's tenant admission layer "
        "(token bucket / per-tenant SLO class): offered wire load exceeds "
        "the tenant's provisioned rate — shed responses carry Retry-After"
    ),
}


def is_wait(name: str) -> bool:
    """WAIT span? Exact taxonomy membership, else the suffix convention."""
    return name in WAIT_SPANS or name.endswith("_wait")


def stage_of(name: str) -> str:
    """The stage prefix (``actor``/``server``/``learner``/``staging``)."""
    return name.split(".", 1)[0]


# Thread-name -> thread-group mapping (the flight recorder's "distinct
# thread groups" and the report's per-group rollup). Threads the framework
# names map to their subsystem; anything else groups as its own name, and
# a thread can override explicitly via ``trace.tag_thread``.
_GROUP_PREFIXES = (
    ("actor-", "actor"),
    ("inference-server", "server"),
    ("serve-core", "server"),
    ("flightrec-", "flightrec"),
    ("obs-http", "obs"),
    ("gateway-", "gateway"),
    ("checkpoint", "checkpoint"),
)


def thread_group(thread_name: str) -> str:
    for prefix, group in _GROUP_PREFIXES:
        if thread_name.startswith(prefix):
            return group
    return thread_name
