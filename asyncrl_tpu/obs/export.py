"""Chrome/Perfetto ``trace_event`` JSON export of the span rings.

The output is the JSON Object Format the Trace Event spec defines (and
Perfetto's UI at https://ui.perfetto.dev opens directly): complete-span
``"X"`` events with microsecond ``ts``/``dur``, one ``"M"``
``thread_name`` metadata event per ring, span stage as ``cat``. The same
schema is what :func:`validate_trace` checks — ``scripts/trace_smoke.sh``
gates on it, so the exporter and the validator live side by side.
"""

from __future__ import annotations

import json
import os
from typing import Any

from asyncrl_tpu.obs import spans as span_names
from asyncrl_tpu.obs import trace

SCHEMA = "asyncrl-trace-v1"


def to_trace_events(
    snapshots: list[dict[str, Any]],
    anchor_perf: float,
    anchor_unix: float,
) -> dict[str, Any]:
    """Snapshot list -> the Perfetto-loadable trace document."""
    pid = os.getpid()
    events: list[dict[str, Any]] = []
    for tid, snap in enumerate(snapshots, start=1):
        events.append({
            "ph": "M",
            "name": "thread_name",
            "pid": pid,
            "tid": tid,
            "args": {"name": snap["thread"], "group": snap["group"]},
        })
        for span in snap["spans"]:
            # 3-tuple (name, start, end), or 4-tuple with a meta dict —
            # request-journal replay spans carry their trace id, which
            # lands in Perfetto's args pane.
            name, start, end = span[0], span[1], span[2]
            event = {
                "ph": "X",
                "name": name,
                "cat": span_names.stage_of(name),
                "pid": pid,
                "tid": tid,
                "ts": max(0.0, (start - anchor_perf) * 1e6),
                "dur": max(0.0, (end - start) * 1e6),
            }
            if len(span) > 3 and span[3]:
                event["args"] = dict(span[3])
            events.append(event)
    return {
        "schema": SCHEMA,
        "displayTimeUnit": "ms",
        "metadata": {
            "anchor_unix": anchor_unix,
            "threads": [
                {
                    "thread": s["thread"],
                    "group": s["group"],
                    "recorded": s["recorded"],
                    "dropped": s["dropped"],
                }
                for s in snapshots
            ],
        },
        "traceEvents": events,
    }


def export_document() -> dict[str, Any] | None:
    """The armed tracer's current trace document (None when disabled)."""
    tracer = trace.active()
    if tracer is None:
        return None
    return to_trace_events(
        tracer.snapshots(), tracer.anchor_perf, tracer.anchor_unix
    )


def write_document(doc: dict[str, Any], path: str) -> str:
    """Serialize a trace document to ``path`` (created dirs included)."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def write_trace(path: str) -> str | None:
    """Export the armed tracer to ``path``; returns the path, or None
    when tracing is disabled."""
    doc = export_document()
    if doc is None:
        return None
    return write_document(doc, path)


def validate_trace(
    doc: dict[str, Any], require_spans: bool = True
) -> list[str]:
    """Schema check for an exported trace document; returns the list of
    violations (empty = valid). One shared home: the exporter above and
    ``scripts/trace_smoke.sh``'s gate can never drift.

    ``require_spans=False`` accepts a span-less document: a flight dump
    whose lookback window was quiet (the pipeline wedged outside any
    instrumented stage) is correctly recorded, not malformed — only a
    full run export with zero spans indicates broken instrumentation."""
    errors: list[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if doc.get("schema") != SCHEMA:
        errors.append(f"schema is {doc.get('schema')!r}, expected {SCHEMA!r}")
    events = doc.get("traceEvents")
    if not isinstance(events, list) or (not events and require_spans):
        return errors + ["traceEvents missing or empty"]
    thread_meta = 0
    span_events = 0
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph == "M":
            thread_meta += 1
            if ev.get("name") != "thread_name" or "name" not in ev.get(
                "args", {}
            ):
                errors.append(f"{where}: malformed thread_name metadata")
            continue
        if ph != "X":
            errors.append(f"{where}: ph={ph!r}, expected 'X' or 'M'")
            continue
        span_events += 1
        if not isinstance(ev.get("name"), str) or not ev.get("name"):
            errors.append(f"{where}: missing span name")
        for field in ("ts", "dur"):
            value = ev.get(field)
            if not isinstance(value, (int, float)) or value < 0:
                errors.append(f"{where}: {field}={value!r} not a number >= 0")
        for field in ("pid", "tid"):
            if not isinstance(ev.get(field), int):
                errors.append(f"{where}: {field} missing or not an int")
    if require_spans:
        if thread_meta == 0:
            errors.append("no thread_name metadata events")
        if span_events == 0:
            errors.append("no span ('X') events")
    return errors
