"""Per-window metric time-series: the run's metric history, queryable.

Before this module a window's metrics existed exactly once — in the dict
handed to the sinks — and vanished when the window closed. Post-mortems
leaned on grepping JSONL (if a JsonlSink happened to be wired) and live
questions ("is fps collapsing *right now*?") had no machine-readable
answer at all. The store keeps the answer in two places:

- **In memory**: a bounded, preallocated ring of per-window sample dicts,
  single-writer (the trainer's window close), snapshot-consistent for
  cross-thread readers (the HTTP endpoint, tests) in the style of
  ``trace.py``'s span rings — readers copy the slot list and discard the
  bounded window of slots a concurrent writer may have been overwriting,
  so no returned sample is torn.
- **On disk**: every sample (and every health event) appends one JSON
  line to ``<run_dir>/timeseries.jsonl``, so the run's full metric
  history survives the process — ``python -m asyncrl_tpu.obs doctor``
  replays it offline.

The JSONL grammar (one object per line, ``kind`` discriminated):

    {"kind": "meta",   "schema": "asyncrl-timeseries-v1", "t": ..,
     "run": {env_id, algo, backend, seed, platform, thresholds, ...}}
    {"kind": "sample", "t": .., "window": {env_steps, fps, loss, ...}}
    {"kind": "event",  "t": .., "event": {detector, component, ...}}

A reused run_dir appends (never truncates); each run opens with its own
meta line, and :func:`read_jsonl` returns the LAST such segment — the
doctor always judges the most recent run by that run's own thresholds.
Non-finite floats are encoded as "NaN"/"Infinity"/"-Infinity" strings on
disk (strict JSON for external tooling) and decoded back on read.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from typing import Any, Iterable

SCHEMA = "asyncrl-timeseries-v1"
DEFAULT_CAPACITY = 4096
FILENAME = "timeseries.jsonl"
# In-memory bound on health-event annotations (the JSONL keeps them all).
EVENTS_CAPACITY = 256


# Non-finite float <-> strict-JSON spelling. json.dumps would emit bare
# NaN/Infinity literals (its Python dialect), which RFC-compliant readers
# (jq, JS, Go — exactly the tooling a .jsonl exists for) reject; encode
# them as these strings on write and decode on read, so a diverging run's
# loss=NaN survives the round-trip AND the file stays valid JSON.
_NONFINITE = {"NaN": math.nan, "Infinity": math.inf, "-Infinity": -math.inf}


def _encode(value: Any) -> Any:
    if isinstance(value, float) and not math.isfinite(value):
        if math.isnan(value):
            return "NaN"
        return "Infinity" if value > 0 else "-Infinity"
    return value


def _decode(value: Any) -> Any:
    if isinstance(value, str) and value in _NONFINITE:
        return _NONFINITE[value]
    return value


def encode_tree(obj: Any) -> Any:
    """:func:`_encode` applied through nested dicts/lists (event ``data``
    payloads carry the offending values, e.g. grad_norm=inf)."""
    if isinstance(obj, dict):
        return {k: encode_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode_tree(v) for v in obj]
    return _encode(obj)


def decode_tree(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {k: decode_tree(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [decode_tree(v) for v in obj]
    return _decode(obj)


def _jsonable(value: Any) -> Any:
    """A JSON-serializable scalar for ``value``, or None to drop it.
    Window dicts occasionally carry numpy scalars (an aggregation that
    skipped the float() coercion) — ``.item()`` unwraps them; anything
    non-scalar is dropped rather than poisoning the whole line."""
    if isinstance(value, (bool, int, float, str)) or value is None:
        return value
    item = getattr(value, "item", None)
    if item is not None:
        try:
            return item()
        except (TypeError, ValueError):
            return None
    return None


class TimeSeriesStore:
    """One run's per-window sample ring + incremental JSONL persistence.

    Single-writer by contract: only the trainer's window-close thread
    calls :meth:`append`/:meth:`annotate`. Cross-thread readers (the obs
    HTTP server) use :meth:`snapshot`/:meth:`series`/:meth:`latest`,
    which tolerate the bounded copy-window tear exactly like
    ``trace.SpanRing.snapshot`` — the declared non-lock discipline.
    """

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        persist_path: str | None = None,
        meta: dict[str, Any] | None = None,
    ):
        if capacity < 2:
            raise ValueError(
                f"timeseries capacity must be >= 2, got {capacity}"
            )
        self.capacity = capacity
        self.persist_path = persist_path
        self.meta = dict(meta or {})
        # lint: thread-shared-ok(single-writer ring slots; snapshot discards the copy-window slots a concurrent append may touch)
        self._slots: list[dict[str, Any] | None] = [None] * capacity
        # lint: thread-shared-ok(GIL-atomic int; single-writer monotone counter, snapshot reads it before/after the copy)
        self.idx = 0
        # lint: thread-shared-ok(single-writer bounded list; readers take a slice under the GIL — events are append-only dicts, never mutated)
        self._events: list[dict[str, Any]] = []
        self._f = None
        if persist_path:
            parent = os.path.dirname(persist_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            # Line-buffered append: each window's sample is on disk the
            # moment it is written — a crash loses at most the line in
            # flight, never the run's history.
            self._f = open(persist_path, "a", buffering=1)
            self._write_line(
                {"kind": "meta", "schema": SCHEMA, "t": time.time(),
                 "pid": os.getpid(), "run": self.meta}
            )

    # ------------------------------------------------------------- writer

    def _write_line(self, row: dict[str, Any]) -> None:
        if self._f is None:
            return
        try:
            line = json.dumps(
                encode_tree(row), default=str, allow_nan=False
            )
        except (TypeError, ValueError) as e:
            # One unserializable row is dropped; the file stays alive.
            print(f"timeseries: row not serializable: {e}", file=sys.stderr)
            return
        try:
            self._f.write(line + "\n")
        except (OSError, ValueError) as e:
            # Best-effort persistence: a full disk (or a close() racing a
            # final append) must never take down the training loop; the
            # in-memory ring keeps serving the endpoint either way.
            print(f"timeseries: persist failed: {e}", file=sys.stderr)
            self._f = None

    def append(self, window: dict[str, Any]) -> dict[str, Any]:
        """Record one window sample (writer thread only). The stored dict
        is a sanitized copy stamped with ``t`` (unix) — the caller's dict
        is NOT retained, so later caller-side mutation cannot tear a
        reader's view."""
        sample = {"t": time.time()}
        for key, value in window.items():
            coerced = _jsonable(value)
            if coerced is not None:
                sample[key] = coerced
        self._slots[self.idx % self.capacity] = sample
        self.idx += 1
        self._write_line({"kind": "sample", "t": sample["t"],
                          "window": sample})
        return sample

    def annotate(self, event: dict[str, Any]) -> None:
        """Record one health-event annotation (writer thread only):
        bounded in memory, unbounded on disk."""
        row = dict(event)
        row.setdefault("t", time.time())
        self._events.append(row)
        del self._events[:-EVENTS_CAPACITY]
        self._write_line({"kind": "event", "t": row["t"], "event": row})

    @property
    def dropped(self) -> int:
        return max(0, self.idx - self.capacity)

    # ------------------------------------------------------------ readers

    def snapshot(self) -> list[dict[str, Any]]:
        """Oldest-to-newest copy of the retained samples, from ANY thread
        (the SpanRing discipline: the copy-window slot a concurrent
        append may be mid-store on is excluded, so no sample is torn)."""
        i0 = self.idx
        slots = list(self._slots)
        i1 = self.idx
        lo = max(0, i1 - self.capacity + 1)
        out = []
        for j in range(lo, i0):
            sample = slots[j % self.capacity]
            if sample is not None:
                out.append(sample)
        return out

    def latest(self) -> dict[str, Any] | None:
        """The newest sample (None before the first window closes)."""
        i = self.idx
        if i == 0:
            return None
        return self._slots[(i - 1) % self.capacity]

    def series(self, key: str, last_n: int = 240) -> list[list[float]]:
        """Recent ``[t, value]`` points for one metric key (samples that
        lack the key — or hold a non-finite value no chart can plot and
        no strict-JSON reader can parse — are skipped) — the
        ``/timeseries`` endpoint's shape."""
        points = []
        for sample in self.snapshot():
            value = sample.get(key)
            if (
                isinstance(value, (int, float))
                and not isinstance(value, bool)
                and math.isfinite(value)
            ):
                points.append([sample["t"], float(value)])
        return points[-last_n:]

    def events(self, last_n: int = 64) -> list[dict[str, Any]]:
        return list(self._events[-last_n:])

    def keys(self) -> list[str]:
        """Every metric key any retained sample carries (dashboards)."""
        out: set[str] = set()
        for sample in self.snapshot():
            out.update(sample)
        return sorted(out)

    def close(self) -> None:
        f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass


# ---------------------------------------------------------------- reading


def read_jsonl(path: str) -> dict[str, Any]:
    """Parse a persisted ``timeseries.jsonl`` into
    ``{"meta": .., "samples": [..], "events": [..]}`` (the doctor's
    input). Tolerates torn final lines (a crashed writer) and unknown
    kinds (forward compatibility). A reused run_dir appends one meta
    line per run SEGMENT; the returned view is the LAST segment — the
    run the doctor is being asked about — so an earlier run's samples
    are never replayed under a later run's thresholds (and recorded
    events always align with the samples' window indices)."""
    meta: dict[str, Any] = {}
    samples: list[dict[str, Any]] = []
    events: list[dict[str, Any]] = []
    started = False  # a meta AFTER data starts a new segment
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail of a crashed run — keep what parsed
            kind = row.get("kind")
            if kind == "meta":
                if started:
                    samples, events = [], []
                    started = False
                meta = row.get("run") or {}
            elif kind == "sample":
                window = row.get("window")
                if isinstance(window, dict):
                    started = True
                    samples.append(decode_tree(window))
            elif kind == "event":
                event = row.get("event")
                if isinstance(event, dict):
                    started = True
                    events.append(decode_tree(event))
    return {"meta": meta, "samples": samples, "events": events}


def series_of(
    samples: Iterable[dict[str, Any]], key: str
) -> list[float]:
    """The numeric values of ``key`` across ``samples`` (missing skipped)."""
    out = []
    for sample in samples:
        value = sample.get(key)
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            out.append(float(value))
    return out
