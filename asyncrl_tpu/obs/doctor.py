"""``obs doctor``: offline run-health diagnosis for a recorded run_dir.

Replays everything a run left behind — ``timeseries.jsonl`` (the metric
history), ``flightrec-*.json`` (crash forensics), ``trace-*.json`` (the
span export) — into one report:

1. **Detector timeline**: the health events recorded live, merged with an
   offline :func:`~asyncrl_tpu.obs.health.replay` of the same detector
   set over the samples (same thresholds, read back from the run's meta
   line) — so runs recorded before a detector existed still get judged
   by it, and a live monitor that died mid-run loses nothing.
2. **Learning timeline** (ISSUE 8): the learning-health trajectory —
   entropy, behaviour-vs-learner KL, V-trace clip saturation, value
   explained-variance, off-policy staleness percentiles, compile counts,
   memory watermarks — first/last/min/max per metric, plus every
   recorded compile event with its static-shape blame. The offline
   replay of what the introspection layer measured live.
3. **Serving timeline**: the serving-side story — cumulative gateway/
   fleet counters (shed, deadline shed, failover, canary promote/
   rollback) from the windows, and, when the run journaled requests
   (``requests.jsonl``), the deciding-stage census for every non-200,
   per-stage duration percentiles across all hops, and the worst
   journals' budget waterfalls inlined (the ``obs explain`` shape).
4. **Bottleneck attribution**: the stall-attribution table from the run's
   newest trace export (falling back to the newest flight dump's embedded
   trace) — the ``obs report`` analysis inlined.
5. **Regression verdict**: the run's best window throughput against the
   matching BENCH_HISTORY.json rows (preset- and platform-matched,
   newest row wins) with a tolerance fraction — "did this PR regress
   perf" as a command, not archaeology.

Exit code: 0 clean (or no baseline to compare against — absence of
evidence is reported, never treated as regression), 1 when the regression
verdict fires, 2 when the run_dir has no readable timeseries.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any

from asyncrl_tpu.obs import health, report, timeseries

# A run "regresses" when its best window fps falls below this fraction of
# the baseline row. Generous by default: shared/noisy hosts swing real
# throughput run to run (see perf_smoke.sh); tighten on quiet hardware.
DEFAULT_FPS_TOLERANCE = 0.5


def load_run(run_dir: str) -> dict[str, Any]:
    """{"meta", "samples", "events"} from ``<run_dir>/timeseries.jsonl``.
    Raises FileNotFoundError when the run recorded no timeseries."""
    path = os.path.join(run_dir, timeseries.FILENAME)
    return timeseries.read_jsonl(path)


def _infer_preset(meta: dict[str, Any]) -> str | None:
    """The preset whose (env_id, algo) matches the run's — how doctor
    joins a run_dir to BENCH_HISTORY rows without the run knowing its
    preset name. First declaration order wins on ties."""
    env_id, algo = meta.get("env_id"), meta.get("algo")
    if not env_id or not algo:
        return None
    from asyncrl_tpu.configs import presets

    for name, cfg in presets.PRESETS.items():
        if cfg.env_id == env_id and cfg.algo == algo:
            return name
    return None


def best_fps(samples: list[dict[str, Any]]) -> float:
    """The run's best window throughput — best-of-N, the same discipline
    every smoke harness uses against scheduler noise."""
    values = timeseries.series_of(samples, "fps")
    return max(values) if values else 0.0


def regression_verdict(
    meta: dict[str, Any],
    samples: list[dict[str, Any]],
    preset: str | None = None,
    tolerance: float = DEFAULT_FPS_TOLERANCE,
    history_path: str | None = None,
) -> dict[str, Any]:
    """Compare the run against its matching BENCH_HISTORY rows.

    verdict: "ok" | "regressed" | "no-baseline" (no matching row, or the
    run recorded no fps — reported, never conflated with regression).
    """
    from asyncrl_tpu.utils import bench_history

    preset = preset or _infer_preset(meta)
    run_fps = best_fps(samples)
    out: dict[str, Any] = {
        "verdict": "no-baseline",
        "preset": preset,
        "platform": meta.get("platform"),
        "run_fps": round(run_fps),
        "tolerance": tolerance,
        "baseline_fps": None,
        "baseline_ts": None,
    }
    if preset is None or run_fps <= 0:
        return out
    rows = [
        row for row in bench_history.load(history_path)
        if row.get("kind") == "throughput"
        and row.get("preset") == preset
        and (
            meta.get("platform") is None
            or row.get("platform") == meta.get("platform")
        )
        and isinstance(row.get("frames_per_sec"), (int, float))
    ]
    if not rows:
        return out
    baseline = rows[-1]  # newest matching row: the last known good
    out["baseline_fps"] = baseline["frames_per_sec"]
    out["baseline_ts"] = baseline.get("ts")
    out["verdict"] = (
        "ok" if run_fps >= tolerance * float(baseline["frames_per_sec"])
        else "regressed"
    )
    return out


def _latest_trace_doc(run_dir: str) -> tuple[dict[str, Any] | None, str | None]:
    """The newest analyzable trace document in the run_dir: a full
    ``trace-*.json`` export preferred, else the newest flight dump's
    embedded trace section."""
    traces = sorted(glob.glob(os.path.join(run_dir, "trace-*.json")))
    for path in reversed(traces):
        try:
            with open(path) as f:
                return json.load(f), path
        except (OSError, json.JSONDecodeError):
            continue
    dumps = sorted(glob.glob(os.path.join(run_dir, "flightrec-*.json")))
    for path in reversed(dumps):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if doc.get("trace"):
            return doc["trace"], path
    return None, None


# Learning-health keys the learning-timeline section summarizes, in
# display order (only keys the run actually recorded are shown).
LEARNING_KEYS = (
    "loss", "entropy", "kl", "target_kl", "rho_clip_frac", "c_clip_frac",
    "explained_variance", "staleness_p50", "staleness_p95",
    "staleness_max", "reuse_p50", "reuse_p95", "replay_fill_frac",
    "learner_stall_frac", "compiles", "infer_recompile",
    "learner_recompile",
    "mem_device_bytes_in_use", "mem_device_peak_bytes",
    "mem_host_rss_bytes", "mem_host_rss_peak_bytes",
)


def learning_timeline(
    samples: list[dict[str, Any]], events: list[dict[str, Any]]
) -> list[str]:
    """The learning-timeline section lines: metric trajectories
    (first/last/min/max over the run) + recorded compile events with
    their static-shape blame."""
    lines: list[str] = []
    for key in LEARNING_KEYS:
        values = timeseries.series_of(samples, key)
        if not values:
            continue
        lines.append(
            f"{key:<26} first {values[0]:>12.5g}  last {values[-1]:>12.5g}"
            f"  min {min(values):>12.5g}  max {max(values):>12.5g}"
        )
    if not lines:
        lines.append(
            "no learning-health metrics recorded (introspection was off, "
            "or the run predates it)"
        )
    compiles = [e for e in events if e.get("type") == "compile"]
    if compiles:
        lines.append(f"-- {len(compiles)} recorded compile event(s) --")
    for event in compiles:
        dt = event.get("compile_s")
        lines.append(
            f"compile #{event.get('seq', '?')} at {event.get('site', '?')}"
            + (f" ({1e3 * dt:.0f}ms)" if isinstance(dt, (int, float)) else "")
            + f": {event.get('blame', '?')}"
        )
    return lines


# Serving-side counters the serving-timeline section surfaces (window
# samples carry them cumulatively; only non-zero keys are shown).
SERVING_KEYS = (
    "gateway_requests", "gateway_errors", "gateway_shed",
    "gateway_deadline_shed", "gateway_stale_served",
    "gateway_fallback_served", "gateway_netfaults", "fleet_failovers",
    "fleet_ejections", "fleet_readmissions", "fleet_promotions",
    "fleet_rollbacks", "fleet_replica_restarts", "request_journals",
    "request_journals_persisted", "request_journals_capped",
)


def serving_timeline(
    run_dir: str, samples: list[dict[str, Any]]
) -> list[str]:
    """The serving-timeline section lines: shed/failover/canary counters
    from the windows, plus — when the run journaled requests — the
    deciding-stage census, per-stage duration percentiles, and the worst
    journals' budget waterfalls from ``requests.jsonl``."""
    from asyncrl_tpu.obs import requests as requests_mod

    lines: list[str] = []
    any_counter = False
    for key in SERVING_KEYS:
        values = timeseries.series_of(samples, key)
        if not values or max(values) <= 0:
            continue
        any_counter = True
        lines.append(f"{key:<28} last {values[-1]:>10.0f}")
    if not any_counter:
        lines.append("no serving traffic recorded in the timeseries")
    path = os.path.join(run_dir, requests_mod.FILENAME)
    if not os.path.exists(path):
        lines.append(
            "no requests.jsonl: request journaling was off "
            "(config.request_trace / ASYNCRL_REQUEST_TRACE)"
        )
        return lines
    docs = requests_mod.read_jsonl(path)["requests"]
    if not docs:
        lines.append("requests.jsonl holds no finished journals")
        return lines
    non200 = sum(1 for d in docs if int(d.get("status", 0)) != 200)
    lines.append(f"-- {len(docs)} journaled request(s), {non200} non-200 --")
    deciders: dict[str, int] = {}
    for d in docs:
        if int(d.get("status", 0)) != 200:
            key = str(d.get("decided_by") or "?")
            deciders[key] = deciders.get(key, 0) + 1
    for key in sorted(deciders, key=lambda k: -deciders[k]):
        lines.append(f"decided_by {key:<24} {deciders[key]:>6}")
    stage_durs: dict[str, list[float]] = {}
    for d in docs:
        for hop in d.get("hops", ()):
            stage_durs.setdefault(str(hop.get("stage", "?")), []).append(
                float(hop.get("dur_ms", 0.0))
            )
    if stage_durs:
        lines.append("per-stage dur_ms:            count       p50       "
                     "p95       max")
        for stage in sorted(stage_durs):
            vals = sorted(stage_durs[stage])
            p50 = vals[max(0, min(len(vals) - 1, int(0.50 * len(vals))))]
            p95 = vals[max(0, min(len(vals) - 1, int(0.95 * len(vals))))]
            lines.append(
                f"{stage:<26} {len(vals):>7}  {p50:>8.1f}  {p95:>8.1f}"
                f"  {vals[-1]:>8.1f}"
            )
    text, code = requests_mod.explain(run_dir, worst=3)
    if code == 0:
        lines.append("-- worst journals (obs explain --worst 3) --")
        lines.extend(text.splitlines())
    return lines


def _timeline(
    recorded: list[dict[str, Any]], replayed: list[health.HealthEvent]
) -> list[dict[str, Any]]:
    """Recorded + replayed events, deduplicated on (detector, window) —
    a live event and its offline re-derivation are the same fact."""
    out: list[dict[str, Any]] = []
    seen: set[tuple[str, int]] = set()
    for event in recorded:
        key = (event.get("detector", "?"), int(event.get("window_idx", -1)))
        if key not in seen:
            seen.add(key)
            out.append(dict(event, source="recorded"))
    for event in replayed:
        key = (event.detector, event.window_idx)
        if key not in seen:
            seen.add(key)
            out.append(dict(event.to_dict(), source="replayed"))
    out.sort(key=lambda e: (e.get("window_idx", 0), e.get("detector", "")))
    return out


def diagnose(
    run_dir: str,
    preset: str | None = None,
    tolerance: float = DEFAULT_FPS_TOLERANCE,
    history_path: str | None = None,
) -> tuple[str, int]:
    """(report text, exit code) for a recorded run_dir."""
    try:
        run = load_run(run_dir)
    except OSError as e:
        return f"obs doctor: {run_dir}: no readable timeseries — {e}", 2
    meta, samples, recorded = run["meta"], run["samples"], run["events"]
    if not samples:
        return (
            f"obs doctor: {run_dir}: timeseries holds no window samples "
            "(the run died before its first window closed)",
            2,
        )
    thresholds = health.Thresholds.from_meta(meta)
    replayed = health.replay(samples, thresholds=thresholds)
    # The event stream mixes detector firings and compile annotations
    # (both are kind=event lines): the detector timeline reads the
    # former, the learning timeline the latter.
    health_events = [e for e in recorded if "detector" in e]
    timeline = _timeline(health_events, replayed)

    lines: list[str] = []
    steps = timeseries.series_of(samples, "env_steps")
    lines.append(
        f"obs doctor: {run_dir}"
    )
    lines.append(
        f"run: env_id={meta.get('env_id')} algo={meta.get('algo')} "
        f"backend={meta.get('backend')} platform={meta.get('platform')} "
        f"windows={len(samples)} env_steps={int(steps[-1]) if steps else 0}"
    )
    lines.append("")
    lines.append(f"== detector timeline ({len(timeline)} event(s)) ==")
    if not timeline:
        lines.append("no health events: every detector stayed quiet")
    for event in timeline:
        lines.append(
            f"[window {event.get('window_idx', '?'):>4} | "
            f"steps {int(event.get('env_steps', 0) or 0):>10}] "
            f"{event.get('severity', '?'):<8} {event.get('detector', '?'):<20} "
            f"({event.get('component', '?')}, {event.get('source')}): "
            f"{event.get('message', '')}"
        )

    lines.append("")
    lines.append("== learning timeline ==")
    lines.extend(learning_timeline(samples, recorded))

    lines.append("")
    lines.append("== serving timeline ==")
    lines.extend(serving_timeline(run_dir, samples))

    lines.append("")
    lines.append("== bottleneck attribution ==")
    doc, trace_path = _latest_trace_doc(run_dir)
    if doc is None:
        lines.append(
            "no trace export or flight dump with a trace section in the "
            "run_dir (tracing was off, or the run never exported)"
        )
    else:
        analysis = report.analyze(doc)
        if analysis["waits"]:
            share, group, name, _ = analysis["waits"][0]
            from asyncrl_tpu.obs import spans as span_names

            cause = span_names.WAIT_CAUSES.get(name, "")
            lines.append(f"from {trace_path}:")
            lines.append(
                f"dominant stall: {name} ({100.0 * share:.1f}% of {group} "
                f"wall time)" + (f" — {cause}" if cause else "")
            )
        else:
            lines.append(
                f"from {trace_path}: no wait spans recorded — nothing "
                "in the pipeline blocked long enough to attribute"
            )

    lines.append("")
    lines.append("== regression verdict (vs BENCH_HISTORY) ==")
    verdict = regression_verdict(
        meta, samples, preset=preset, tolerance=tolerance,
        history_path=history_path,
    )
    if verdict["verdict"] == "no-baseline":
        lines.append(
            f"no baseline: preset={verdict['preset']} "
            f"platform={verdict['platform']} matched no throughput row "
            f"(run best fps {verdict['run_fps']:,})"
        )
    else:
        lines.append(
            f"preset={verdict['preset']} platform={verdict['platform']}: "
            f"run best fps {verdict['run_fps']:,} vs baseline "
            f"{verdict['baseline_fps']:,} ({verdict['baseline_ts']}), "
            f"tolerance {verdict['tolerance']:g}x -> {verdict['verdict'].upper()}"
        )

    code = 1 if verdict["verdict"] == "regressed" else 0
    lines.append("")
    lines.append(
        f"verdict: {'REGRESSED' if code else 'CLEAN'} "
        f"({len(timeline)} health event(s), "
        f"throughput {verdict['verdict']})"
    )
    return "\n".join(lines), code
