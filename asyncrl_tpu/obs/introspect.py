"""Training introspection: learning health and device behavior as telemetry.

Everything in ``obs/`` so far watches the *system* — fps, stalls, restarts,
latency. Nothing measured whether the *learning* is healthy or what the
*device* is doing. This module closes that gap with three host-side pieces
(the device-side half lives in the loss aux — ``ops/losses.py`` exports
behaviour-vs-learner KL, V-trace rho/c clip fractions, and value
explained-variance as loss metrics when ``config.introspect`` is on):

- :class:`StalenessWindow` — per-window off-policy staleness aggregation.
  Each consumed fragment carries its behaviour-params version (the
  ``ParamStore`` version stamped into ``Fragment.version``); the trainer
  feeds each fragment's lag-in-learner-updates here and drains
  ``staleness_p50/p95/max/mean`` at window close. IMPACT-style
  staleness-tolerant replay (PAPERS.md, arxiv 1912.00167) is only safe when
  off-policy-ness is *observed*, not assumed — this is the observation.

- :func:`instrument` — a thin wrapper around a jitted callable that counts
  (re)compilations with static-shape blame. Detection is a signature set
  over the argument shapes/dtypes (deterministic and testable: the counter
  trips exactly when an argument SHAPE changes — the same condition that
  keys jit's own cache), so the inference server's partial-batch recompile
  behavior (``rollout/inference_server.py``) is measurable for the first
  time. Each detected compile increments its registry counters (the shared
  ``compiles`` total plus site counters like ``infer_recompile``), observes
  the call's wall time into the ``compile_ms`` histogram (the compile-time
  vs run-time split: steady-state calls are covered by the existing
  ``learner.update``/``serve.dispatch`` spans, compile calls additionally
  get a ``<site>.compile`` span and the histogram), and pushes a structured
  event that the trainer's window close persists into ``timeseries.jsonl``
  as a ``kind=event`` annotation. The count is per-wrapper-lifetime: wrap
  ONCE next to where the jit cache lives (the trainer holds the jitted
  inference fn across supervised server rebuilds, so the counter never
  resets with the server).

- :func:`sample_memory` — per-window memory watermarks: device memory
  stats where the backend supports them (``Device.memory_stats()``;
  ``mem_device_bytes_in_use`` / ``mem_device_peak_bytes``), with a
  host-RSS fallback (``mem_host_rss_bytes`` from /proc/self/statm, plus a
  monotone ``mem_host_rss_peak_bytes`` watermark) — published as registry
  gauges so every window sink and ``/metrics`` carry them.

Arming: ``config.introspect`` (default on), with ``ASYNCRL_INTROSPECT``
winning when set — the no-code-change A/B knob, the ``ASYNCRL_TRACE``
precedence. ``scripts/introspect_smoke.sh`` is the on/off A/B gate
(identical losses, overhead within tolerance).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable

import numpy as np

from asyncrl_tpu.obs import registry, trace

ENV_VAR = "ASYNCRL_INTROSPECT"
_FALSEY = ("", "0", "false", "no")

# Bounded in-memory compile-event log (the timeseries JSONL keeps them all
# once drained; an undrained process — store off — caps here).
COMPILE_EVENTS_CAP = 256


def env_requests() -> bool | None:
    """What ASYNCRL_INTROSPECT asks for: None when unset (the config
    decides), else its truthiness — the obs.setup/trace precedence."""
    raw = os.environ.get(ENV_VAR)
    if raw is None:
        return None
    return raw.lower() not in _FALSEY


def enabled(config) -> bool:
    """Is introspection on for ``config``? Env wins when set."""
    env = env_requests()
    if env is not None:
        return env
    return bool(config.introspect)


# ------------------------------------------------------------- staleness


class StalenessWindow:
    """Per-window staleness-lag aggregation (lag in learner updates).

    Single-thread by contract: the trainer's learner-drain thread both
    observes (per consumed fragment) and drains (at window close) — the
    same thread, so no lock. Keys follow the window-metric convention:
    ``staleness_p50`` / ``staleness_p95`` / ``staleness_max`` /
    ``staleness_mean``; a window that consumed no fragments contributes
    no keys (absent, never a misleading 0).
    """

    def __init__(self) -> None:
        self._lags: list[float] = []

    def observe(self, lag_updates: float) -> None:
        self._lags.append(float(lag_updates))

    def drain(self) -> dict[str, float]:
        if not self._lags:
            return {}
        lags = np.asarray(self._lags, np.float64)
        self._lags = []
        return {
            "staleness_p50": float(np.percentile(lags, 50)),
            "staleness_p95": float(np.percentile(lags, 95)),
            "staleness_max": float(lags.max()),
            "staleness_mean": float(lags.mean()),
        }


# ------------------------------------------------------- compile tracking


class _CompileLog:
    """Process-wide bounded compile-event sink, drained on the trainer's
    window-close thread into the time-series store."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: deque[dict] = deque(
            maxlen=COMPILE_EVENTS_CAP
        )  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock

    def push(self, event: dict) -> None:
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(event)

    def drain(self) -> list[dict]:
        with self._lock:
            out = list(self._events)
            self._events.clear()
            return out

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0


_LOG = _CompileLog()


def drain_compile_events() -> list[dict]:
    """Pop every pending compile event (the window-close drain)."""
    return _LOG.drain()


def reset() -> None:
    """Drop pending compile events AND the host-RSS peak watermark (a
    fresh trainer's obs setup — a new agent must never persist a
    predecessor's compiles, nor report a peak its own run never
    reached, into its run_dir)."""
    global _RSS_PEAK
    _LOG.reset()
    _RSS_PEAK = 0.0


def _sig(obj: Any) -> Any:
    """A hashable (shape, dtype) signature of one argument pytree, without
    importing jax: containers recurse, array-likes reduce to their shape/
    dtype, everything else to its type. Flax ``struct.dataclass`` nodes
    (Rollout, LearnerState) walk their fields."""
    if isinstance(obj, (tuple, list)):
        return tuple(_sig(x) for x in obj)
    if isinstance(obj, dict):
        return tuple(sorted((k, _sig(v)) for k, v in obj.items()))
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return tuple(
            (f.name, _sig(getattr(obj, f.name)))
            for f in dataclasses.fields(obj)
        )
    shape = getattr(obj, "shape", None)
    if shape is not None:
        return ("arr", tuple(shape), str(getattr(obj, "dtype", "?")))
    return ("py", type(obj).__name__)


def _fmt_sig(sig: Any) -> str:
    """Compact human-readable rendering of a :func:`_sig` signature."""
    if isinstance(sig, tuple) and len(sig) == 3 and sig[0] == "arr":
        return f"{sig[2]}{list(sig[1])}"
    if isinstance(sig, tuple) and len(sig) == 2 and sig[0] == "py":
        return sig[1]
    if isinstance(sig, tuple):
        return "(" + ", ".join(_fmt_sig(s) for s in sig) + ")"
    return str(sig)


def _blame(prev: Any, new: Any) -> str:
    """Which argument's shape changed between the previous call and this
    compiling one — the static-shape blame line of a compile event."""
    if prev is None:
        return "first call"
    for (argnum, old), (_, cur) in zip(prev, new):
        if old != cur:
            return (
                f"arg{argnum} shape changed: "
                f"{_fmt_sig(old)} -> {_fmt_sig(cur)}"
            )
    if len(prev) != len(new):
        return f"arity changed: {len(prev)} -> {len(new)} args"
    return "signature changed (non-shape static argument)"


class InstrumentedFn:
    """Compile-counting wrapper for a jitted callable (see module doc).

    Thread-safe: any thread may call (actor threads share the per-thread
    inference fn). The signature check/registration runs under a tiny
    lock; the wrapped call itself never does — a compile must not
    serialize unrelated callers.
    """

    def __init__(
        self,
        fn: Callable,
        site: str,
        counters: Iterable[str] = ("compiles",),
        ignore_argnums: Iterable[int] = (),
    ):
        self._fn = fn
        self.site = site
        self._ignore = frozenset(ignore_argnums)
        # Counter NAMES, resolved at increment time: the wrapper is
        # typically constructed before ``obs.setup`` resets the registry
        # (the trainer builds learner/inference fns first), so holding
        # instrument objects here would strand the increments on orphans
        # the window drain never sees. Compiles are rare — the per-compile
        # registry lookup is free.
        self._counter_names = tuple(counters)
        self._lock = threading.Lock()
        self._seen: set[Any] = set()  # guarded-by: _lock
        self._prev: Any = None  # guarded-by: _lock
        # Written under _lock; GIL-atomic metrics-only reads (tests).
        self.compiles = 0  # guarded-by: _lock

    def _signature(self, args: tuple) -> tuple:
        return tuple(
            (i, _sig(arg))
            for i, arg in enumerate(args)
            if i not in self._ignore
        )

    def __call__(self, *args):
        sig = self._signature(args)
        with self._lock:
            known = sig in self._seen
            prev = self._prev
            self._prev = sig
            if not known:
                self._seen.add(sig)
                self.compiles += 1
                seq = self.compiles
        if known:
            return self._fn(*args)
        # New signature: count it, blame the changed shape, and time the
        # call — on a new shape the jit trace+compile happens inside this
        # dispatch, so its wall time IS (approximately) the compile cost.
        for name in self._counter_names:
            registry.counter(name).inc()
        t0 = time.perf_counter()
        with trace.span(f"{self.site}.compile"):
            out = self._fn(*args)
        dt = time.perf_counter() - t0
        registry.histogram("compile_ms").observe(1e3 * dt)
        _LOG.push({
            "type": "compile",
            "site": self.site,
            "seq": seq,
            "t": time.time(),
            "compile_s": round(dt, 6),
            "blame": _blame(prev, sig),
            "signature": _fmt_sig(sig),
        })
        return out


def instrument(
    fn: Callable,
    site: str,
    counters: Iterable[str] = ("compiles",),
    ignore_argnums: Iterable[int] = (),
) -> InstrumentedFn:
    """Wrap ``fn`` (typically a ``jax.jit`` product) in compile counting.

    ``site`` names the entry point in events/spans (``"infer"``,
    ``"learner.update"``); ``counters`` are the registry counters each
    detected compile increments (always include the shared ``"compiles"``
    total so the recompile-storm detector sees every site); and
    ``ignore_argnums`` skips arguments whose pytrees are large and whose
    shapes cannot change (the params/state argument) — keeping the
    per-call signature walk to the small, shape-varying arguments.
    """
    return InstrumentedFn(
        fn, site, counters=counters, ignore_argnums=ignore_argnums
    )


# ------------------------------------------------------ memory watermarks

# Monotone host-RSS high-water mark across the run. Window-close-thread
# only (sample_memory's single caller is PipelineObs.observe_window).
_RSS_PEAK = 0.0


def _host_rss_bytes() -> float | None:
    """Current resident set size. /proc/self/statm (Linux); falls back to
    ru_maxrss (which is a PEAK — still a usable watermark) elsewhere."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys

        # This fallback only runs where /proc is absent — i.e. almost
        # always macOS, where ru_maxrss is BYTES; Linux reports KiB.
        # ru_maxrss is a peak, not current RSS — still a usable watermark.
        raw = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        return raw if sys.platform == "darwin" else raw * 1024.0
    except (ImportError, OSError, ValueError):
        return None


def device_memory_stats() -> dict[str, float]:
    """Backend device-memory stats, when the platform exposes them (TPU/GPU
    runtimes do; CPU returns nothing). Lazy + failure-tolerant like
    ``obs._platform``: introspection must never break on a backend that
    can't answer."""
    out: dict[str, float] = {}
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    # lint: broad-except-ok(metadata enrichment only; a backend without memory_stats or a broken jax install must not break the window close)
    except Exception:
        return out
    if not stats:
        return out
    for src, dst in (
        ("bytes_in_use", "mem_device_bytes_in_use"),
        ("peak_bytes_in_use", "mem_device_peak_bytes"),
        ("bytes_limit", "mem_device_bytes_limit"),
    ):
        value = stats.get(src)
        if isinstance(value, (int, float)):
            out[dst] = float(value)
    return out


def sample_memory() -> dict[str, float]:
    """Sample the memory watermarks into registry gauges (and return them).

    Called once per metrics window from ``PipelineObs.observe_window``
    (the window-close thread) when introspection is on — the gauges then
    ride the shared registry drain into every sink, ``/metrics``, and
    ``timeseries.jsonl``.
    """
    global _RSS_PEAK
    out = device_memory_stats()
    rss = _host_rss_bytes()
    if rss is not None:
        out["mem_host_rss_bytes"] = rss
        if rss > _RSS_PEAK:
            _RSS_PEAK = rss
        out["mem_host_rss_peak_bytes"] = _RSS_PEAK
    for key, value in out.items():
        registry.gauge(key).set(value)
    return out
