"""Per-thread span ring buffers: the tracing core of ``asyncrl_tpu.obs``.

Design constraints (ISSUE 5 tentpole):

- **Lock-free hot path.** Each thread owns one :class:`SpanRing`; recording
  a span is three list stores and an integer increment by the owning
  thread, no lock. Cross-thread readers (export, flight recorder) take a
  :meth:`SpanRing.snapshot`, which copies the slot lists under the GIL and
  discards the bounded window of slots a concurrent writer may have been
  overwriting mid-copy — a snapshot can lose a few newest/oldest spans,
  never produce a torn one that claims to be valid.
- **Preallocated, drop-oldest.** Rings are fixed capacity, allocated once
  per thread; overflow overwrites the oldest span and counts into
  ``dropped`` (exported as the ``trace_dropped_spans`` window counter).
- **Near-zero cost when disabled.** ``trace.span(name)`` with no armed
  tracer returns one shared no-op context manager — no allocation, no
  ring registration, one module-global read and a ``None`` check (the
  same compile-away discipline as ``utils.faults.site``).

Arming mirrors ``utils.faults``: explicit :func:`configure` (the trainer's
``config.trace``), or lazily from ``ASYNCRL_TRACE=1`` on first use
(``ASYNCRL_TRACE_RING`` overrides the per-thread capacity).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from asyncrl_tpu.obs import spans as span_names

ENV_VAR = "ASYNCRL_TRACE"
ENV_RING = "ASYNCRL_TRACE_RING"
DEFAULT_CAPACITY = 4096
_FALSEY = ("", "0", "false", "no")


class _NoopSpan:
    """The disabled-mode context manager: one shared instance, no state."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    """One in-flight span: records [enter, exit) into the owning ring."""

    __slots__ = ("_ring", "_name", "_t0")

    def __init__(self, ring: "SpanRing", name: str):
        self._ring = ring
        self._name = name
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._ring.record(self._name, self._t0, time.perf_counter())
        return False


class SpanRing:
    """One thread's preallocated span storage (single-writer).

    ``idx`` counts spans ever recorded; slot ``idx % capacity`` is the
    write target, so overflow is drop-oldest by construction and
    ``dropped == max(0, idx - capacity)``. Only the owning thread writes;
    snapshot readers tolerate the bounded copy-window tear (see module
    docstring) — this is the declared non-lock discipline.
    """

    __slots__ = ("capacity", "thread_name", "group", "names", "starts",
                 "ends", "metas", "idx", "thread")

    def __init__(self, capacity: int, thread_name: str, group: str,
                 thread=None):
        self.capacity = capacity
        self.thread_name = thread_name
        # The owning Thread object (None for legacy/test construction):
        # the tracer's bounded dead-ring retention needs liveness, and
        # names alone cannot provide it.
        self.thread = thread
        # lint: thread-shared-ok(written only via tag_thread on the owning thread; readers see old or new group, both coherent)
        self.group = group
        # lint: thread-shared-ok(single-writer ring slots; snapshot discards the copy-window slots a concurrent record may touch)
        self.names: list[str | None] = [None] * capacity
        # lint: thread-shared-ok(single-writer ring slots, same snapshot discipline as names)
        self.starts: list[float] = [0.0] * capacity
        # lint: thread-shared-ok(single-writer ring slots, same snapshot discipline as names)
        self.ends: list[float] = [0.0] * capacity
        # Optional per-span metadata (request trace ids). None for the
        # overwhelming majority of spans — snapshots emit the legacy
        # (name, start, end) 3-tuple unless a meta dict is present.
        # lint: thread-shared-ok(single-writer ring slots, same snapshot discipline as names)
        self.metas: list[dict | None] = [None] * capacity
        # lint: thread-shared-ok(GIL-atomic int; single-writer monotone counter, snapshot reads it before/after the copy)
        self.idx = 0

    def record(self, name: str, start: float, end: float,
               meta: dict | None = None) -> None:
        i = self.idx % self.capacity
        self.names[i] = name
        self.starts[i] = start
        self.ends[i] = end
        self.metas[i] = meta
        self.idx += 1

    @property
    def dropped(self) -> int:
        return max(0, self.idx - self.capacity)

    def snapshot(self) -> dict[str, Any]:
        """A consistent copy of this ring, taken from ANY thread.

        Logical indices valid after the copy: ``[i1 - capacity + 1, i0)``
        where ``i0``/``i1`` are ``idx`` before/after the list copies —
        slots the writer may have overwritten (or been mid-store on)
        during the copy are excluded, so no returned span is torn.
        """
        i0 = self.idx
        names = list(self.names)
        starts = list(self.starts)
        ends = list(self.ends)
        metas = list(self.metas)
        i1 = self.idx
        lo = max(0, i1 - self.capacity + 1)
        out = []
        for j in range(lo, i0):
            slot = j % self.capacity
            name = names[slot]
            if name is not None:
                if metas[slot] is None:
                    out.append((name, starts[slot], ends[slot]))
                else:
                    out.append(
                        (name, starts[slot], ends[slot], metas[slot])
                    )
        return {
            "thread": self.thread_name,
            "group": self.group,
            "recorded": i0,
            "dropped": max(0, i0 - self.capacity),
            "spans": out,
        }


class Tracer:
    """The armed span collector: a registry of per-thread rings plus the
    perf_counter->unix clock anchor every exporter needs."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 2:
            raise ValueError(f"trace ring capacity must be >= 2, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        # A LIST, deliberately not a dict keyed on thread.ident: CPython
        # recycles idents, and a restarted actor's fresh ring must never
        # evict its crashed predecessor's spans from the export/dumps.
        self._rings: list[SpanRing] = []  # guarded-by: _lock
        self.pruned = 0  # guarded-by: _lock
        self._local = threading.local()
        # Clock anchor: exported timestamps are
        # (span.start - anchor_perf) in µs, wall-anchored by anchor_unix.
        self.anchor_perf = time.perf_counter()
        self.anchor_unix = time.time()

    # Bound on RETAINED rings: dead threads' rings stay for forensics (a
    # crashed actor's spans must survive into the export/dumps), but
    # thread-per-request servers (the gateway's HTTP handlers) would
    # otherwise grow the registry one ring per connection, forever —
    # unbounded RSS and O(total requests) window closes. Past the cap,
    # the OLDEST dead rings are pruned (live rings are never touched);
    # the cap is far above any bounded fleet's thread count, so actor
    # forensics keep the old retention semantics in practice.
    RING_RETENTION = 128

    def _ring(self) -> SpanRing:
        ring = getattr(self._local, "span_ring", None)
        if ring is None:
            thread = threading.current_thread()
            ring = SpanRing(
                self.capacity, thread.name,
                span_names.thread_group(thread.name),
                thread=thread,
            )
            self._local.span_ring = ring
            with self._lock:
                self._rings.append(ring)
                if len(self._rings) > self.RING_RETENTION:
                    excess = len(self._rings) - self.RING_RETENTION
                    dead = [
                        r for r in self._rings
                        if r.thread is not None and not r.thread.is_alive()
                    ][:excess]
                    for old in dead:
                        self._rings.remove(old)
                    self.pruned += len(dead)
        return ring

    def span(self, name: str) -> _Span:
        return _Span(self._ring(), name)

    def tag_thread(self, group: str) -> None:
        """Override the calling thread's group (the trainer tags its drain
        thread ``learner`` — it usually runs on MainThread)."""
        self._ring().group = group

    def snapshots(self) -> list[dict[str, Any]]:
        """One snapshot per registered thread ring (any thread may call);
        dead threads' rings are retained (up to ``RING_RETENTION``, then
        oldest-dead-first pruning) — a crashed actor's spans stay in the
        export and the flight dumps, while thread-per-request handlers
        cannot grow the registry without bound."""
        with self._lock:
            rings = list(self._rings)
        return [r.snapshot() for r in rings]

    def stats(self) -> dict[str, int]:
        """Window-metric view: spans recorded and dropped, all threads."""
        with self._lock:
            rings = list(self._rings)
            pruned = self.pruned
        return {
            "trace_spans": sum(r.idx for r in rings),
            "trace_dropped_spans": sum(r.dropped for r in rings),
            "trace_threads": len(rings),
            "trace_rings_pruned": pruned,
        }


_ARM_LOCK = threading.Lock()
# Double-checked lazy arming (the faults.py pattern): writes happen under
# _ARM_LOCK; the hot-path read in active() is deliberately lock-free.
# lint: thread-shared-ok(single reference swap under _ARM_LOCK; lock-free readers see None or a fully-constructed Tracer)
_TRACER: Tracer | None = None
# lint: thread-shared-ok(GIL-atomic bool latch, written under _ARM_LOCK; a racing reader at worst re-enters the locked init once)
_ENV_CHECKED = False


def configure(enabled: bool = True, capacity: int | None = None) -> Tracer | None:
    """Arm (or disarm) process-wide tracing explicitly. Returns the armed
    tracer (None when disabling). Re-arming replaces the tracer — old
    rings stop receiving spans at each thread's next ``span()`` call."""
    global _TRACER, _ENV_CHECKED
    with _ARM_LOCK:
        if enabled:
            # `is not None`, not truthiness: capacity=0 must reach the
            # Tracer's >= 2 validation and fail fast, never silently
            # substitute the default.
            _TRACER = Tracer(
                capacity if capacity is not None else _env_capacity()
            )
        else:
            _TRACER = None
        _ENV_CHECKED = True
        return _TRACER


def _env_capacity() -> int:
    raw = os.environ.get(ENV_RING, "")
    return int(raw) if raw else DEFAULT_CAPACITY


def active() -> Tracer | None:
    """The armed tracer, lazily initialized from ``ASYNCRL_TRACE`` on
    first call (so plain scripts get tracing without code changes)."""
    global _TRACER, _ENV_CHECKED
    if not _ENV_CHECKED:
        with _ARM_LOCK:
            if not _ENV_CHECKED:
                if os.environ.get(ENV_VAR, "").lower() not in _FALSEY:
                    _TRACER = Tracer(_env_capacity())
                _ENV_CHECKED = True
    return _TRACER


def enabled() -> bool:
    return active() is not None


def env_requests() -> bool | None:
    """What ASYNCRL_TRACE asks for: None when unset (the config decides),
    else its truthiness — the precedence obs.setup implements."""
    raw = os.environ.get(ENV_VAR)
    if raw is None:
        return None
    return raw.lower() not in _FALSEY


def span(name: str):
    """THE instrumentation entry point: a context manager recording one
    span into the calling thread's ring — or the shared no-op when
    tracing is disabled (no allocation, no ring registration)."""
    tracer = active()
    if tracer is None:
        return _NOOP
    return tracer.span(name)


def record_span(name: str, start: float, end: float,
                meta: dict | None = None) -> None:
    """Record one already-timed span (perf_counter stamps) into the
    calling thread's ring — the request-journal replay path, which emits
    trace-id-stamped ``request.*`` spans at journal close. No-op when
    tracing is disabled."""
    tracer = active()
    if tracer is not None:
        tracer._ring().record(name, start, end, meta)


def tag_thread(group: str) -> None:
    """Tag the calling thread's group in the armed tracer (no-op when
    disabled)."""
    tracer = active()
    if tracer is not None:
        tracer.tag_thread(group)


def stats() -> dict[str, int]:
    """Window-metric counters ({} when disabled)."""
    tracer = active()
    return tracer.stats() if tracer is not None else {}


def snapshots() -> list[dict[str, Any]]:
    """All thread-ring snapshots ([] when disabled)."""
    tracer = active()
    return tracer.snapshots() if tracer is not None else []
