"""Request-scoped hop journals: deadline-budget accounting on the wire.

Aggregate counters and window percentiles (``serve_latency_ms`` and the
gateway histograms) answer "how is the fleet doing?" — they cannot answer
"where did THIS request's 50 ms go, and why was it shed?". This module
gives every gateway request a **hop journal**: a wire-propagated trace id
(``X-Trace-Id``, generated at the client, echoed in responses) plus an
ordered record of each stage the request crossed — rate-bucket verdict,
tenant admission wait, per-replica failover attempts with their budget
shares, canary assignment, the scheduler's admission/batch-fill/dispatch
phases — each hop stamped with its **budget remaining at entry** so the
rendered timeline reads as a waterfall ("admitted at 46 ms remaining,
batch-fill held 9 ms, shed by slo-gate").

Journal invariants (what the tests gate):

- **Level-0 hops partition the request.** The gateway records contiguous
  level-0 segments (each new segment starts where the previous ended) and
  :meth:`RequestJournal.finish` closes the tail, so level-0 durations sum
  to the journal latency *exactly* (float slack only). Nested detail —
  fleet attempts (level 1), scheduler phases (level 2) — overlaps its
  parent and is excluded from the sum.
- **One journal, N attempts.** The journal is bound to the gateway
  handler thread (:func:`bind`); the fleet router and scheduler pick it
  up via :func:`current` — retries and failover hops append to the same
  journal, never fork a new one.
- **Every non-200 names its deciding stage.** ``finish(status, stage)``
  records the stage that produced the verdict (``gateway.rate_bucket``,
  ``serve.slo_gate``, ``serve.dispatch_grace``, ...) as ``decided_by``.
- **Off is off.** With no armed store, :func:`begin` returns None and
  every hook degrades to a thread-local read + ``None`` check — no
  allocation, no registry keys, no file handles (the ``trace.py``
  compile-away discipline).

Persistence mirrors ``timeseries.jsonl``: slow/shed journals append one
JSON line each to ``<run_dir>/requests.jsonl`` (line-buffered, non-finite
floats as strings, torn-tail-tolerant reader, last run segment wins),
sampled by ``request_sample_slow_ms`` and budget-bounded by
``request_journal_cap``. Finished journals also emit their hops as
trace-id-stamped spans into the per-thread rings (Perfetto export) and
feed a bounded in-memory ring the flight recorder embeds into
netfault/replica/gateway dumps.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any

from asyncrl_tpu.obs import registry, trace
from asyncrl_tpu.obs.timeseries import decode_tree, encode_tree

SCHEMA = "asyncrl-requests-v1"
FILENAME = "requests.jsonl"
ENV_VAR = "ASYNCRL_REQUEST_TRACE"
_FALSEY = ("", "0", "false", "no")

DEFAULT_JOURNAL_CAP = 512
DEFAULT_SLOW_MS = 0.0  # <= 0: every finished journal is persist-eligible
# In-memory bound on finished journals (flight-recorder embeds, explain
# on a live store); the JSONL keeps the sampled history.
RECENT_CAPACITY = 32
# Spans emitted per journal are bounded by the hop count, which is itself
# bounded by the fleet size (attempts) + fixed stage vocabulary.

# Journal stage vocabulary (level-0 gateway segments + nested detail).
STAGE_PARSE = "gateway.parse"
STAGE_ADMIT = "gateway.admit"
STAGE_SERVE = "gateway.serve"
STAGE_RESPOND = "gateway.respond"
STAGE_ATTEMPT = "fleet.attempt"
STAGE_CORE_ADMIT = "serve.admit"
STAGE_BATCH_FILL = "serve.batch_fill"
STAGE_DISPATCH = "serve.dispatch"

# Deciding stages (``decided_by`` vocabulary) for non-200 verdicts.
DECIDED_PARSE = "gateway.parse"
DECIDED_NETFAULT = "gateway.netfault"
DECIDED_DRAIN = "gateway.drain"
DECIDED_DEADLINE = "gateway.deadline"
DECIDED_RATE_BUCKET = "gateway.rate_bucket"
DECIDED_TENANT_GATE = "gateway.tenant_gate"
DECIDED_DEGRADE = "gateway.degrade"
DECIDED_BACKEND_ERROR = "gateway.backend_error"
DECIDED_SLO_GATE = "serve.slo_gate"
DECIDED_DISPATCH_GRACE = "serve.dispatch_grace"
DECIDED_FLEET = "fleet.exhausted"
DECIDED_SERVED = "served"


def new_trace_id() -> str:
    """A fresh 16-hex-char wire trace id (client-side generation)."""
    return os.urandom(8).hex()


class RequestJournal:
    """One request's hop record, rooted at the gateway handler.

    Single-writer by contract: hops are appended by the handler thread
    that owns the request (the scheduler's serve thread hands its stamps
    back through the ``_Request`` event handshake, so even core-phase
    hops are recorded handler-side). Absolute times are
    ``time.perf_counter()`` — the span rings' clock — so emitted spans
    land on the exporter's anchor.
    """

    __slots__ = ("trace_id", "endpoint", "tenant", "policy", "deadline_ms",
                 "t0", "hops", "status", "decided_by", "cause",
                 "latency_ms", "_cursor", "_done")

    def __init__(self, trace_id: str, endpoint: str = "",
                 deadline_ms: float = 0.0, tenant: str = "",
                 policy: str = ""):  # budget: deadline_ms
        self.trace_id = trace_id
        self.endpoint = endpoint
        self.tenant = tenant
        self.policy = policy
        self.deadline_ms = float(deadline_ms)
        self.t0 = time.perf_counter()
        # lint: race-ok(single-writer by contract: only the owning handler thread appends hops; the scheduler's serve thread hands stamps back through the _Request event handshake, never touching the journal)
        self.hops: list[dict[str, Any]] = []
        self.status = 0
        self.decided_by = ""
        self.cause = ""
        self.latency_ms = 0.0
        # end of the last level-0 segment
        # lint: race-ok(single-writer by contract: advanced only by the owning handler thread's level-0 segments)
        self._cursor = self.t0
        self._done = False

    def annotate(self, tenant: str = "", policy: str = "",
                 deadline_ms: float = 0.0) -> None:  # budget: deadline_ms
        """Backfill request identity once the gateway has parsed it. A
        method rather than bare attribute assignment at the call site:
        the journal local is untyped there, and a cross-module attribute
        write on an untyped receiver is exactly what the race pass's
        unique-name attribution would pin to the wrong class."""
        if tenant:
            self.tenant = tenant
        if policy:
            self.policy = policy
        if deadline_ms:
            self.deadline_ms = float(deadline_ms)

    def budget_remaining_ms(self, at: float | None = None) -> float:
        """Wire budget left at ``at`` (perf stamp; now when omitted) —
        negative once the deadline is overdrawn, deliberately unclamped
        so the waterfall shows the overdraft."""
        t = time.perf_counter() if at is None else at
        return self.deadline_ms - 1e3 * (t - self.t0)

    def hop(self, stage: str, t_enter: float, t_exit: float,
            level: int = 1, cause: str = "", **extra: Any) -> None:
        """Append one hop. ``level`` 0 = gateway segment (sums to the
        latency), 1 = fleet attempt, 2 = scheduler phase (nested detail,
        excluded from the sum)."""
        row: dict[str, Any] = {
            "stage": stage,
            "t_ms": 1e3 * (t_enter - self.t0),
            "dur_ms": max(0.0, 1e3 * (t_exit - t_enter)),
            "budget_ms": self.budget_remaining_ms(t_enter),
            "level": level,
            "_t0": t_enter,
            "_t1": t_exit,
        }
        if cause:
            row["cause"] = cause
        for key, value in extra.items():
            row[key] = value
        self.hops.append(row)
        if level == 0:
            self._cursor = t_exit

    def seg(self, stage: str, cause: str = "", **extra: Any) -> None:
        """Close the current level-0 segment at now, named ``stage``.
        Segments are contiguous by construction (each starts at the
        previous segment's end), which is what makes the level-0
        durations sum to the journal latency."""
        now = time.perf_counter()
        self.hop(stage, self._cursor, now, level=0, cause=cause, **extra)

    def finish(self, status: int, stage: str, cause: str = "") -> None:
        """Close the journal: the tail becomes a final level-0 segment
        named ``stage`` (the verdict's deciding stage for non-200s), and
        the finished journal is committed to the armed store (span
        emission, sampling, persistence). Idempotent — only the first
        verdict sticks."""
        if self._done:
            return
        self._done = True
        self.seg(stage, cause=cause)
        self.status = int(status)
        self.decided_by = stage if status != 200 else DECIDED_SERVED
        self.cause = cause
        self.latency_ms = 1e3 * (self._cursor - self.t0)
        store = active()
        if store is not None:
            store.commit(self)

    def to_doc(self) -> dict[str, Any]:
        """The persisted/embedded shape (relative-ms hops, no perf
        stamps)."""
        hops = []
        for row in self.hops:
            hops.append({k: v for k, v in row.items()
                         if not k.startswith("_")})
        return {
            "trace_id": self.trace_id,
            "endpoint": self.endpoint,
            "tenant": self.tenant,
            "policy": self.policy,
            "deadline_ms": self.deadline_ms,
            "status": self.status,
            "decided_by": self.decided_by,
            "cause": self.cause,
            "latency_ms": self.latency_ms,
            "hops": hops,
        }


class JournalStore:
    """The armed journal collector: bounded recent ring + sampled JSONL.

    ``commit`` is called from gateway handler threads (plural), so the
    ring/counters/file mutate under ``_lock`` — journals finish at
    request rate, not window rate, and the critical section is a deque
    append plus one buffered write.
    """

    def __init__(self, run_dir: str | None = None,
                 cap: int = DEFAULT_JOURNAL_CAP,
                 slow_ms: float = DEFAULT_SLOW_MS,
                 meta: dict[str, Any] | None = None):
        self.cap = max(0, int(cap))
        self.slow_ms = float(slow_ms)
        self.persist_path = (
            os.path.join(run_dir, FILENAME) if run_dir else None
        )
        self._lock = threading.Lock()
        self._recent: deque[dict[str, Any]] = deque(
            maxlen=RECENT_CAPACITY
        )  # guarded-by: _lock
        self._persisted = 0  # guarded-by: _lock
        self._f = None  # guarded-by: _lock
        self._c_finished = registry.counter("request_journals")
        self._c_persisted = registry.counter("request_journals_persisted")
        self._c_capped = registry.counter("request_journals_capped")
        if self.persist_path:
            parent = os.path.dirname(self.persist_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            f = open(self.persist_path, "a", buffering=1)
            with self._lock:
                self._f = f
                self._write_line(
                    {"kind": "meta", "schema": SCHEMA, "t": time.time(),
                     "pid": os.getpid(), "run": dict(meta or {})}
                )

    def _write_line(self, row: dict[str, Any]) -> None:  # holds: _lock
        if self._f is None:
            return
        try:
            line = json.dumps(encode_tree(row), default=str,
                              allow_nan=False)
        except (TypeError, ValueError) as e:
            print(f"requests: row not serializable: {e}", file=sys.stderr)
            return
        try:
            self._f.write(line + "\n")
        except (OSError, ValueError) as e:
            # Best-effort persistence (the timeseries discipline): a full
            # disk must never fail a request on the serving path.
            print(f"requests: persist failed: {e}", file=sys.stderr)
            self._f = None

    def _emit_spans(self, journal: RequestJournal) -> None:
        """Replay the hops as ``request.*`` spans into the calling
        thread's ring, trace-id-stamped, in pre-order (enter asc, exit
        desc) so the per-thread nesting invariant the report relies on
        holds."""
        tracer = trace.active()
        if tracer is None:
            return
        meta = {"trace_id": journal.trace_id}
        ordered = sorted(journal.hops,
                         key=lambda h: (h["_t0"], -h["_t1"]))
        for row in ordered:
            trace.record_span(f"request.{row['stage']}", row["_t0"],
                              row["_t1"], meta=meta)

    def commit(self, journal: RequestJournal) -> None:
        """Accept one finished journal (any handler thread)."""
        self._emit_spans(journal)
        doc = journal.to_doc()
        self._c_finished.inc()
        persist = (
            journal.status != 200
            or self.slow_ms <= 0.0
            or journal.latency_ms >= self.slow_ms
        )
        with self._lock:
            self._recent.append(doc)
            if persist and self._f is not None:
                if self._persisted < self.cap:
                    self._persisted += 1
                    self._write_line(
                        {"kind": "request", "t": time.time(),
                         "request": doc}
                    )
                    self._c_persisted.inc()
                else:
                    # Budget-bounded: past the cap the JSONL stays fixed
                    # size; the recent ring and counters keep moving.
                    self._c_capped.inc()

    def recent(self, n: int = RECENT_CAPACITY) -> list[dict[str, Any]]:
        """Newest-last copies of the most recent finished journals."""
        with self._lock:
            docs = list(self._recent)
        return docs[-n:]

    def close(self) -> None:
        with self._lock:
            f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass


# ------------------------------------------------------------ module state

_ARM_LOCK = threading.Lock()
# Double-checked lazy arming (the trace.py pattern): writes under
# _ARM_LOCK; the hot-path read in active() is deliberately lock-free.
# lint: thread-shared-ok(single reference swap under _ARM_LOCK; lock-free readers see None or a fully-constructed JournalStore)
_STORE: JournalStore | None = None
# lint: thread-shared-ok(GIL-atomic bool latch, written under _ARM_LOCK; a racing reader at worst re-enters the locked init once)
_ENV_CHECKED = False
_LOCAL = threading.local()


def arm(run_dir: str | None = None, cap: int = DEFAULT_JOURNAL_CAP,
        slow_ms: float = DEFAULT_SLOW_MS,
        meta: dict[str, Any] | None = None) -> JournalStore:
    """Arm process-wide request journaling (the trainer's
    ``config.request_trace``). Re-arming replaces — and closes — the
    previous store."""
    global _STORE, _ENV_CHECKED
    # Construct (and open the JSONL) OUTSIDE the lock: file I/O under
    # _ARM_LOCK would stall every hot-path active() reader racing the
    # first lazy init.
    store = JournalStore(run_dir=run_dir, cap=cap, slow_ms=slow_ms,
                         meta=meta)
    with _ARM_LOCK:
        old, _STORE = _STORE, store
        _ENV_CHECKED = True
    if old is not None:
        old.close()
    return store


def disarm() -> None:
    global _STORE, _ENV_CHECKED
    with _ARM_LOCK:
        old, _STORE = _STORE, None
        _ENV_CHECKED = True
    if old is not None:
        old.close()


def active() -> JournalStore | None:
    """The armed store, lazily initialized from ``ASYNCRL_REQUEST_TRACE``
    on first call (plain scripts get journaling without code changes; an
    env-armed store has no run_dir, so it keeps the recent ring and
    metrics but persists nothing)."""
    global _STORE, _ENV_CHECKED
    if not _ENV_CHECKED:
        # Construct outside _ARM_LOCK (no blocking I/O under the lock);
        # a racing loser closes its store and defers to the winner's.
        want = os.environ.get(ENV_VAR, "").lower() not in _FALSEY
        store = JournalStore() if want else None
        published = False
        with _ARM_LOCK:
            if not _ENV_CHECKED:
                _STORE = store
                _ENV_CHECKED = True
                published = True
        if store is not None and not published:
            store.close()
    return _STORE


def env_requests() -> bool | None:
    """What ASYNCRL_REQUEST_TRACE asks for: None when unset (the config
    decides), else its truthiness — the precedence obs.setup implements."""
    raw = os.environ.get(ENV_VAR)
    if raw is None:
        return None
    return raw.lower() not in _FALSEY


def begin(trace_id: str, endpoint: str = "", deadline_ms: float = 0.0,
          tenant: str = "", policy: str = "") -> RequestJournal | None:  # budget: deadline_ms
    """Open a journal for one request (None when journaling is off — the
    single branch every gateway hook keys on). Generates a trace id when
    the client did not send one."""
    if active() is None:
        return None
    return RequestJournal(trace_id or new_trace_id(), endpoint=endpoint,
                          deadline_ms=deadline_ms, tenant=tenant,
                          policy=policy)


class _Bind:
    """Context manager binding a journal to the calling thread, so the
    fleet router and scheduler (same thread, deeper frames) can append
    hops via :func:`current` without signature plumbing."""

    __slots__ = ("_journal", "_prev")

    def __init__(self, journal: RequestJournal | None):
        self._journal = journal
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_LOCAL, "journal", None)
        _LOCAL.journal = self._journal
        return self._journal

    def __exit__(self, *exc):
        _LOCAL.journal = self._prev
        return False


def bind(journal: RequestJournal | None) -> _Bind:
    return _Bind(journal)


def current() -> RequestJournal | None:
    """The journal bound to the calling thread (None off the request
    path, or when journaling is off)."""
    return getattr(_LOCAL, "journal", None)


def current_trace_id() -> str | None:
    """The bound journal's trace id (histogram exemplar stamping)."""
    journal = current()
    return journal.trace_id if journal is not None else None


def recent(n: int = RECENT_CAPACITY) -> list[dict[str, Any]]:
    """Most recent finished journal docs ([] when disarmed) — the flight
    recorder's embed source."""
    store = active()
    return store.recent(n) if store is not None else []


# ---------------------------------------------------------------- reading


def read_jsonl(path: str) -> dict[str, Any]:
    """Parse a persisted ``requests.jsonl`` into ``{"meta": ..,
    "requests": [..]}`` — torn-tail-tolerant, last run segment wins (the
    ``timeseries.read_jsonl`` contract)."""
    meta: dict[str, Any] = {}
    requests: list[dict[str, Any]] = []
    started = False  # a meta AFTER data starts a new segment
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail of a crashed run — keep what parsed
            kind = row.get("kind")
            if kind == "meta":
                if started:
                    requests = []
                    started = False
                meta = row.get("run") or {}
            elif kind == "request":
                doc = row.get("request")
                if isinstance(doc, dict):
                    started = True
                    requests.append(decode_tree(doc))
    return {"meta": meta, "requests": requests}


# -------------------------------------------------------------- rendering


def level0_sum_ms(doc: dict[str, Any]) -> float:
    """Sum of the level-0 segment durations — equals ``latency_ms`` up to
    float slack (the invariant the smoke gates)."""
    return sum(
        float(h.get("dur_ms", 0.0))
        for h in doc.get("hops", ())
        if int(h.get("level", 0)) == 0
    )


def render_waterfall(doc: dict[str, Any]) -> list[str]:
    """One journal as a budget waterfall (the ``obs explain`` shape)."""
    status = int(doc.get("status", 0))
    head = (
        f"trace {doc.get('trace_id', '?')}  {doc.get('endpoint', '?')}"
        f"  tenant={doc.get('tenant') or '-'}"
        f"  status={status}"
        f"  decided_by={doc.get('decided_by') or '-'}"
    )
    cause = doc.get("cause")
    if cause:
        head += f"  cause={cause}"
    lines = [head]
    lines.append(
        f"  deadline {float(doc.get('deadline_ms', 0.0)):.1f} ms"
        f" · latency {float(doc.get('latency_ms', 0.0)):.1f} ms"
        f" · level-0 sum {level0_sum_ms(doc):.1f} ms"
    )
    lines.append("      t+ms    budget_ms  stage")
    known = {"stage", "t_ms", "dur_ms", "budget_ms", "level", "cause"}
    for hop in doc.get("hops", ()):
        level = int(hop.get("level", 0))
        indent = "  " * level
        extras = " ".join(
            f"{k}={hop[k]}" for k in sorted(hop) if k not in known
        )
        tail = f"  [{hop['cause']}]" if hop.get("cause") else ""
        if extras:
            tail += f"  {extras}"
        lines.append(
            f"  {float(hop.get('t_ms', 0.0)):8.1f} {float(hop.get('budget_ms', 0.0)):10.1f}"
            f"  {indent}{hop.get('stage', '?')}"
            f"  {float(hop.get('dur_ms', 0.0)):.1f} ms{tail}"
        )
    return lines


def explain(run_dir: str, trace_id: str | None = None,
            worst: int = 0) -> tuple[str, int]:
    """Render hop timelines from a run's ``requests.jsonl``: one journal
    by trace id, or the ``--worst N`` set (non-200 verdicts first, then
    by latency). Returns ``(text, exit_code)`` — 2 when the file or the
    trace id is missing (the doctor's "cannot judge" convention)."""
    path = os.path.join(run_dir, FILENAME)
    if not os.path.exists(path):
        return f"explain: no {FILENAME} under {run_dir}", 2
    docs = read_jsonl(path)["requests"]
    if not docs:
        return f"explain: {FILENAME} has no finished journals", 2
    if trace_id:
        picked = [d for d in docs if d.get("trace_id") == trace_id]
        if not picked:
            return (
                f"explain: trace {trace_id} not found "
                f"({len(docs)} journal(s) in the segment)", 2,
            )
    else:
        n = max(1, worst)
        picked = sorted(
            docs,
            key=lambda d: (int(d.get("status", 0)) != 200,
                           float(d.get("latency_ms", 0.0))),
            reverse=True,
        )[:n]
    lines: list[str] = []
    for doc in picked:
        lines.extend(render_waterfall(doc))
        lines.append("")
    return "\n".join(lines).rstrip(), 0
