"""Trainer: the user-facing training-loop owner, name-parity with the
reference's ``Trainer`` (BASELINE.json:5; SURVEY.md §3.1).

``Trainer.train()`` drives ``Learner.update`` and drains device-resident
metrics to the host every ``log_every`` update CALLS (each call fuses
``updates_per_call`` learner updates) — the hot loop never blocks on host
sync between drains. ``Trainer.evaluate()`` runs greedy episodes fully on
device (SURVEY.md §3.5).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from asyncrl_tpu.envs import registry
from asyncrl_tpu.learn.learner import (
    Learner,
    TrainState,
    validate_train_target,
)
from asyncrl_tpu.models.networks import build_model, is_recurrent, reset_core
from asyncrl_tpu.ops.normalize import normalizing_apply
from asyncrl_tpu.parallel.mesh import make_mesh
from asyncrl_tpu.utils.config import Config, default_eval_max_steps


def make_eval_rollout(config, env, model, num_episodes: int, max_steps: int):
    """Build ``eval_rollout(params, obs_stats, key) -> [num_episodes]``:
    one fully-on-device greedy rollout returning per-episode returns
    (SURVEY.md §3.5). Shared by ``Trainer.evaluate`` and the population
    trainer's per-member ranking (``jax.vmap`` over the params axis —
    api/population.py)."""
    from asyncrl_tpu.ops import distributions

    apply_fn = model.apply
    dist = distributions.for_config(config, env.spec)
    recurrent = is_recurrent(model)

    def eval_rollout(params, obs_stats, key):
        # Greedy eval must see the same normalized observations the
        # policy trained on (ops/normalize.py; identity when None).
        napply = normalizing_apply(apply_fn, obs_stats)
        init_keys = jax.random.split(key, num_episodes + 1)
        env_state = jax.vmap(env.init)(init_keys[:-1])
        obs = jax.vmap(env.observe)(env_state)
        step_key = init_keys[-1]
        core = model.initial_core(num_episodes) if recurrent else None

        def body(carry, _):
            env_state, obs, ret, alive, k, core = carry
            if recurrent:
                dist_params, _, core = napply(params, obs, core)
            else:
                dist_params, _ = napply(params, obs)
            actions = dist.mode(dist_params)
            k, sub = jax.random.split(k)
            step_keys = jax.random.split(sub, num_episodes)
            env_state, ts = jax.vmap(env.step)(env_state, actions, step_keys)
            if recurrent:
                core = reset_core(core, ts.done)
            ret = ret + ts.reward * alive
            alive = alive * (1.0 - ts.done.astype(jnp.float32))
            return (env_state, ts.obs, ret, alive, k, core), None

        zeros = jnp.zeros((num_episodes,), jnp.float32)
        (_, _, ret, _, _, _), _ = jax.lax.scan(
            body,
            (env_state, obs, zeros, zeros + 1.0, step_key, core),
            None,
            length=max_steps,
        )
        return ret

    return eval_rollout


class Trainer:
    """Owns env, model, mesh, learner, and the training loop.

    Checkpointing (SURVEY.md §5.4): with ``config.checkpoint_dir`` set, the
    full TrainState + env-steps counter is saved there every
    ``config.checkpoint_every`` updates (orbax, async), plus once when
    ``train()`` exits — by any path. On construction, an explicit
    ``restore=path`` loads initial state from that path read-only; otherwise
    an existing checkpoint under ``config.checkpoint_dir`` auto-resumes
    bit-exact.
    """

    def __init__(
        self, config: Config, env=None, model=None, mesh=None, restore=None
    ):
        # Resolve the ASYNCRL_INTROSPECT override once (env wins over
        # config.introspect, the ASYNCRL_TRACE precedence): the jitted
        # loss aux reads the RESOLVED flag at trace time, never the env.
        from asyncrl_tpu.obs import introspect

        if introspect.enabled(config) != config.introspect:
            config = config.replace(introspect=introspect.enabled(config))
        self.config = config
        self.env = (
            env if env is not None else registry.make(config.env_id, config)
        )
        self.model = (
            model if model is not None else build_model(config, self.env.spec)
        )
        self.mesh = (
            mesh
            if mesh is not None
            else make_mesh(config.mesh_shape, config.mesh_axes)
        )
        self.learner = Learner(config, self.env, self.model, self.mesh)
        self.state: TrainState = self.learner.init_state(config.seed)
        self.env_steps = 0
        self._eval_fns: dict[tuple[int, int], Callable] = {}

        from asyncrl_tpu.utils import checkpoint

        self._ckpt, self.state, self.env_steps = checkpoint.setup(
            config, restore, self.state
        )
        self.checkpointer = self._ckpt.checkpointer

    def save_checkpoint(self) -> None:
        """Save the current TrainState now (async; see ``Checkpointer``)."""
        self._ckpt.save_now(self.state, self.env_steps)

    def close(self) -> None:
        """Flush pending async checkpoint saves and release resources."""
        self._ckpt.close()

    # ------------------------------------------------------------------ train

    def train(
        self,
        total_env_steps: int | None = None,
        callback: Callable[[dict[str, Any]], None] | None = None,
    ) -> list[dict[str, Any]]:
        """Run updates until ``total_env_steps`` env frames consumed.

        Returns the list of drained metric dicts (one per ``log_every``
        update calls; a call fuses ``updates_per_call`` updates), each
        including ``env_steps``, ``fps``, and ``episode_return`` (mean over
        episodes completed in the window).
        """
        cfg = self.config
        target = total_env_steps or cfg.total_env_steps
        validate_train_target(cfg, target)
        steps_per_update = cfg.batch_steps_per_update * cfg.updates_per_call
        history: list[dict[str, Any]] = []

        pending: list[dict[str, jax.Array]] = []
        window_start = time.perf_counter()
        window_steps = 0
        calls = calls_at_eval = 0

        try:
            while self.env_steps < target:
                self.state, metrics = self.learner.update(self.state)
                self.env_steps += steps_per_update
                window_steps += steps_per_update
                calls += 1
                pending.append(metrics)
                self._ckpt.after_update(self.state, self.env_steps)

                if len(pending) >= cfg.log_every or self.env_steps >= target:
                    drained = jax.device_get(pending)
                    pending = []
                    elapsed = time.perf_counter() - window_start
                    window_start = time.perf_counter()

                    # Metric leaves are scalars (updates_per_call=1) or [K]
                    # stacks (fused multi-update calls): np handles both.
                    agg = {
                        k: float(np.mean([np.mean(m[k]) for m in drained]))
                        for k in drained[0]
                        if not k.startswith("episode_")
                    }
                    ep_count = float(
                        np.sum([np.sum(m["episode_count"]) for m in drained])
                    )
                    agg["episode_count"] = ep_count
                    agg["episode_return"] = float(
                        np.sum(
                            [np.sum(m["episode_return_sum"]) for m in drained]
                        )
                        / max(ep_count, 1.0)
                    )
                    agg["episode_length"] = float(
                        np.sum(
                            [np.sum(m["episode_length_sum"]) for m in drained]
                        )
                        / max(ep_count, 1.0)
                    )
                    agg["env_steps"] = self.env_steps
                    agg["fps"] = window_steps / max(elapsed, 1e-9)
                    window_steps = 0
                    # In-training greedy eval on the log boundary (so the
                    # eval never lands mid-window and its wall time never
                    # pollutes a window's fps).
                    if (
                        cfg.eval_every > 0
                        and calls - calls_at_eval >= cfg.eval_every
                    ):
                        calls_at_eval = calls
                        agg["eval_return"] = self.evaluate(
                            num_episodes=cfg.eval_episodes
                        )
                        self._ckpt.maybe_save_best(
                            self.state, self.env_steps, agg["eval_return"]
                        )
                        window_start = time.perf_counter()
                    history.append(agg)
                    if callback:
                        callback(agg)
        finally:
            # A crash must not lose progress: save whatever state we have
            # (even with periodic saves disabled) and flush async writes.
            self._ckpt.finalize(self.state, self.env_steps)
        return history

    # ----------------------------------------------------------------- eval

    def evaluate(
        self,
        num_episodes: int = 32,
        max_steps: int | None = None,
        seed: int = 1234,
        return_episodes: bool = False,
    ):
        """Mean greedy-policy episode return over ``num_episodes`` fresh envs,
        fully on device (one jitted scan). ``return_episodes=True`` returns
        the per-episode return vector instead of the mean (same single
        batched rollout either way)."""
        # Default horizon: contain the longest builtin episode (shared
        # helper; pass a smaller value explicitly for quick checks).
        if max_steps is None:
            max_steps = default_eval_max_steps(self.config)
        cache_key = (num_episodes, max_steps)
        if cache_key not in self._eval_fns:
            self._eval_fns[cache_key] = jax.jit(
                make_eval_rollout(
                    self.config, self.env, self.model, num_episodes, max_steps
                )
            )
        returns = self._eval_fns[cache_key](
            self.state.params, self.state.obs_stats, jax.random.PRNGKey(seed)
        )
        if return_episodes:
            import numpy as np

            return np.asarray(returns)
        return float(jnp.mean(returns))
