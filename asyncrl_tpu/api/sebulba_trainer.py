"""SebulbaTrainer: host actor threads + device learner, pipelined.

``backend="sebulba"`` is the framework's answer to the reference's default
architecture — per-thread actors feeding a learner through a queue
(BASELINE.json:5; SURVEY.md §3.1) — for envs that cannot live in HBM (C++
engines, gymnasium suites). Actors produce ``Rollout`` fragments on the host;
the learner thread transfers them batch-sharded to the mesh and steps the
``RolloutLearner``; weights publish back through a ``ParamStore`` every
``actor_staleness`` updates. The bounded queue is the pipelining element:
actors run ahead of the learner by up to ``queue_capacity`` fragments, and
V-trace (algo="impala") corrects the resulting off-policyness exactly as in
the reference (SURVEY.md §7.3).

With ``config.overlap_h2d`` (default on) the fragment data itself moves
zero-copy: actors write into leased staging-slab rows (rollout/staging.py),
the drain transfers whole slabs double-buffered against the learner's
compute, and per-window pipeline metrics (h2d_wait_s, h2d_bytes,
learner_stall_frac, slab_reuse_waits) make the overlap measurable — see
docs/ARCHITECTURE.md "Data path & transfer overlap".
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time
from collections import deque
from typing import Any, Callable

import jax
import numpy as np

from asyncrl_tpu import obs
from asyncrl_tpu.obs import flightrec, introspect
from asyncrl_tpu.obs import registry as obs_registry
from asyncrl_tpu.obs import spans as span_names
from asyncrl_tpu.obs import trace
from asyncrl_tpu.learn.learner import (
    validate_ppo_geometry,
    validate_train_target,
)
from asyncrl_tpu.learn import replay as replay_lib
from asyncrl_tpu.learn.rollout_learner import (
    LearnerState,
    RolloutLearner,
    rollout_sharding,
)
from asyncrl_tpu.models.networks import build_model, is_recurrent, reset_core
from asyncrl_tpu.ops import distributions
from asyncrl_tpu.ops.normalize import normalizing_apply
from asyncrl_tpu.parallel.mesh import TIME_AXIS, dp_size, make_mesh
from asyncrl_tpu.rollout.sebulba import (
    ActorThread,
    Fragment,
    FragmentSequenceChecker,
    ParamStore,
    make_host_pool,
    make_inference_fn,
)
from asyncrl_tpu.runtime import durability
from asyncrl_tpu.utils import faults
from asyncrl_tpu.utils.config import Config, default_eval_max_steps


def _stack_fragments(rollouts):
    """K host fragments -> one [K, T, B, ...] stack for the fused-dispatch
    learner (updates_per_call > 1). K=1 fast path: the single fragment
    passes through AS-IS — no stack, no copy (the K=1 learner expects the
    plain [T, B, ...] layout anyway, and a redundant ``np.stack`` here
    would tax every update of the default configuration). Legacy path
    only; the staging ring (config.overlap_h2d) never stacks at all."""
    if len(rollouts) == 1:
        return rollouts[0]
    return jax.tree.map(lambda *xs: np.stack(xs), *rollouts)


class SebulbaTrainer:
    """Owns host actor threads, the param store, and the device learner."""

    def __init__(
        self, config: Config, spec=None, model=None, mesh=None, restore=None
    ):
        self.config = config
        # Chaos layer (utils/faults.py): config-armed unless the operator's
        # ASYNCRL_FAULTS is set (env wins — it is the no-code-change knob).
        # An empty fault_spec DISARMS, so constructing a fresh agent never
        # inherits a previous agent's armed sites in the same process.
        # Armed BEFORE the body so the constructor's own checkpoint restore
        # and probe pool run under the spec'd sites; disarmed again if
        # construction fails — a half-built trainer must not leave its
        # faults armed for whatever runs next in the process.
        armed = not os.environ.get(faults.ENV_VAR)
        if armed:
            faults.arm(config.fault_spec)
        try:
            self._init(config, spec, model, mesh, restore)
        except BaseException:
            if armed:
                faults.disarm()
            raise

    def _init(self, config, spec, model, mesh, restore):
        # Resolve the ASYNCRL_INTROSPECT override ONCE (env wins over
        # config.introspect, the ASYNCRL_TRACE precedence) so every
        # downstream consumer — the jitted loss aux, the learner's compile
        # instrumentation, the staleness tracker — reads the same resolved
        # flag instead of re-consulting the environment.
        if introspect.enabled(config) != config.introspect:
            config = config.replace(introspect=introspect.enabled(config))
            self.config = config
        # Device replay ring (learn/replay.py): ASYNCRL_REPLAY wins over
        # config.replay_slabs when set — resolved ONCE here, like
        # ASYNCRL_INTROSPECT, so the jitted IMPACT update and the ring
        # construction below read the same resolved depth and never
        # re-consult the environment.
        env_replay = os.environ.get("ASYNCRL_REPLAY", "")
        if env_replay and int(env_replay) != config.replay_slabs:
            config = config.replace(replay_slabs=int(env_replay))
            self.config = config
        if config.num_envs % config.actor_threads:
            raise ValueError(
                f"num_envs={config.num_envs} not divisible by "
                f"actor_threads={config.actor_threads}"
            )
        self._envs_per_actor = config.num_envs // config.actor_threads

        # Spec comes from a probe pool (host envs are authoritative here).
        probe = make_host_pool(config, 1, seed=config.seed)
        self.spec = spec if spec is not None else _pool_spec(probe, config)
        _close(probe)

        self.model = (
            model if model is not None else build_model(config, self.spec)
        )
        self.mesh = (
            mesh
            if mesh is not None
            else make_mesh(config.mesh_shape, config.mesh_axes)
        )

        # Eager geometry validation, mirroring the Anakin Learner: fail at
        # construction, not with a cryptic sharding error mid-train after
        # actor threads have already started.
        dp = dp_size(self.mesh)
        if self._envs_per_actor % dp:
            raise ValueError(
                f"num_envs/actor_threads={self._envs_per_actor} not "
                f"divisible by dp={dp}"
            )
        # On a time-sharded mesh each (dp, sp) shard shuffles its
        # (unroll/sp)-step slice of the per-actor fragment, so the
        # divisibility check runs on that local geometry.
        sp = (
            self.mesh.shape[TIME_AXIS]
            if TIME_AXIS in self.mesh.axis_names
            else 1
        )
        if config.unroll_len % sp:
            # RolloutLearner re-raises this, but it must come BEFORE the
            # minibatch check: a floored unroll_len//sp there would report
            # a wrong-geometry error for what is really sp-indivisibility.
            raise ValueError(
                f"unroll_len={config.unroll_len} not divisible by the "
                f"time-shard axis sp={sp}"
            )
        validate_ppo_geometry(
            config, self._envs_per_actor // dp, "per-device",
            unroll=config.unroll_len // sp,
            recurrent=is_recurrent(self.model),
        )
        self.learner = RolloutLearner(config, self.spec, self.model, self.mesh)
        self.state: LearnerState = self.learner.init_state(config.seed)
        self.env_steps = 0

        # Checkpoint/resume (SURVEY.md §5.4): learner-side state only — host
        # env states are transient by design (actors restart from fresh envs
        # on resume, exactly as after a §5.3 actor restart).
        from asyncrl_tpu.utils import checkpoint

        self._ckpt, self.state, self.env_steps = checkpoint.setup(
            config, restore, self.state
        )
        self.checkpointer = self._ckpt.checkpointer

        self._inference_fn = make_inference_fn(self.model, self.spec, config)
        if config.introspect:
            # Compile accounting on the inference entry point
            # (obs/introspect.py): wrapped ONCE here — not per server —
            # because the jit cache lives in this function object and
            # survives supervised server rebuilds; the counter must match
            # its lifetime. ``infer_recompile`` makes the shared server's
            # partial-batch recompiles (deadline flushes change the batch
            # shape) measurable next to ``infer_coalesce_batch``. The
            # params argument's shapes never change and is skipped.
            self._inference_fn = introspect.instrument(
                self._inference_fn, "infer",
                counters=("compiles", "infer_recompile"),
                ignore_argnums=(0,),
            )
        # Per-window off-policy staleness aggregation (obs/introspect.py):
        # fed one lag per consumed fragment, drained at window close.
        self._staleness = (
            introspect.StalenessWindow() if config.introspect else None
        )
        self._initial_core = (
            self.model.initial_core if is_recurrent(self.model) else None
        )
        self._store = ParamStore(self._published(self.state), self.env_steps)
        cap = config.queue_capacity or 2 * config.actor_threads
        self._queue: "queue.Queue[Fragment]" = queue.Queue(maxsize=cap)
        # Elastic runtime (asyncrl_tpu/runtime/elastic.py): resolved ONCE
        # (ASYNCRL_ELASTIC wins over config.elastic, the ASYNCRL_SERVE
        # precedence) and validated eagerly — the in-flight ring swap does
        # not compose with fused multi-fragment slabs, and the legacy
        # InferenceServer's client set is fixed-shape.
        self._elastic_on = self._use_elastic()
        if self._elastic_on:
            if config.updates_per_call > 1:
                raise ValueError(
                    "elastic=True requires updates_per_call=1: a fused "
                    "[K>1] slab interrupted by a ring swap would strand "
                    "its partial batch"
                )
            if config.inference_server and not self._use_serve_core():
                raise ValueError(
                    "elastic=True requires the serve core for the shared "
                    "server (serve=True / ASYNCRL_SERVE=1): the legacy "
                    "InferenceServer's client set is fixed-shape"
                )
            emin, emax = self._elastic_bounds()
            if not emin <= config.actor_threads <= emax:
                raise ValueError(
                    f"actor_threads={config.actor_threads} outside the "
                    f"elastic bounds [{emin}, {emax}]"
                )
        else:
            registry = faults.active()
            if registry is not None and registry.has_kind("scale"):
                raise ValueError(
                    "fault spec arms a 'scale' site but the elastic "
                    "runtime is off (elastic=True / ASYNCRL_ELASTIC=1): "
                    "scripted scale requests would accumulate with no "
                    "controller to drain them"
                )
        # Zero-copy staging ring (rollout/staging.py): actors write
        # fragments straight into preallocated [K, T, B, ...] slabs and
        # the drain transfers whole slabs, double-buffered against the
        # learner's compute. config.overlap_h2d=False keeps the legacy
        # copy-and-stack path (A/B-compared by scripts/perf_smoke.sh).
        # Under elasticity the ring sits behind a RingSwapHolder so a
        # fleet-scale event can install a right-sized ring while in-flight
        # leases finish on the old one.
        self._staging = None
        self._staging_template = None
        self._staging_rows = max(config.updates_per_call, 1)
        if config.overlap_h2d:
            from asyncrl_tpu.rollout import staging

            template = staging.fragment_template(
                config, self.spec, self.model, self._envs_per_actor
            )
            self._staging_template = template
            K = self._staging_rows
            ring = staging.StagingRing(
                template,
                rows_per_slab=K,
                num_slabs=(
                    config.staging_slabs
                    or staging.auto_num_slabs(cap, config.actor_threads, K)
                ),
            )
            self._staging = (
                staging.RingSwapHolder(ring) if self._elastic_on else ring
            )
        # IMPACT-style device replay (learn/replay.py; ROADMAP item 3):
        # the last replay_slabs consumed fragments stay resident in
        # device memory, re-fed to the learner between fresh slabs so
        # the duty cycle stops tracking actor throughput. replay off
        # constructs NOTHING (the elastic/introspect off-is-bit-identical
        # discipline). Fragment geometry is invariant under elastic
        # scaling (fleet size changes, per-actor env count does not), so
        # the ring composes with the elastic runtime as-is.
        self._replay = None
        self._reuse_window = None
        self._replay_rng = None
        self._stall_history = None
        if config.replay_slabs > 0:
            from asyncrl_tpu.rollout import staging

            # ONE source of slab geometry: reuse the staging ring's
            # template when the overlap path already derived it.
            replay_template = (
                self._staging_template
                if self._staging_template is not None
                else staging.fragment_template(
                    config, self.spec, self.model, self._envs_per_actor
                )
            )
            self._replay = replay_lib.DeviceReplayRing(
                replay_template,
                rollout_sharding(self.mesh, replay_template, stacked=True),
                rows=config.replay_slabs,
            )
            self._reuse_window = replay_lib.ReuseWindow()
            # Replay-row selection is seed-deterministic (ties among
            # equally-reused rows break by this stream), decorrelated
            # from the actor seed ladder.
            self._replay_rng = np.random.default_rng(config.seed * 9973 + 13)
            # Trailing stall fractions for the learner_stall_trend key
            # (this window minus the trailing mean: the operator-facing
            # "is replay actually closing the duty-cycle gap" signal).
            self._stall_history = deque(maxlen=8)
        # HBM rollout hand-off (rollout/device_queue.py): the staging
        # ledger one tier down — bounds device-resident fragments
        # between H2D and the consuming update, and (with the replay
        # ring) enables the zero-copy ref publish. "auto" resolves on
        # the backend: fragments live in HBM only on a real accelerator;
        # on CPU the device array aliases host memory and host staging
        # already owns the hand-off, so the off path constructs NOTHING.
        dq = config.device_queue
        if dq == "auto":
            dq = "on" if jax.default_backend() == "tpu" else "off"
            config = config.replace(device_queue=dq)
            self.config = config
        if dq not in ("on", "off"):
            raise ValueError(
                f"unknown device_queue {config.device_queue!r}; "
                "expected auto|on|off"
            )
        self._device_queue = None
        if dq == "on":
            from asyncrl_tpu.rollout import device_queue as devq_lib

            self._device_queue = devq_lib.DeviceRolloutQueue(
                self.learner.put_rollout,
                slots=config.device_queue_slots,
            )
        # Replay adoption (publish ref=True) hands the learner the SAME
        # device pytree on replayed passes, so it is only sound when the
        # update does not donate its fragment argument.
        self._replay_ref = (
            self._device_queue is not None and not config.donate_buffers
        )
        # Observability (asyncrl_tpu/obs/): arms span tracing + the
        # flight recorder per config.trace (ASYNCRL_TRACE wins), resets
        # the counters/histograms registry, and mounts the run-health
        # layer (time-series store + detectors + optional /metrics
        # endpoint per config.obs_http_port); the window aggregation
        # (observe_window) and close()/shutdown() drive the handle.
        self._obs = obs.setup(config)
        # The elastic controller itself (policy) + the save → reconfigure
        # → restore barrier (safety). Both None when elasticity is off —
        # the off path constructs NOTHING elastic, the bit-identity
        # contract of scripts/elastic_smoke.sh.
        self._elastic = None
        self._elastic_barrier = None
        if self._elastic_on:
            from asyncrl_tpu.obs import health as health_mod
            from asyncrl_tpu.runtime import elastic as elastic_mod

            monitor = self._obs.monitor
            blame_fn = None
            if monitor is not None:

                def blame_fn():
                    # Runs AFTER observe_window advanced the monitor's
                    # close timestamp — pass the closed window's duration
                    # or the span horizon collapses to the 1s clamp.
                    stage, _ = monitor.bottleneck(
                        elapsed=monitor.last_window_s
                    )
                    return health_mod.blame_component(stage)

            self._elastic = elastic_mod.ElasticController(
                min_actors=self._elastic_bounds()[0],
                max_actors=self._elastic_bounds()[1],
                cooldown_windows=config.elastic_cooldown_windows,
                up_stall_frac=config.elastic_up_stall_frac,
                up_shed_rate=config.elastic_up_shed_rate,
                down_backpressure=config.elastic_down_backpressure,
                down_admission=config.elastic_down_admission,
                # The replay inversion: high ring fill + low stall means
                # sample reuse is covering the learner's duty cycle, so
                # the fleet is oversized — armed only when the ring
                # exists (0 keeps the signal out of every replay-off
                # identity A/B, the elastic_smoke discipline).
                down_replay_fill=(
                    elastic_mod.DOWN_REPLAY_FILL
                    if config.replay_slabs > 0
                    else 0.0
                ),
                blame_fn=blame_fn,
            )
            self._elastic_barrier = elastic_mod.ReconfigureBarrier(self._ckpt)
        # Durable runs (asyncrl_tpu/runtime/durability.py): the drain
        # grace and resume flag resolve ONCE (env wins — the ASYNCRL_SERVE
        # precedence), and a preempt-kind fault spec is refused when the
        # drain is disabled: its scripted SIGTERM would hit a process with
        # no handler and kill it undrained — the one outcome the spec
        # exists to test against.
        self._drain_grace = durability.drain_grace(config)
        self._resume_on = durability.resume_enabled(config)
        registry = faults.active()
        if (
            registry is not None
            and registry.has_kind("preempt")
            and self._drain_grace <= 0
        ):
            raise ValueError(
                "fault spec arms a 'preempt' site but the preemption "
                "drain is disabled (drain_grace_s=0 / "
                "ASYNCRL_DRAIN_GRACE_S=0): the scripted SIGTERM would "
                "kill the run undrained instead of testing the drain"
            )
        # External gateway (asyncrl_tpu/serve/gateway.py): the wire
        # frontier over the serve core. gateway_port=0 constructs NOTHING
        # (zero threads, zero registry keys — the introspect=False
        # bit-identity discipline); when on, the gateway requires the
        # serve core (it routes through ServeCore.submit_external) and a
        # feed-forward inference signature (recurrent/eps serving over
        # the wire is a follow-up: core state has no wire story yet).
        # A netfault-kind fault site is refused when the gateway is off —
        # the preempt/scale precedent: a chaos script that can never fire
        # is a chaos script that silently tests nothing.
        self._gateway = None
        self._gateway_backend = None
        self._gateway_tenants = None
        self._gateway_port: int | None = None
        self._gateway_restarts = 0
        self._recent_gateway_restarts: list[float] = []
        # Supervisor re-bind backoff (a failed rebuild retries, it never
        # kills training — see _supervise_gateway).
        self._gateway_retry_at = 0.0
        if config.gateway_port != 0:
            if not config.inference_server or not self._use_serve_core():
                raise ValueError(
                    "gateway_port != 0 requires inference_server=True and "
                    "the serve core (serve=True / ASYNCRL_SERVE=1): the "
                    "gateway serves through ServeCore's continuous batch"
                )
            from asyncrl_tpu.rollout.sebulba import inference_mode

            if inference_mode(config, self.model) != "ff":
                raise ValueError(
                    "gateway_port != 0 requires a feed-forward policy "
                    "(core='ff', algo != 'qlearn'): recurrent/epsilon "
                    "inference has no wire protocol yet"
                )
            if config.gateway_deadline_ms <= 0:
                raise ValueError(
                    "gateway_deadline_ms must be > 0: it is the default "
                    "end-to-end budget for requests without an "
                    f"X-Deadline-Ms header (got {config.gateway_deadline_ms})"
                )
            from asyncrl_tpu.serve import gateway as gateway_mod

            # Eager spec validation: a malformed SLO matrix (or deadline,
            # above) fails at construction, where the operator reads it —
            # not mid-train when the gateway first spawns.
            self._gateway_tenants = gateway_mod.parse_tenant_spec(
                config.gateway_tenant_spec
            )
        else:
            registry = faults.active()
            if registry is not None and registry.has_kind("netfault"):
                raise ValueError(
                    "fault spec arms a 'netfault' site but the gateway is "
                    "off (gateway_port=0): the scripted wire failure "
                    "could never fire and would silently test nothing"
                )
        # Automatic divergence rollback (RollbackPolicy): armed by
        # rollback_bad_windows > 0, which also arms the learner's
        # device-side NaN-guard. Needs a checkpoint_dir — without retained
        # steps there is nothing to roll back to.
        self._rollback = None
        if config.rollback_bad_windows > 0:
            if not config.checkpoint_dir:
                raise ValueError(
                    "rollback_bad_windows > 0 requires checkpoint_dir: "
                    "divergence rollback restores the last-good retained "
                    "checkpoint"
                )
            self._rollback = durability.RollbackPolicy(
                config.rollback_bad_windows, config.rollback_max_attempts
            )
        # Cumulative NaN-guard skip count (window key nonfinite_skips).
        self._nonfinite_skips = 0.0
        # §5.2b debug mode: transport invariants on drained fragments.
        from asyncrl_tpu.utils.debug import sync_debug_enabled

        self._seq_checker = (
            FragmentSequenceChecker() if sync_debug_enabled() else None
        )
        self._errors: "queue.Queue[tuple[int, int, BaseException]]" = (
            queue.Queue()
        )
        self._stop = threading.Event()
        self._actors: list[ActorThread] = []
        # Per-slot restart counters (monotone across stop/start cycles;
        # stamped into fragments for the §5.2b transport checker).
        self._actor_gens = [0] * config.actor_threads
        self._updates = 0
        # version -> update count at publish, for the param_lag metric
        # (with fused dispatch, publishes are no longer every
        # actor_staleness updates, so the mapping must be recorded, not
        # derived). Version 0 is the constructor-published initial params.
        self._published_updates: dict[int, int] = {0: 0}
        self._actor_restarts = 0
        # Crash-storm window: CRASH-caused restarts only. Watchdog
        # retirements keep their own window below, and deliberate elastic
        # scale-downs enter NEITHER — a run must never abort for being
        # scaled (or stall-churned) the way it aborts for crash-looping.
        self._recent_restarts: list[float] = []
        self._recent_watchdog: list[float] = []
        self._RESTART_WINDOW_S = 300.0
        # Supervised inference-server restarts (same storm window; the
        # threshold is the actor rule at one instance: > 3 in the window).
        self._server_restarts = 0
        self._recent_server_restarts: list[float] = []
        # Cumulative queue.Full retries of RETIRED actors; the live window
        # metric adds the running actors' own counters on top.
        self._backpressure_base = 0
        self._next_actor_seed = config.seed * 7919 + 1
        self._actor_device = None  # CpuAsyncTrainer pins actors to host CPU
        self._server = None  # shared inference server (config.inference_server)
        # The server's OWN stop event (never the cohort's): a supervised
        # server restart must be able to retire one server without taking
        # every healthy actor down with it.
        self._server_stop = threading.Event()
        # Inference-server coalescing snapshot for the per-window
        # infer_coalesce_batch metric: (server incarnation, rounds, rows)
        # at the last window close. Keyed on the monotonic restart counter
        # — not id(server), whose freed address can be reused — so a
        # rebuilt server's fresh counters never read as a negative delta.
        self._infer_snap: tuple[int | None, int, int] = (None, 0, 0)
        # Caches built on first use but DECLARED here (no hasattr dances):
        # evaluation host pools per (num_episodes, seed), and the jitted
        # greedy fn (set lazily in evaluate — model apply shape is known
        # only there for recurrent cores).
        self._eval_pools = {}
        self._greedy_fn = None
        # Crash-consistent resume (runtime/durability.py): checkpoint.setup
        # above already restored the LEARNER state (the pre-existing
        # auto-resume); with resume armed the checkpoint's run_state
        # metadata restores the rest of the run — host counters, the
        # actor-PRNG cursor, the health monitor's window cursor, the
        # elastic fleet size (applied when the fleet starts), and the
        # rollback attempt budget — so every counter is monotone across
        # the process boundary and timeseries.jsonl continues as a new
        # segment marked with a resume event.
        self._resume_fleet: int | None = None
        run_state = (self._ckpt.restore_meta or {}).get("run_state")
        if self._resume_on and run_state:
            self._updates = int(run_state.get("updates", 0))
            # Staleness-ledger rebase: the restored params ARE version 0
            # of this process, published at the restored update count —
            # without this, every resumed fragment would report a lag of
            # the full pre-preemption update count.
            self._published_updates = {0: self._updates}
            self._next_actor_seed = int(
                run_state.get("next_actor_seed", self._next_actor_seed)
            )
            self._actor_restarts = int(run_state.get("actor_restarts", 0))
            self._server_restarts = int(run_state.get("server_restarts", 0))
            self._gateway_restarts = int(
                run_state.get("gateway_restarts", 0)
            )
            if self._rollback is not None:
                self._rollback.attempts = int(
                    run_state.get("rollback_attempts", 0)
                )
            fleet = int(run_state.get("actors_live", config.actor_threads))
            if self._elastic_on and fleet != config.actor_threads:
                emin, emax = self._elastic_bounds()
                self._resume_fleet = max(emin, min(emax, fleet))
            monitor = self._obs.monitor
            if monitor is not None:
                monitor.window_idx = int(run_state.get("window_idx", 0))
            if self._obs.store is not None:
                self._obs.store.annotate({
                    "event_type": "resume",
                    "restored_update": self._updates,
                    "env_steps": float(self.env_steps),
                    "actors": fleet,
                })
        # The fleet size the last STOPPED fleet ran at: stop() clears
        # self._actors before the drain's final save_now (and before the
        # crash-path finalize), so without this snapshot an elastically
        # scaled fleet would checkpoint as the CONFIGURED size and resume
        # at the wrong shape.
        self._last_live_fleet = self._resume_fleet or config.actor_threads
        # Every save from here on carries the full run state in its
        # metadata (TrainerCheckpointing.meta_fn), so ANY retained step —
        # periodic, elastic-barrier, or the drain's final save — can
        # resume the whole run.
        self._ckpt.meta_fn = self._run_state

    def _elastic_bounds(self) -> tuple[int, int]:
        """The elastic fleet bounds ``[min_actors, max_actors]``
        (``elastic_max_actors=0`` defaults the max to 2x the configured
        fleet) — ONE definition shared by the construct-time validation,
        the live controller, and the resume clamp."""
        cfg = self.config
        return (
            cfg.elastic_min_actors,
            cfg.elastic_max_actors or 2 * cfg.actor_threads,
        )

    def _run_state(self) -> dict[str, Any]:
        """The resume inventory carried by every checkpoint's metadata
        (see docs/ARCHITECTURE.md "Durable runs & divergence rollback")."""
        monitor = self._obs.monitor
        return {
            "actors_live": len(self._actors) or self._last_live_fleet,
            "next_actor_seed": self._next_actor_seed,
            "updates": self._updates,
            "window_idx": monitor.window_idx if monitor is not None else 0,
            "rollback_attempts": (
                self._rollback.attempts if self._rollback is not None else 0
            ),
            "actor_restarts": self._actor_restarts,
            "server_restarts": self._server_restarts,
            "gateway_restarts": self._gateway_restarts,
        }

    def _published(self, state):
        """What actors act under: the params, bundled with the obs-
        normalization stats when enabled (make_inference_fn unpacks)."""
        if self.config.normalize_obs:
            return (state.params, state.obs_stats)
        return state.params

    # --------------------------------------------------------------- actors

    def _epsilon_fn(self, index: int):
        """Per-thread behaviour-ε schedule for the Q-learning family: thread
        ``index``'s env slots take their rungs of the shared schedule
        (``learn.learner.qlearn_epsilon_schedule`` — one formula for every
        backend), annealed by the trainer's AUTHORITATIVE global frame
        counter, published to the ParamStore alongside params. (An earlier
        design extrapolated global frames from the thread's own count
        times actor_threads, which drifted under uneven thread progress
        and after actor restarts — ADVICE.md round 1.) The thread's own
        frames since its last store read are added so the anneal still
        advances between publishes (cadence: actor_staleness updates)."""
        cfg = self.config
        if cfg.algo != "qlearn":
            return None
        from asyncrl_tpu.learn.learner import qlearn_epsilon_schedule

        B = self._envs_per_actor
        gidx = index * B + np.arange(B, dtype=np.float32)
        store = self._store
        last = {"steps": store.env_steps(), "frames": 0, "anneal": 0.0}

        def epsilon_fn(thread_frames: int) -> np.ndarray:
            published = store.env_steps()
            if published != last["steps"]:
                last["steps"] = published
                last["frames"] = thread_frames
            frames = published + (thread_frames - last["frames"]) * max(
                cfg.actor_threads, 1
            )
            # Monotone anneal: the between-publish extrapolation can
            # OVERshoot true global progress (this thread faster than the
            # others), and the next publish would snap frames back down —
            # epsilon must never rise again once lowered.
            frames = max(float(frames), last["anneal"])
            last["anneal"] = frames
            return np.asarray(qlearn_epsilon_schedule(cfg, gidx, frames))

        return epsilon_fn

    def _spawn_actor(self, index: int) -> ActorThread:
        seed = self._next_actor_seed
        self._next_actor_seed += 104729
        pool = make_host_pool(self.config, self._envs_per_actor, seed=seed)
        inference_fn = (
            self._server.client(index)
            if self._server is not None
            else self._inference_fn
        )
        actor = ActorThread(
            index=index,
            pool=pool,
            inference_fn=inference_fn,
            store=self._store,
            out_queue=self._queue,
            unroll_len=self.config.unroll_len,
            seed=seed,
            stop_event=self._stop,
            errors=self._errors,
            device=self._actor_device,
            initial_core=self._initial_core,
            epsilon_fn=self._epsilon_fn(index),
            track_returns=self.config.normalize_returns,
            return_discount=self.config.gamma,
            generation=self._actor_gens[index],
            staging=self._staging,
        )
        actor.start()
        return actor

    def _start_actors(self) -> None:
        if self._actors:
            return
        # A FRESH stop event per cohort (never .clear() the old one): if a
        # previous stop()'s join timed out, the zombie thread still holds
        # the old event — which stays set, so the zombie exits at its next
        # check instead of being revived alongside its replacement. Every
        # new cohort also bumps all generation stamps, so a zombie's late
        # fragments can never collide with the new cohort's seq streams.
        self._stop = threading.Event()
        self._actor_gens = [g + 1 for g in self._actor_gens]
        if self.config.inference_server:
            self._spawn_server()
        if self.config.gateway_port != 0:
            self._spawn_gateway()
        self._actors = [
            self._spawn_actor(i) for i in range(self.config.actor_threads)
        ]

    def _use_serve_core(self) -> bool:
        """Serve core (asyncrl_tpu/serve/) vs legacy InferenceServer for
        the shared server. ``ASYNCRL_SERVE`` wins over ``config.serve``
        when set — the no-code-change A/B knob, like ASYNCRL_FAULTS."""
        env = os.environ.get("ASYNCRL_SERVE", "")
        if env:
            return env.lower() not in ("0", "false", "no")
        return self.config.serve

    def _use_elastic(self) -> bool:
        """Elastic runtime on? ``ASYNCRL_ELASTIC`` wins over
        ``config.elastic`` when set — same precedence as ASYNCRL_SERVE."""
        env = os.environ.get("ASYNCRL_ELASTIC", "")
        if env:
            return env.lower() not in ("0", "false", "no")
        return self.config.elastic

    def _spawn_server(self) -> None:
        """(Re)build the shared inference server on a fresh personal stop
        event. Callers re-wire actors separately: existing clients of a
        dead/retired server raise into their actor threads, whose restarts
        pick up ``self._server``'s new clients. Both cores expose the same
        supervisor surface (heartbeat, _fatal, client(i), coalesce
        counters), so everything downstream is core-agnostic."""
        from asyncrl_tpu.rollout.sebulba import inference_mode

        cfg = self.config
        self._server_stop = threading.Event()
        # Decorrelate the restarted server's action-sampling key stream
        # from its predecessor's.
        seed = cfg.seed + 1_000_003 * self._server_restarts
        mode = inference_mode(cfg, self.model)
        if self._use_serve_core():
            from asyncrl_tpu.serve.scheduler import ServeCore
            from asyncrl_tpu.serve.slo import SLOGate

            self._server = ServeCore(
                self._inference_fn,
                store=self._store,
                # The LIVE fleet size, not the configured one: a
                # supervised rebuild after an elastic scale-up must cover
                # every live client slot (fresh construction sees an
                # empty fleet and falls back to the config).
                num_clients=max(cfg.actor_threads, len(self._actors)),
                stop_event=self._server_stop,
                mode=mode,
                seed=seed,
                device=self._actor_device,
                deadline_ms=cfg.serve_deadline_ms,
                slo=SLOGate(
                    p95_target_ms=cfg.serve_slo_p95_ms,
                    max_inflight=cfg.serve_max_inflight,
                    shed=cfg.serve_shed,
                ),
            )
        else:
            from asyncrl_tpu.rollout.inference_server import InferenceServer

            self._server = InferenceServer(
                self._inference_fn,
                self._store,
                num_clients=cfg.actor_threads,
                stop_event=self._server_stop,
                seed=seed,
                mode=mode,
                device=self._actor_device,
            )
        self._server.start()

    def _spawn_gateway(self) -> None:
        """(Re)build the external gateway (serve/gateway.py). The BACKEND
        persists across rebuilds — its serve-stale anchor (a held
        ParamSlots lease on the last-good generation) must survive a
        gateway crash, that being exactly the outage stale mode exists
        for. A rebuild after a crash re-binds the SAME port the first
        spawn resolved (ephemeral -1 included), so external clients'
        retry layers reconnect without re-discovery."""
        from asyncrl_tpu.serve import gateway as gateway_mod

        cfg = self.config
        if self._gateway_backend is None:
            self._gateway_backend = gateway_mod.CoreBackend(
                core_fn=lambda: self._server,
                inference_fn=self._inference_fn,
                obs_shape=self.spec.obs_shape,
                seed=cfg.seed,
            )
        port = (
            self._gateway_port
            if self._gateway_port is not None
            else cfg.gateway_port
        )
        self._gateway = gateway_mod.ServeGateway(
            self._gateway_backend,
            port=port,
            bind_host=gateway_mod.env_host(cfg.gateway_host),
            tenants=self._gateway_tenants,
            default_deadline_ms=cfg.gateway_deadline_ms,
        ).start()
        self._gateway_port = self._gateway.port

    def _supervise_gateway(self) -> None:
        """Supervised gateway rebuild: a gateway whose serving thread died
        (netfault crash, serving-loop failure) is retired and rebuilt on
        its own storm window — the ACTOR FLEET IS NEVER TOUCHED (the
        chaos matrix's headline assertion for this boundary: a frontier
        death must cost external availability only, never training). The
        same invariant covers the REBUILD itself: a re-bind that fails
        (the port momentarily taken during the outage) costs external
        availability only — training continues and the supervisor keeps
        retrying on a short backoff. (The INITIAL bind in _start_actors
        stays loud: a taken port at startup is an operator config error,
        not an outage.)"""
        if self._stop.is_set() or self.config.gateway_port == 0:
            return
        gateway = self._gateway
        if gateway is None:
            # A previous rebuild could not re-bind: retry, backed off.
            if time.monotonic() < self._gateway_retry_at:
                return
            try:
                self._spawn_gateway()
            except OSError as e:
                self._gateway_retry_at = time.monotonic() + 2.0
                print(
                    f"asyncrl_tpu: gateway re-bind failed ({e}); external "
                    "serving stays down, retrying (training continues)",
                    file=sys.stderr,
                )
            return
        if gateway.is_alive() and gateway.fatal is None:
            return
        fatal = gateway.fatal
        flightrec.record(
            "supervisor.gateway_restart", detail=f"{fatal!r}"
        )
        self._gateway_restarts += 1
        obs_registry.counter("gateway_restarts").inc()
        # The server storm rule at one instance: > 3 in the window aborts.
        self._storm_guard(
            self._recent_gateway_restarts, 3, "gateway", fatal
        )
        gateway.stop()
        self._gateway = None  # a failed re-spawn must not re-reap the dead one
        try:
            self._spawn_gateway()
        except OSError as e:
            self._gateway_retry_at = time.monotonic() + 2.0
            flightrec.record(
                "supervisor.gateway_rebind_failed", detail=f"{e}"
            )
            print(
                f"asyncrl_tpu: gateway re-bind failed ({e}); external "
                "serving stays down, retrying (training continues)",
                file=sys.stderr,
            )

    def _supervise(self) -> None:  # thread-entry: watchdog@learner
        """The reap loop: rebuild a dead/hung inference server, restart
        dead actors (SURVEY.md §5.3 — fresh env pool each time), retire and
        replace HUNG actors via the heartbeat watchdog, and re-raise only
        if failures repeat rapidly. "Rapidly" means within
        ``_RESTART_WINDOW_S``: sporadic transient failures over a long run
        recover indefinitely; a crash loop aborts."""
        from asyncrl_tpu.rollout.inference_server import InvariantViolation

        self._supervise_server()
        self._supervise_gateway()
        self._supervise_stalled_actors()
        try:
            while True:
                index, gen, err = self._errors.get_nowait()
                if isinstance(err, InvariantViolation):
                    # §5.2b failures are integrity bugs, not transient actor
                    # faults: abort NOW instead of churning restarts (even
                    # when reported by an already-replaced generation). The
                    # one abort class that means a REAL pipeline bug gets
                    # forensics like every other failure path.
                    flightrec.record(
                        "supervisor.invariant_abort",
                        detail=f"actor {index} gen {gen}: {err!r}",
                    )
                    self.stop()
                    raise err
                if index >= len(self._actors) or gen != self._actor_gens[index]:
                    # A thread the supervisor already retired (watchdog
                    # abandonment racing the thread's own death report) or
                    # a slot a deliberate scale-down removed (its gen was
                    # bumped at retirement): ONE failure must not restart
                    # the slot twice — the second restart would orphan the
                    # live replacement (or resurrect a retired slot).
                    continue
                self._restart_actor(index, err)
        except queue.Empty:
            pass

    def _storm_guard(
        self,
        stamps: list[float],
        threshold: int,
        what: str,
        cause: BaseException | None,
    ) -> None:
        """ONE sliding-window storm policy for every supervised component:
        record a restart, prune the window, abort past the threshold."""
        now = time.monotonic()
        stamps.append(now)
        stamps[:] = [t for t in stamps if now - t < self._RESTART_WINDOW_S]
        if len(stamps) > threshold:
            # Last forensics before the abort: the flight recorder gets
            # the final seconds of every thread's spans (no-op unarmed).
            flightrec.record(
                "supervisor.storm_abort",
                detail=f"{what}: {len(stamps)} restarts in "
                f"{self._RESTART_WINDOW_S}s (cause: {cause!r})",
            )
            self.stop()
            raise RuntimeError(
                f"{what} failed repeatedly ({len(stamps)} restarts in "
                f"{self._RESTART_WINDOW_S}s)"
            ) from cause

    def _restart_actor(
        self, index: int, err: BaseException | None, reason: str = "crash"
    ) -> None:
        """Retire actor ``index`` (already dead or abandoned) and spawn its
        replacement, aborting on a restart storm. ``reason`` classifies
        the retirement cause for the storm accounting: ``"crash"`` feeds
        the crash-storm window, ``"watchdog"`` its own window — a
        stall-churning fleet and a crash-looping one are different
        failures and must not pool toward one abort threshold (and a
        deliberate elastic scale-down goes through
        :meth:`_scale_down_actor` instead, entering neither)."""
        # Forensics FIRST, replacement second: the dump captures every
        # thread's spans as they were when the failure was detected
        # (crash or watchdog retirement alike). No-op when unarmed.
        flightrec.record(
            "supervisor.actor_restart",
            detail=(
                f"actor {index} gen {self._actor_gens[index]} "
                f"reason={reason}: {err!r}"
            ),
        )
        self._actor_restarts += 1
        stamps = (
            self._recent_watchdog
            if reason == "watchdog"
            else self._recent_restarts
        )
        # The bar follows the LIVE fleet (3 per actor), not the configured
        # actor_threads: an elastically grown fleet earns proportionally
        # more tolerated restarts, a shrunken one keeps the tight bar a
        # small fleet had before elasticity existed.
        self._storm_guard(
            stamps, 3 * max(1, len(self._actors)),
            f"actor {index} ({reason})", err,
        )
        self._actor_gens[index] += 1
        self._backpressure_base += self._actors[index].backpressure
        if self._staging is not None:
            # Void the dead/abandoned thread's open slab lease: the row
            # re-opens for the replacement under a fresh generation, and
            # any late write/commit from a zombie raises StaleLeaseError
            # instead of scribbling on the re-leased row.
            lease = self._actors[index]._open_lease
            if lease is not None:
                self._staging.void(lease)
        self._actors[index] = self._spawn_actor(index)

    def _supervise_stalled_actors(self) -> None:
        """Heartbeat watchdog (config.stall_timeout_s > 0): an actor whose
        progress stamp went stale is HUNG — a raised exception would have
        landed in the error queue — so retire it through its personal
        abandon event and restart, under the same storm accounting as a
        crash. A thread wedged past the join window is abandoned exactly
        like stop()'s timeout path (it can only exit, never produce: its
        puts check the abandon event, and generations already advanced)."""
        timeout_s = self.config.stall_timeout_s
        if timeout_s <= 0 or not self._actors:
            return
        now = time.monotonic()
        for index, actor in enumerate(self._actors):
            if not actor.is_alive():
                continue  # crashed, not hung: the error path owns it
            if now - actor.heartbeat <= timeout_s:
                continue
            actor.abandon.set()
            actor.join(timeout=1.0)
            if actor.is_alive():
                print(
                    f"asyncrl_tpu: hung actor {actor.index} did not join "
                    "within 1s; abandoning thread (it exits at its next "
                    "abandon-event check)",
                    file=sys.stderr,
                )
            self._restart_actor(
                index,
                RuntimeError(
                    f"actor {index} made no progress for more than "
                    f"{timeout_s}s (heartbeat watchdog)"
                ),
                reason="watchdog",
            )

    def _supervise_server(self) -> None:
        """Supervised inference-server restart: a server thread that died
        (any exception — recorded in ``_fatal``) or hung (stale heartbeat
        under the watchdog) is retired via its personal stop event and
        rebuilt. Its orphaned clients raise the real cause into their
        actor threads, whose restarts wire up to the new server. An
        ``InvariantViolation`` death aborts instead — transport-integrity
        bugs must never feed a restart loop."""
        server = self._server
        if server is None or self._stop.is_set():
            return
        from asyncrl_tpu.rollout.inference_server import InvariantViolation

        fatal = server._fatal
        if isinstance(fatal, InvariantViolation):
            flightrec.record(
                "supervisor.invariant_abort", detail=f"server: {fatal!r}"
            )
            self.stop()
            raise fatal
        hung = (
            self.config.stall_timeout_s > 0
            and server.is_alive()
            and time.monotonic() - server.heartbeat
            > self.config.stall_timeout_s
        )
        if server.is_alive() and not hung:
            return
        # Authoritative _fatal re-read: the cause is written just before
        # the thread exits, so the first read above can race it — but once
        # is_alive() is False the assignment is guaranteed visible. Without
        # this, an InvariantViolation landing in that window would feed a
        # rebuild instead of the abort the policy promises.
        fatal = server._fatal or fatal
        if isinstance(fatal, InvariantViolation):
            flightrec.record(
                "supervisor.invariant_abort", detail=f"server: {fatal!r}"
            )
            self.stop()
            raise fatal
        flightrec.record(
            "supervisor.server_restart",
            detail=f"hung={hung}: {fatal!r}",
        )
        self._server_restarts += 1
        # The actor storm rule at one instance: > 3 in the window aborts.
        self._storm_guard(
            self._recent_server_restarts, 3, "inference server", fatal
        )
        self._server_stop.set()  # wake blocked clients of the old server
        server.join(timeout=5.0)
        if server.is_alive():
            print(
                "asyncrl_tpu: hung inference server did not join within "
                "5s; abandoning thread (its stop event stays set)",
                file=sys.stderr,
            )
        self._spawn_server()
        # Actors were likely blocked on the dead server; their stamps are
        # stale through no fault of their own — refresh so the stall
        # watchdog doesn't double-count the outage against them. Stamped
        # AFTER the join above (which can eat seconds on a wedged server);
        # an earlier timestamp could already be past stall_timeout_s.
        refreshed = time.monotonic()
        for actor in self._actors:
            actor.heartbeat = refreshed

    # -------------------------------------------------------------- elastic

    def _scale_up_actor(self) -> None:
        """Grow the fleet by one slot (window-close thread). The serve
        core's client slot registers FIRST (``client(index)`` must not
        bounds-fail), the thread spawns LAST — mutate-last, so a failing
        env-pool build observed by the reconfigure barrier leaves the
        fleet exactly as it was."""
        index = len(self._actors)
        while len(self._actor_gens) <= index:
            self._actor_gens.append(0)
        if self._server is not None:
            self._server.ensure_client(index)
        try:
            self._actors.append(self._spawn_actor(index))
        # lint: broad-except-ok(not a swallow: cleanup-and-reraise — the serve-client registration unwinds and the original failure propagates to the reconfigure barrier)
        except BaseException:
            # _spawn_actor registers the serve-client slot (client(index))
            # BEFORE the thread exists; if the build fails after that
            # point, a ghost registration would hold every future
            # dispatch's slab-full target one client high — each batch
            # waiting out its full deadline on a client that can never
            # submit. remove_client is idempotent, so this is safe even
            # when the failure preceded the registration.
            if self._server is not None:
                self._server.remove_client(index)
            raise

    def _scale_down_actor(self) -> None:
        """Retire the highest slot (window-close thread) through the
        existing per-thread retirement path — the abandon event, the join
        window, the lease void — so shrink is drain-clean by the same
        argument as a watchdog retirement: the thread can only exit, and
        its voided OPEN lease raises ``StaleLeaseError`` on any late
        write. Fragments it already committed and queued keep valid
        leases and drain into the learner normally — real on-policy data
        is consumed, not discarded (the "zero dropped leases" chaos
        assertion counts on exactly this). The slot's
        generation bumps so a zombie's late error report (and a future
        regrow of the same index) can never be confused with the retired
        stream. Deliberate: enters NO storm window."""
        index = len(self._actors) - 1
        actor = self._actors[index]
        actor.abandon.set()
        actor.join(timeout=5.0)
        if actor.is_alive():
            print(
                f"asyncrl_tpu: scaled-down actor {index} did not join "
                "within 5s; abandoning thread (it exits at its next "
                "abandon-event check)",
                file=sys.stderr,
            )
        self._actors.pop()
        self._actor_gens[index] += 1
        self._backpressure_base += actor.backpressure
        if self._staging is not None:
            lease = actor._open_lease
            if lease is not None:
                self._staging.void(lease)
        if self._server is not None:
            # AFTER the join: the actor can no longer submit, so removing
            # its registration cannot strand a pending request — and the
            # removal wakes the batch-fill wait so the slab-full condition
            # re-targets the shrunken client set.
            self._server.remove_client(index)

    def _build_staging_ring(self, actor_count: int):
        """Allocate — NOT install — a staging ring sized for
        ``actor_count`` (auto sizing only; an explicit ``staging_slabs``
        is an operator's fixed choice). None = no resize needed. The
        fallible slab allocation lives here so the reconfigure closure
        can run it BEFORE any fleet mutation; installing is the separate
        ``self._staging.swap`` (the RingSwapHolder generation protocol,
        rollout/staging.py: in-flight leases finish on the old ring)."""
        if self._staging_template is None or self.config.staging_slabs:
            return None
        from asyncrl_tpu.rollout import staging

        depth = staging.auto_num_slabs(
            self._queue.maxsize, actor_count, self._staging_rows
        )
        if depth == self._staging.num_slabs:
            return None
        return staging.StagingRing(
            self._staging_template,
            rows_per_slab=self._staging_rows,
            num_slabs=depth,
        )

    def _elastic_step(self, window: dict[str, Any]) -> None:
        """One controller evaluation at window close (window-close thread,
        next to the health monitor). A decision executes inside the
        save → reconfigure → restore barrier and is recorded as a
        structured event everywhere a crash would be: flight recorder,
        registry counters, time-series annotation."""
        decision = self._elastic.decide(window, len(self._actors))
        if decision is None:
            return
        before = len(self._actors)
        flightrec.record(
            f"elastic.scale_{decision.direction}",
            detail=f"{decision.reason}: {decision.detail} "
            f"(fleet {before} {decision.delta:+d})",
        )

        def reconfigure():
            # Exactly ONE slot per decision (the controller's delta
            # contract: delta is always ±1) — and mutate-last across the
            # COMPOSED action: the ring resize's fallible slab allocation
            # runs before the fleet changes, and the swap installs it
            # only after the slot operation succeeded. A failure anywhere
            # leaves both the fleet and the data path on the pre-scale
            # shape the barrier's restore message describes; an unused
            # pre-built ring is just garbage-collected.
            new_ring = self._build_staging_ring(before + decision.delta)
            if decision.delta > 0:
                self._scale_up_actor()
            else:
                self._scale_down_actor()
            if new_ring is not None:
                self._staging.swap(new_ring)

        with trace.span(span_names.ELASTIC_RECONFIGURE):
            self.state, self.env_steps, ok = self._elastic_barrier.run(
                self.state, self.env_steps, reconfigure
            )
        if not ok:
            # A rolled-back scale is NOT a scale: only
            # elastic_reconfigure_failed records the attempt, so the
            # scale counters/annotations never report a fleet change
            # that did not happen.
            obs_registry.counter("elastic_reconfigure_failed").inc()
            flightrec.record(
                "elastic.reconfigure_failed",
                detail=f"restored checkpoint barrier; fleet stays at "
                f"{len(self._actors)}",
            )
            return
        obs_registry.counter(f"elastic_scale_{decision.direction}").inc()
        if self._obs.store is not None:
            self._obs.store.annotate(
                decision.event(before, len(self._actors))
            )

    def _advance_updates(self, n: int) -> None:
        """Advance the learner-update counter by ``n`` and publish at
        every crossed actor_staleness boundary — ONE home for the
        publish cadence, so the fresh drain and the replay passes can
        never drift on when actors see new weights. (With n >= the
        staleness period, every call publishes — the fused-dispatch
        coarsening trade, unchanged.)"""
        before = self._updates
        self._updates += n
        staleness = max(self.config.actor_staleness, 1)
        if before // staleness != self._updates // staleness:
            version = self._store.publish(
                self._published(self.state), self.env_steps
            )
            self._published_updates[version] = self._updates
            # Bound the map: anything older than the deepest possible
            # in-flight fragment is unreachable.
            for old in [
                v for v in self._published_updates
                if v < version - 4 * (self._queue.maxsize + 2)
            ]:
                del self._published_updates[old]

    def _replay_passes(self, pending: list) -> None:
        """The IMPACT reuse phase, run after each fresh update: lease up
        to ``replay_passes - 1`` least-reused ring rows and feed each to
        the learner as one more SGD pass. Replayed consumptions feed the
        PR-8 staleness ledger (lag measured against the slab's ORIGINAL
        behaviour publish — off-policy-ness stays observed, not guessed)
        and the reuse/target-lag window; env_steps does NOT advance (no
        new environment data was consumed)."""
        cfg = self.config
        # target_lag is phased on the HOST update cursor. Approximation,
        # documented: under the NaN-guard (a skipped update holds the
        # device-side update_step while this cursor advances) or after a
        # rollback restore (device step rewinds, this cursor does not —
        # the PR-10 rule that only resume rewrites it), the reported
        # phase can drift from the device refresh schedule. Diagnostic-
        # grade by design; deriving it from the device step would cost a
        # host sync per consumed sample.
        period = max(cfg.target_update_period, 1)
        for _ in range(cfg.replay_passes - 1):
            rlease = self._replay.lease_sample(self._replay_rng)
            if rlease is None:
                break
            try:
                replayed, reuse, behaviour = rlease.consume()
            except replay_lib.ReplayStaleError:
                continue
            self.state, metrics = self.learner.update(self.state, replayed)
            pending.append(metrics)
            # Observed BEFORE the counter advances, matching the fresh
            # path's convention (lag = consuming update's pre-advance
            # index minus the behaviour publish): the replay pass that
            # immediately follows a fresh consumption at lag L reports
            # L+1, not L+2.
            if self._staleness is not None:
                self._staleness.observe(self._updates - behaviour)
            self._reuse_window.observe(reuse, self._updates % period)
            self._advance_updates(1)

    def _infer_coalesce_window(self) -> dict[str, float]:
        """Mean coalesced inference-batch rows per served round since the
        last window close ({} without a shared server). Snapshots per
        server INCARNATION (the restart counter), so a supervised
        rebuild's fresh counters never read as a negative delta."""
        server = self._server
        if server is None:
            return {}
        incarnation = self._server_restarts
        rounds, rows = server.coalesce_rounds, server.coalesce_rows
        snap_inc, snap_rounds, snap_rows = self._infer_snap
        if snap_inc != incarnation:
            snap_rounds = snap_rows = 0
        d_rounds = rounds - snap_rounds
        d_rows = rows - snap_rows
        self._infer_snap = (incarnation, rounds, rows)
        return {
            "infer_coalesce_batch": d_rows / d_rounds if d_rounds else 0.0
        }

    def _drain_queue(self) -> None:
        """Discard queued fragments — THROUGH the §5.2b checker when armed,
        so a discarded fragment still advances its stream (a later gap from
        skipping it unchecked would be a false positive, and a real
        transport bug hiding among discards would go unseen)."""
        try:
            while True:
                fragment = self._queue.get_nowait()
                if self._seq_checker is not None:
                    self._seq_checker.check(fragment)
        except queue.Empty:
            pass

    def stop(self) -> None:
        """Stop actor threads (and the inference server), drain the queue."""
        self._stop.set()
        if self._gateway is not None:
            # The wire boundary closes FIRST: external clients observe
            # 503-draining (and then connection refused) rather than
            # requests dying mid-pipeline behind them.
            self._gateway.close_admissions()
            self._gateway.stop()
            self._gateway = None
        # The server's personal event must be set BEFORE the actor joins:
        # actors blocked in _submit wake on the SERVER's stop event, not
        # the cohort's — setting it late would make every join below eat
        # its full timeout against a wedged server.
        self._server_stop.set()
        # Unblock producers stuck on a full queue.
        self._drain_queue()
        for actor in self._actors:
            actor.join(timeout=5.0)
            if actor.is_alive():
                # Loud, not silent: the thread outlived the join window
                # (e.g. wedged in pool.step). Its cohort's stop event stays
                # set forever — it can only exit, never resume — and the
                # next cohort gets a fresh event + bumped generations.
                print(
                    f"asyncrl_tpu: actor {actor.index} did not join within "
                    "5s; abandoning thread (it will exit at its next "
                    "stop-event check)",
                    file=sys.stderr,
                )
        # Drain AGAIN after the joins: an actor mid-put when the first drain
        # ran can still land one fragment; left queued, it would feed the
        # next train() a stale-cohort fragment.
        self._drain_queue()
        for actor in self._actors:
            self._backpressure_base += actor.backpressure
        if self._actors:
            self._last_live_fleet = len(self._actors)
        self._actors = []
        if self._server is not None:
            self._server_stop.set()
            self._server.join(timeout=5.0)
            self._server = None
        if self._staging is not None:
            # Every lease (queued, open, or held by an abandoned zombie)
            # goes stale and every slab frees: the next train() starts on
            # a clean ring, and a zombie's late commit raises instead of
            # landing in a recycled row.
            self._staging.reset()
        if self._replay is not None:
            # Same hygiene at the device tier: a new cohort starts on an
            # empty replay ring — cross-cohort replay would resurrect a
            # stopped run's off-policy tail — and on fresh telemetry
            # (the trend baseline and any undrained reuse observations
            # belong to the stopped cohort's windows).
            self._replay.quarantine()
            self._reuse_window.drain()
            self._stall_history.clear()
        if self._device_queue is not None:
            # Straggler device leases go stale and every pending update
            # handle drains: no async consumer of a slot outlives the
            # cohort whose drain minted it.
            self._device_queue.reset()

    # ----------------------------------------------------- durable runs

    def _restore_fleet(self) -> None:
        """Resume path: grow/shrink the just-started fleet to the
        checkpointed size (one slot at a time through the SAME executors
        a live scale uses, ring resize included), so a run preempted at
        an elastically-scaled shape resumes at that shape instead of the
        configured one."""
        target = self._resume_fleet
        if target is None:
            return
        self._resume_fleet = None
        before = len(self._actors)
        while len(self._actors) != target:
            step = 1 if len(self._actors) < target else -1
            new_ring = self._build_staging_ring(len(self._actors) + step)
            if step > 0:
                self._scale_up_actor()
            else:
                self._scale_down_actor()
            if new_ring is not None:
                self._staging.swap(new_ring)
        flightrec.record(
            "durability.fleet_restored",
            detail=f"resume rebuilt the fleet at {target} actors "
            f"(configured {before})",
        )

    def _preempt_drain(self, drain) -> None:
        """The preemption-safe drain (SIGTERM/SIGINT under a grace
        budget): stop serve admissions, retire the fleet through the
        existing void/commit path, flush the partial obs window + flight
        recorder (reason=preempt), make ONE final full-run-state
        checkpoint durable, then leave with the distinct EXIT_DRAINED
        code. Runs on the train (window-close) thread; the coordinator's
        deadline watchdog hard-kills past the grace."""
        flightrec.record(
            "supervisor.preempt",
            detail=f"signal {drain.signum}: draining within "
            f"{drain.grace_s:.0f}s, then exiting {durability.EXIT_DRAINED}",
        )
        if self._gateway is not None:
            # The drain protocol's outermost edge: gateway admissions
            # close BEFORE the serve gate, so no external request can be
            # admitted into a pipeline that is about to drain under it —
            # and before the final checkpoint below, so the checkpoint
            # never races live wire traffic.
            self._gateway.close_admissions()
        server = self._server
        if server is not None:
            gate = getattr(server, "slo", None)
            if gate is not None:
                # New admissions refuse FIRST, so the actor joins below
                # never race fresh requests into the dispatch queue.
                gate.close()
        # stop() is the existing drain-clean retirement: queued fragments
        # discard through the §5.2b checker, actors join (or abandon),
        # every staging lease goes stale, every slab frees.
        self.stop()
        # Flush the partial metrics window so the timeseries' final
        # sample records where the run actually stopped (counters are
        # cumulative, so a short window is honest, never misleading).
        agg: dict[str, Any] = {
            "env_steps": self.env_steps,
            "drain_preempt": 1.0,
            "actor_restarts": self._actor_restarts,
            "server_restarts": self._server_restarts,
        }
        if self.config.gateway_port != 0:
            # Same guarded key the main-loop window exports: the terminal
            # sample must not drop the gateway's restart history.
            agg["gateway_restarts"] = self._gateway_restarts
        agg.update(faults.counters())
        self._obs.observe_window(agg)
        if self._ckpt.checkpointer is not None:
            # The final checkpoint carries the full run state via meta_fn
            # and must be DURABLE before the exit code promises it.
            self._ckpt.save_now(self.state, self.env_steps)
            self._ckpt.checkpointer.wait()
        self._obs.close()  # flight-recorder queue flushed to disk
        drain.finish()
        raise durability.PreemptedExit(drain.signum)

    def _quarantine_poisoned(self, slab_groups, fragments) -> int:
        """Divergence quarantine: fragments produced under (or poisoned
        by) a diverging policy must never reach the learner. Queued
        fragments discard through the §5.2b checker with their slab
        leases voided (rows re-open under fresh generations — the
        supervisor-retirement mechanics applied to data instead of
        threads); partial slab groups and legacy-path stacks clear the
        same way. Returns the quarantined fragment count."""
        count = 0
        try:
            while True:
                fragment = self._queue.get_nowait()
                if self._seq_checker is not None:
                    self._seq_checker.check(fragment)
                if fragment.lease is not None and self._staging is not None:
                    self._staging.void(fragment.lease)
                count += 1
        except queue.Empty:
            pass
        for group in slab_groups.values():
            for fragment in group:
                if fragment.lease is not None and self._staging is not None:
                    self._staging.void(fragment.lease)
                count += 1
        slab_groups.clear()
        count += len(fragments)
        fragments.clear()
        if self._replay is not None:
            # The PR-10 path extended to the replay tier: every
            # outstanding replay lease voids (a zombie consume raises)
            # and the ring empties — slabs produced under, or reused
            # across, the diverging stretch must never feed another
            # update. The telemetry purges with the data (the stop()
            # hygiene): the poisoned stretch's reuse/target-lag
            # observations and its stall baseline must not contaminate
            # the first post-rollback window's keys.
            dropped = self._replay.quarantine()
            self._reuse_window.drain()
            self._stall_history.clear()
            if dropped:
                obs_registry.counter("replay_quarantined").inc(dropped)
        if count:
            obs_registry.counter("rollback_quarantined").inc(count)
        return count

    def _execute_rollback(self, action) -> None:
        """Restore the last-good checkpoint (window-close thread). The
        tainted steps saved AFTER the last clean window are evicted
        first, so the fallback restore cannot land on a checkpoint
        written while the run was already diverging; the actor-PRNG
        cursor folds so the replayed stretch decorrelates from the
        trajectory that diverged; the restored params republish
        immediately so actors stop acting under the poisoned weights."""
        ckpt = self._ckpt.checkpointer
        ckpt.wait()
        steps = sorted(ckpt.all_steps())
        if not steps:
            # Rollback fired before the first save landed: there is
            # nothing to restore, but the NaN-guard already held the
            # params through every poisoned update, so the run continues
            # on the held state — record the degraded action instead of
            # dying on a restore that cannot exist.
            flightrec.record(
                "rollback.no_checkpoint",
                detail="rollback fired with no retained steps; "
                "continuing on NaN-guard-held params",
            )
            return
        last_good = self._rollback.last_good_step
        target = None
        if last_good is not None:
            good = [s for s in steps if s <= last_good]
            if good:
                target = good[-1]
        if target is None:
            # The banked last-good step was rotated out by max_to_keep
            # retention (or no clean window has banked one yet): the
            # OLDEST retained step is the closest surviving
            # approximation. Never evict the whole directory hunting for
            # a step that no longer exists.
            target = steps[0]
        for step in steps:
            if step > target:
                ckpt.delete_step(step)
        self.state, self.env_steps = ckpt.restore(self.state)
        # The run RE-TRAINS from here with fresh data: when it reaches
        # the restored step number again the save must REPLACE, not
        # no-op on the idempotent-save rule.
        ckpt.invalidate_restored()
        self._next_actor_seed += 104729 * 997  # fresh PRNG fold
        version = self._store.publish(
            self._published(self.state), self.env_steps
        )
        self._published_updates[version] = self._updates

    def _rollback_step(self, agg, slab_groups, fragments) -> bool:
        """One RollbackPolicy evaluation at window close (next to the
        health monitor and the elastic controller, same thread). Returns
        True when an action fired — the elastic controller skips a
        window whose signals a divergence just poisoned."""
        monitor = self._obs.monitor
        if monitor is not None:
            events = [
                e for e in monitor.recent_events()
                if e.window_idx == monitor.window_idx
            ]
        else:
            # No health layer mounted (trace off, no exposition port):
            # the policy still sees the one divergence signal the window
            # dict itself carries — a non-finite loss/grad_norm.
            events = []
            for key in ("loss", "grad_norm"):
                value = agg.get(key)
                if isinstance(value, float) and not np.isfinite(value):
                    events.append(
                        type("E", (), {"detector": "nonfinite_loss"})()
                    )
                    break
        ckpt = self._ckpt.checkpointer
        latest = ckpt.latest_step() if ckpt is not None else None
        action = self._rollback.on_window(events, latest)
        if action is None:
            return False
        counter = {
            "quarantine": "rollback_quarantine",
            "rollback": "rollback_restores",
            "abort": "rollback_abort",
        }[action.kind]
        obs_registry.counter(counter).inc()
        flightrec.record(f"rollback.{action.kind}", detail=action.detail)
        if self._obs.store is not None:
            self._obs.store.annotate(action.event())
        if action.kind == "abort":
            self.stop()
            raise RuntimeError(
                f"divergence rollback attempts exhausted: {action.detail}"
            )
        quarantined = self._quarantine_poisoned(slab_groups, fragments)
        print(
            f"asyncrl_tpu: rollback policy: {action.kind} — "
            f"{action.detail} ({quarantined} in-flight fragment(s) "
            "quarantined)",
            file=sys.stderr,
        )
        if action.kind == "rollback":
            self._execute_rollback(action)
        return True

    # ---------------------------------------------------------------- train

    def train(  # thread-entry: learner-drain@learner
        self,
        total_env_steps: int | None = None,
        callback: Callable[[dict[str, Any]], None] | None = None,
    ) -> list[dict[str, Any]]:
        """Drain fragments and update until ``total_env_steps`` consumed.

        Metric dicts match ``Trainer.train``'s contract (env_steps, fps,
        episode_return/length/count + loss terms).
        """
        cfg = self.config
        target = total_env_steps or cfg.total_env_steps
        validate_train_target(cfg, target)
        steps_per_fragment = self._envs_per_actor * cfg.unroll_len
        history: list[dict[str, Any]] = []

        # The drain usually runs on MainThread — tag its span ring with
        # the pipeline-stage group so reports/flight dumps say "learner".
        trace.tag_thread("learner")
        # Preemption-safe drain (runtime/durability.py): with a grace
        # budget, SIGTERM/SIGINT route through the coordinator (handlers
        # install on the main thread only; the scripted `preempt` fault
        # kind reaches the same coordinator either way) and the loop
        # polls one Event per iteration — the unarmed cost discipline.
        drain = None
        if self._drain_grace > 0:
            drain = durability.DrainCoordinator(self._drain_grace)
            drain.install()
            durability.set_active(drain)
        try:
            self._start_actors()
            self._restore_fleet()
        # lint: broad-except-ok(cleanup-and-reraise: the drain handlers uninstall, then the startup failure propagates unchanged)
        except BaseException:
            # Startup died before the main try/finally below could own
            # the teardown: the process signal handlers (and the
            # scripted-preempt registration) must not outlive the train
            # call that installed them — a later Ctrl-C would request a
            # drain nothing polls, and the orphaned watchdog would
            # os._exit the host process 30s later.
            if drain is not None:
                drain.finish()
                drain.uninstall()
                durability.clear_active(drain)
            raise
        pending: list[dict[str, jax.Array]] = []
        ret_sum = len_sum = count = lag_sum = 0.0
        # Fresh fragments consumed this window: the param_lag mean's
        # denominator (``pending`` also carries replay-pass metrics when
        # the ring is armed, so len(drained) would over-count).
        frag_count = 0
        window_start = time.perf_counter()
        window_steps = 0
        # Pipeline instrumentation (utils/metrics.py window keys):
        # learner_stall_frac = fraction of window wall time the drain spent
        # waiting on the fragment queue (the learner starved for data);
        # h2d_wait_s = time in host->device transfer the compute could not
        # hide (overlap path: an explicit transfer barrier before the next
        # dispatch; legacy path: the device_put call itself); h2d_bytes =
        # host bytes shipped.
        stall_s = 0.0
        h2d_wait_s = 0.0
        h2d_bytes = 0
        # Cumulative-counter baseline: a SECOND train() call on this agent
        # must not fire an eval at its first log boundary.
        updates_at_eval = self._updates
        K = cfg.updates_per_call
        fragments: list[Fragment] = []
        # Staging mode: fragments grouped by slab until a slab has all K
        # rows in hand (completion order, like the legacy arrival order).
        # Keyed by (minting ring, slab): under an elastic ring swap the
        # old ring's in-flight fragments and the new ring's never share a
        # group — a batch is one ring's slab, always.
        slab_groups: dict[tuple[Any, int], list[Fragment]] = {}
        ring = self._staging
        try:
            while self.env_steps < target:
                if drain is not None and drain.requested:
                    self._preempt_drain(drain)  # raises PreemptedExit
                self._supervise()
                t_wait = time.perf_counter()
                try:
                    with trace.span(span_names.LEARNER_QUEUE_WAIT):
                        fragment = self._queue.get(timeout=1.0)
                except queue.Empty:
                    stall_s += time.perf_counter() - t_wait
                    continue
                stall_s += time.perf_counter() - t_wait
                if self._seq_checker is not None:
                    self._seq_checker.check(fragment)
                if ring is not None:
                    lease = fragment.lease
                    if lease is None or not lease.valid():
                        # A zombie's fragment: its lease was voided when
                        # the supervisor retired the thread, and the row
                        # now belongs to the replacement. (The checker
                        # above already advanced the old stream.)
                        continue
                    batch_ring = lease.ring
                    group_key = (batch_ring, lease.slab)
                    group = slab_groups.setdefault(group_key, [])
                    group.append(fragment)
                    if len(group) >= K:
                        # Re-validate at the boundary: a lease can go
                        # stale AFTER queueing (supervisor voiding racing
                        # the actor's post-put bookkeeping) — the voided
                        # row's replacement fragment completes the slab.
                        group[:] = [f for f in group if f.lease.valid()]
                    if len(group) < K:
                        continue
                    batch = sorted(
                        slab_groups.pop(group_key),
                        key=lambda f: f.lease.row,
                    )
                    slab_id = lease.slab
                    rollout = batch_ring.batch(slab_id)
                else:
                    fragments.append(fragment)
                    if len(fragments) < K:
                        # Fused-dispatch mode: keep draining until K
                        # fragments are in hand (actors keep producing;
                        # supervision keeps running between gets).
                        continue
                    batch, fragments = fragments, []
                    slab_id = None
                    batch_ring = None
                    rollout = _stack_fragments([f.rollout for f in batch])
                if cfg.reward_scale != 1.0 or cfg.step_cost != 0.0:
                    # Learner's reward view (living cost, then scale). Host
                    # fragments carry RAW rewards, so the cost applies here.
                    # The disc_returns stream (normalize_returns' std
                    # tracker) is scaled but NOT cost-shifted — the same
                    # cost-free stream the anakin path tracks (see
                    # rollout/anakin.py), so both backends normalize by the
                    # same statistic for the same config.
                    rollout = rollout.replace(
                        rewards=(rollout.rewards - cfg.step_cost)
                        * cfg.reward_scale,
                        disc_returns=(
                            None
                            if rollout.disc_returns is None
                            else rollout.disc_returns * cfg.reward_scale
                        ),
                    )
                t_put = time.perf_counter()
                dlease = None
                try:
                    with trace.span(span_names.LEARNER_H2D_WAIT):
                        if self._device_queue is None:
                            rollout_d = self.learner.put_rollout(rollout)
                        else:
                            # HBM hand-off (rollout/device_queue.py): the
                            # same sharded transfer, behind the queue's
                            # slot ledger — enqueue blocks here (counted in
                            # devq_reuse_waits) when the drain has outrun
                            # the learner by the full queue depth.
                            dlease = self._device_queue.enqueue(rollout)
                            rollout_d = dlease.rollout()
                        if ring is not None:
                            # Transfer barrier: wait for slab i+1's H2D to
                            # finish BEFORE dispatching its update — this
                            # wait runs while the PREVIOUS update still
                            # computes on device, so transfer time hides
                            # behind compute and h2d_wait_s records only
                            # the part that didn't fit under it.
                            jax.block_until_ready(rollout_d)
                    h2d_wait = time.perf_counter() - t_put
                    h2d_wait_s += h2d_wait
                    # Registry histogram (obs/registry.py): the per-update
                    # unhidden-transfer distribution — p50/p95/max surface
                    # in the window next to the legacy h2d_wait_s sum.
                    obs_registry.histogram("h2d_wait_ms").observe(
                        1e3 * h2d_wait
                    )
                    # Slab batches are constant-sized (precomputed); only
                    # the legacy stack path needs the per-update leaf walk.
                    h2d_bytes += (
                        batch_ring.slab_nbytes
                        if batch_ring is not None
                        else int(
                            sum(
                                leaf.nbytes
                                for leaf in jax.tree.leaves(rollout)
                            )
                        )
                    )
                    if self._replay is not None:
                        # The fresh slab enters the device ring BEFORE the
                        # update can donate it (publish is a device-to-
                        # device install into the leased row, oldest-
                        # generation eviction); the fresh pass itself
                        # counts as the row's first consumption.
                        self._replay.publish(
                            rollout_d,
                            behaviour_update=self._published_updates.get(
                                batch[0].version, self._updates
                            ),
                            # Zero-copy adoption when the fragment is HBM-
                            # resident behind the device queue's ledger and
                            # the update cannot donate it out from under
                            # the ring (see DeviceReplayRing.publish).
                            ref=self._replay_ref,
                        )
                        self._reuse_window.observe(
                            1,
                            self._updates
                            % max(cfg.target_update_period, 1),
                        )
                    self.state, metrics = self.learner.update(
                        self.state, rollout_d
                    )
                # lint: broad-except-ok(cleanup-and-reraise: the held HBM lease voids so the slot cannot leak past train(), then the failure propagates unchanged)
                except BaseException:
                    if dlease is not None:
                        # The update never consumed this fragment: void
                        # the lease (barriers the in-flight H2D) so the
                        # slot frees instead of leaking held.
                        dlease.void()
                    raise
                if dlease is not None:
                    # The slot re-leases only once THIS update's output
                    # is ready — the staging retire gate, device tier.
                    dlease.consume(self.state.update_step)
                if batch_ring is not None:
                    # The slab frees only once this update's OUTPUT is
                    # ready — the gate that makes reuse safe even where
                    # the device buffer aliases host memory (CPU client).
                    # Retired on the MINTING ring: after an elastic ring
                    # swap an old-ring slab must free on the old ring.
                    batch_ring.retire(slab_id, self.state.update_step)
                self.env_steps += steps_per_fragment * K
                window_steps += steps_per_fragment * K
                pending.append(metrics)
                for i, f in enumerate(batch):
                    ret_sum += f.return_sum
                    len_sum += f.length_sum
                    count += f.count
                    # Policy lag of each fragment, in learner updates: it
                    # was consumed by fused inner update self._updates + i,
                    # and its behaviour params were published at the
                    # RECORDED update count of its version (publishes are
                    # per-boundary, not per-update, under fused dispatch).
                    # With inference_server=True this is an UPPER BOUND —
                    # the server evaluates under the latest published
                    # params, so later steps of a fragment can be fresher
                    # than its fragment-start version implies.
                    lag = (self._updates + i) - self._published_updates.get(
                        f.version, self._updates
                    )
                    lag_sum += lag
                    frag_count += 1
                    if self._staleness is not None:
                        self._staleness.observe(lag)

                self._advance_updates(K)
                if self._replay is not None:
                    # IMPACT reuse phase: replay_passes - 1 more SGD
                    # passes from the device ring, between fresh
                    # fragments — the learner trains while the actors
                    # are still producing the next slab.
                    self._replay_passes(pending)
                self._ckpt.after_update(self.state, self.env_steps)

                if len(pending) >= cfg.log_every or self.env_steps >= target:
                    with trace.span(span_names.LEARNER_METRICS):
                        drained = jax.device_get(pending)
                    pending = []
                    elapsed = time.perf_counter() - window_start
                    window_start = time.perf_counter()
                    # Metric leaves are scalars (K=1) or [K] stacks (fused
                    # dispatch): np handles both.
                    agg = {
                        k: float(np.mean([np.mean(m[k]) for m in drained]))
                        for k in drained[0]
                    }
                    agg["episode_count"] = count
                    agg["episode_return"] = ret_sum / max(count, 1.0)
                    agg["episode_length"] = len_sum / max(count, 1.0)
                    agg["param_lag"] = lag_sum / max(frag_count, 1)
                    agg["env_steps"] = self.env_steps
                    agg["fps"] = window_steps / max(elapsed, 1e-9)
                    # Recovery/robustness counters (cumulative), so the
                    # JSONL/TensorBoard record shows WHEN the pipeline
                    # churned: supervisor restarts, actor->learner queue
                    # backpressure, and per-site injected-fault counts.
                    agg["actor_restarts"] = self._actor_restarts
                    agg["server_restarts"] = self._server_restarts
                    if self.config.gateway_port != 0:
                        # Guarded: gateway off leaks zero gateway keys.
                        agg["gateway_restarts"] = self._gateway_restarts
                    agg["queue_backpressure"] = self._backpressure_base + sum(
                        a.backpressure for a in self._actors
                    )
                    # Pipeline metrics: the transfer-overlap story in
                    # numbers, per window (see the accumulator comments
                    # above and docs/ARCHITECTURE.md "Data path & transfer
                    # overlap").
                    agg["h2d_wait_s"] = h2d_wait_s
                    agg["h2d_bytes"] = h2d_bytes
                    agg["learner_stall_frac"] = min(
                        stall_s / max(elapsed, 1e-9), 1.0
                    )
                    if ring is not None:
                        agg["slab_reuse_waits"] = ring.reuse_waits
                    if self._device_queue is not None:
                        # Device-tier twin of slab_reuse_waits: enqueues
                        # that blocked on a pending update's handle (the
                        # drain outran the learner by the queue depth).
                        agg["devq_reuse_waits"] = (
                            self._device_queue.reuse_waits
                        )
                    # Off-policy staleness distribution for the window
                    # (staleness_p50/p95/max/mean, in learner updates) —
                    # the per-fragment lags behind the param_lag mean.
                    # The compile counters (compiles / infer_recompile /
                    # learner_recompile) ride the shared registry drain
                    # in observe_window below, landing next to
                    # infer_coalesce_batch in this same dict.
                    if self._staleness is not None:
                        agg.update(self._staleness.drain())
                    if "nonfinite_skip" in agg:
                        # NaN-guard accounting (rollback armed): the
                        # per-update skip flags fold into ONE cumulative
                        # counter key; the per-update mean the generic
                        # aggregation produced would under-read as a
                        # fraction.
                        self._nonfinite_skips += float(
                            sum(
                                np.sum(m["nonfinite_skip"]) for m in drained
                            )
                        )
                        del agg["nonfinite_skip"]
                        agg["nonfinite_skips"] = self._nonfinite_skips
                    if self._replay is not None:
                        # Replay telemetry (the ISSUE-14 aux): ring fill,
                        # per-sample reuse percentiles + target lag, and
                        # the stall-fraction trend vs the trailing mean
                        # (negative = replay is closing the duty-cycle
                        # gap). target_kl rides the learner metrics into
                        # this same dict. Replay off leaks NONE of these
                        # keys (the introspect=False discipline).
                        agg["replay_fill_frac"] = self._replay.fill_frac()
                        agg.update(self._reuse_window.drain())
                        hist = self._stall_history
                        agg["learner_stall_trend"] = (
                            agg["learner_stall_frac"]
                            - sum(hist) / len(hist)
                            if hist
                            else 0.0
                        )
                        hist.append(agg["learner_stall_frac"])
                    agg.update(self._infer_coalesce_window())
                    agg.update(faults.counters())
                    ret_sum = len_sum = count = lag_sum = 0.0
                    frag_count = 0
                    window_steps = 0
                    stall_s = h2d_wait_s = 0.0
                    h2d_bytes = 0
                    # In-training greedy eval on the log boundary. Actors
                    # keep filling the (bounded) queue during the pause, so
                    # window_start is deliberately NOT reset: the eval's
                    # wall time counts against the next window (an honest
                    # under-report) rather than letting the queue backlog
                    # drain into a shortened window and report fps above
                    # hardware throughput.
                    if (
                        cfg.eval_every > 0
                        # eval_every counts update CALLS (config.py), and a
                        # fused call is K updates — match Anakin's cadence.
                        and self._updates - updates_at_eval
                        >= cfg.eval_every * K
                    ):
                        updates_at_eval = self._updates
                        with trace.span(span_names.LEARNER_EVAL):
                            agg["eval_return"] = self.evaluate(
                                num_episodes=cfg.eval_episodes
                            )
                        self._ckpt.maybe_save_best(
                            self.state, self.env_steps, agg["eval_return"]
                        )
                    # Fleet-shape gauges (registry → window snapshot →
                    # /metrics + timeseries), exported EVEN when
                    # elasticity is off: without them a retired-and-not-
                    # replaced actor is indistinguishable from a quiet
                    # one in the recorded history (the obs-doctor gap).
                    obs_registry.gauge("actors_live").set(
                        float(sum(a.is_alive() for a in self._actors))
                    )
                    obs_registry.gauge("servers_live").set(
                        1.0
                        if self._server is not None and self._server.is_alive()
                        else 0.0
                    )
                    obs_registry.gauge("staging_slabs_live").set(
                        float(ring.num_slabs) if ring is not None else 0.0
                    )
                    if cfg.gateway_port != 0:
                        # Gateway liveness for /healthz and the recorded
                        # history — guarded on the CONFIG (not the
                        # object), so a crash-plus-failed-rebind outage
                        # (self._gateway is None while the supervisor
                        # retries) reads 0.0 instead of freezing at the
                        # last healthy value; gateway-off still leaks
                        # zero gateway keys (the bit-identity contract).
                        obs_registry.gauge("gateway_live").set(
                            1.0
                            if self._gateway is not None
                            and self._gateway.is_alive()
                            else 0.0
                        )
                    # ONE shared window snapshot (obs/__init__.py): the
                    # registry/trace drain merges in here, the health
                    # detectors run, and the time-series store records —
                    # all on THIS dict, so stdout, JSONL, TensorBoard,
                    # /metrics, and timeseries.jsonl can never disagree
                    # on what the window contained. Placed after the
                    # eval so eval_return feeds the regression detector.
                    self._obs.observe_window(agg)
                    # Divergence rollback: evaluated FIRST at window
                    # close — a window the divergence poisoned must not
                    # also drive a fleet-scale decision.
                    remediated = False
                    if self._rollback is not None:
                        remediated = self._rollback_step(
                            agg, slab_groups, fragments
                        )
                    # Elastic runtime: the controller reads the SAME
                    # merged window the sinks saw; a decision reconfigures
                    # the fleet here, between updates, on this thread.
                    if self._elastic is not None and not remediated:
                        self._elastic_step(agg)
                    history.append(agg)
                    if callback:
                        callback(agg)
        finally:
            self.stop()
            # A crash (including the §5.3 actor crash-loop abort) must not
            # lose progress: save final state and flush async writes.
            # (After a completed preemption drain this re-save no-ops on
            # the idempotent same-step rule — the drain already made the
            # final checkpoint durable.)
            self._ckpt.finalize(self.state, self.env_steps)
            # Flush any flight dumps still queued on the writer thread.
            # (The Perfetto export happens ONCE, in close(): exporting
            # per train() call would tax the measured hot path, and
            # crash-time forensics are the flight recorder's job.)
            self._obs.close()
            if drain is not None:
                # Disarm the deadline watchdog on EVERY exit path (a
                # crash racing a signal must not be hard-killed mid-
                # forensics), restore the previous handlers, and drop the
                # scripted-preempt registration.
                drain.finish()
                drain.uninstall()
                durability.clear_active(drain)
        return history

    def save_checkpoint(self) -> None:
        """Save the current LearnerState now (async; see ``Checkpointer``)."""
        self._ckpt.save_now(self.state, self.env_steps)

    def close(self) -> None:
        """Stop actors, flush pending checkpoint saves, release resources."""
        self.stop()
        if self._gateway_backend is not None:
            # Release the serve-stale anchor leases (stop() keeps the
            # backend alive across gateway rebuilds; final teardown is
            # here, after the last possible rebuild).
            self._gateway_backend.close()
            self._gateway_backend = None
        for pool in self._eval_pools.values():
            _close(pool)
        self._eval_pools = {}
        self._ckpt.close()
        # Perfetto export of everything the rings still hold (the whole
        # run's tail, all threads), then the final obs teardown: stop the
        # exposition endpoint, close timeseries.jsonl, flush forensics.
        self._obs.export_trace()
        self._obs.shutdown()

    # ----------------------------------------------------------------- eval

    def evaluate(
        self,
        num_episodes: int = 32,
        max_steps: int | None = None,
        seed: int = 1234,
        return_episodes: bool = False,
    ):
        """Mean greedy-policy return over ``num_episodes`` fresh host envs.

        Each env counts only its FIRST completed episode (pools auto-reset;
        ``pool.reset()`` below starts the fresh episodes).
        ``return_episodes=True`` returns the per-episode return vector
        instead of the mean — the same contract as ``Trainer.evaluate``, so
        per-episode audits (scripts/eval_caps.py) work on host-backend
        checkpoints too (VERDICT r4 Weak #7).
        """
        if max_steps is None:
            # Contain the longest builtin episode (same contract as
            # Trainer.evaluate — shared helper).
            max_steps = default_eval_max_steps(self.config)
        # Eval pools are cached per (num_episodes, seed) for the trainer's
        # lifetime: in-training evals would otherwise rebuild the pool —
        # and, for JaxHostPool, re-jit its env step — every eval period.
        pool_key = (num_episodes, seed)
        pool = self._eval_pools.get(pool_key)
        if pool is None:
            pool = make_host_pool(self.config, num_episodes, seed=seed)
            # Evaluation runs OUTSIDE the supervised pipeline: an injected
            # pool.step fault here would escape evaluate() un-recovered
            # (and consume the site's deterministic RNG/max budget meant
            # for the actor path under test), so eval pools always step
            # unarmed.
            pool.disarm_faults()
            self._eval_pools[pool_key] = pool
        recurrent = is_recurrent(self.model)
        # One jitted greedy fn for the trainer's lifetime (in-training
        # evals would otherwise redefine-and-retrace it every period; jit
        # still specializes per num_episodes batch shape, cached).
        if self._greedy_fn is None:
            dist = distributions.for_config(self.config, self.spec)
            apply_fn = self.model.apply

            if recurrent:

                @jax.jit
                def greedy_rec(params, obs_stats, obs, core, done_prev):
                    napply = normalizing_apply(apply_fn, obs_stats)
                    core = reset_core(core, done_prev)
                    dist_params, _, core = napply(params, obs, core)
                    return dist.mode(dist_params), core

                self._greedy_fn = greedy_rec
            else:

                @jax.jit
                def greedy(params, obs_stats, obs):
                    napply = normalizing_apply(apply_fn, obs_stats)
                    dist_params, _ = napply(params, obs)
                    return dist.mode(dist_params)

                self._greedy_fn = greedy
        greedy_fn = self._greedy_fn

        params = self.state.params
        obs_stats = self.state.obs_stats
        core = self.model.initial_core(num_episodes) if recurrent else None
        done_prev = np.zeros((num_episodes,), bool)
        try:
            obs = pool.reset()
            ep_return = np.zeros((num_episodes,), np.float64)
            finished = np.zeros((num_episodes,), bool)
            final_return = np.zeros((num_episodes,), np.float64)
            for _ in range(max_steps):
                # ONE batched jax.device_get per eval step (np.asarray
                # was a separate blocking sync per leaf — measurably worse
                # on a high-latency device link); the recurrent core stays
                # on device.
                if recurrent:
                    actions_d, core = greedy_fn(
                        params, obs_stats, obs, core, done_prev
                    )
                    actions = jax.device_get(actions_d)
                else:
                    actions = jax.device_get(greedy_fn(params, obs_stats, obs))
                obs, rew, term, trunc = pool.step(actions)
                done_prev = np.logical_or(term, trunc)
                ep_return += np.where(finished, 0.0, rew)
                done = np.logical_or(term, trunc) & ~finished
                final_return = np.where(done, ep_return, final_return)
                finished |= done
                if finished.all():
                    break
            final_return = np.where(finished, final_return, ep_return)
            if return_episodes:
                return final_return.astype(np.float32)
            return float(final_return.mean())
        # lint: broad-except-ok(not a swallow: evicts the broken eval pool from the cache, then re-raises the original failure)
        except BaseException:
            # A broken pool must not be reused; drop it from the cache.
            self._eval_pools.pop(pool_key, None)
            _close(pool)
            raise


def _pool_spec(pool, config: Config):
    """EnvSpec from a host pool: adapters carry one; the native pool exposes
    obs_dim/num_actions; fall back to the registry env's spec."""
    spec = getattr(pool, "spec", None)
    if spec is not None:
        return spec
    from asyncrl_tpu.envs.core import EnvSpec

    return EnvSpec(
        obs_shape=(pool.obs_dim,), num_actions=pool.num_actions
    )


def _close(pool) -> None:
    close = getattr(pool, "close", None)
    if close is not None:
        try:
            close()
        # lint: broad-except-ok(best-effort pool teardown at a supervisor boundary; a failing close must not mask the path that led here)
        except Exception:
            pass
