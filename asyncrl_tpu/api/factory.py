"""``make_agent``: the reference's user-facing factory (BASELINE.json:5;
SURVEY.md §3.4) — config -> assembled agent, with a ``backend`` selection
point. ``backend="tpu"`` is the Anakin in-HBM path; ``backend="sebulba"``
drives host envs against an on-device double buffer; ``backend="cpu_async"``
is the thread-based parity path mirroring the reference's default A3C mode.
"""

from __future__ import annotations

from asyncrl_tpu.utils.config import Config


def make_agent(
    config: Config | None = None, restore: str | None = None, **overrides
):
    """Build a Trainer for ``config``.

    Any Config field can be passed as a keyword override, e.g.::

        agent = make_agent(env_id="CartPole-v1", algo="impala", backend="tpu")
        agent.train()

    ``restore=path`` loads initial state from an existing checkpoint
    directory (read-only; ongoing saves go to ``config.checkpoint_dir``).
    """
    config = (config or Config()).replace(**overrides)

    # Fail fast on enum-like fields the backends only consult at trace time
    # (a bad algo would otherwise surface mid-train, after env/model build).
    if config.algo not in ("a3c", "impala", "ppo", "qlearn"):
        raise ValueError(
            f"unknown algo {config.algo!r}; expected a3c|impala|ppo|qlearn"
        )
    if config.torso not in ("mlp", "nature_cnn", "impala_cnn"):
        raise ValueError(
            f"unknown torso {config.torso!r}; expected "
            "mlp|nature_cnn|impala_cnn"
        )
    if config.core not in ("ff", "lstm"):
        raise ValueError(f"unknown core {config.core!r}; expected ff|lstm")

    if config.backend == "tpu":
        from asyncrl_tpu.api.trainer import Trainer

        return Trainer(config, restore=restore)
    if config.backend == "sebulba":
        from asyncrl_tpu.api.sebulba_trainer import SebulbaTrainer

        return SebulbaTrainer(config, restore=restore)
    if config.backend == "cpu_async":
        try:
            from asyncrl_tpu.api.cpu_async import CpuAsyncTrainer
        except ImportError as e:
            raise NotImplementedError(
                "backend='cpu_async' is not built yet (planned: thread-based "
                "parity path mirroring the reference's A3C mode)"
            ) from e
        return CpuAsyncTrainer(config, restore=restore)
    raise ValueError(
        f"unknown backend {config.backend!r}; expected tpu|sebulba|cpu_async"
    )
