from asyncrl_tpu.api.factory import make_agent
from asyncrl_tpu.api.trainer import Trainer

__all__ = ["Trainer", "make_agent"]
