from asyncrl_tpu.api.factory import make_agent
from asyncrl_tpu.api.population import PopulationTrainer
from asyncrl_tpu.api.trainer import Trainer

__all__ = ["PopulationTrainer", "Trainer", "make_agent"]
