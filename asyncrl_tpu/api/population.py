"""Population training: K independent seeds advancing in ONE fused program.

A capability the reference's thread architecture cannot express: because
the entire act→store→learn cycle is a pure function of ``TrainState``
(learn/learner.py), a *population* of runs is just ``vmap`` over a stacked
state — K complete training runs (distinct params, optimizer state, env
batches, PRNG streams) advance per XLA dispatch, sharing every compiled
kernel. Seed sweeps and hyperparameter-robustness studies that are K
sequential jobs on the reference become one chip-saturating program here.

Composition: the train-step body is built with ``axes=()`` — no collective
touches anything, so members are EXACTLY independent single-device runs
(test-asserted) — then ``vmap`` adds the member axis and ``shard_map``
shards that axis over the mesh's dp axes: each device owns
``pop_size / dp`` members end to end, so scaling the population across a
pod costs zero inter-chip communication.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from asyncrl_tpu.envs.registry import make as make_env
from asyncrl_tpu.learn.learner import (
    TrainState,
    derive_init_keys,
    fuse_updates,
    fused_smap_opts,
    init_params,
    make_optimizer,
    make_train_step,
    validate_grad_accum_config,
    validate_qlearn_config,
    resolve_scan_impl,
    validate_ppo_geometry,
    validate_recurrent_config,
    validate_selfplay_config,
)
from asyncrl_tpu.models.networks import build_model, is_recurrent
from asyncrl_tpu.parallel.mesh import dp_axes, dp_sharded, dp_size, make_mesh, shard_map
from asyncrl_tpu.rollout.anakin import actor_init
from asyncrl_tpu.utils.config import Config


class PopulationTrainer:
    """Train ``pop_size`` independent seeds of one Config simultaneously.

    ``num_envs`` is PER MEMBER. Members are sharded over the mesh's dp
    axes (``pop_size`` must divide evenly); on one device the whole
    population advances in a single fused program.
    """

    def __init__(
        self,
        config: Config,
        pop_size: int,
        mesh=None,
        learning_rates=None,
        restore: str | None = None,
    ):
        """``learning_rates`` (optional, [pop_size]) turns the population
        into a hyperparameter sweep: member i trains with its own learning
        rate. Implemented with ``optax.inject_hyperparams`` — the rate
        lives inside the (vmapped, per-member) optimizer state, so the
        fused program is unchanged; only the init differs. Note this
        breaks the member==standalone-with-seed-base+i equivalence unless
        the standalone uses the matching learning_rate."""
        if pop_size < 1:
            raise ValueError(f"pop_size={pop_size} must be >= 1")
        if learning_rates is not None and len(learning_rates) != pop_size:
            raise ValueError(
                f"learning_rates has {len(learning_rates)} entries for "
                f"pop_size={pop_size}"
            )
        if mesh is not None:
            self.mesh = mesh
        else:
            # Auto-fit: shard members over the most devices that divide the
            # population (a 2-member population on an 8-device host uses 2
            # devices rather than failing the divisibility check).
            n = len(jax.devices())
            d = min(pop_size, n)
            while pop_size % d:
                d -= 1
            self.mesh = make_mesh((d,), ("dp",), devices=jax.devices()[:d])
        config = resolve_scan_impl(config, self.mesh)
        if config.backend != "tpu":
            raise ValueError(
                "population training is Anakin-only (backend='tpu'); "
                f"got {config.backend!r}"
            )
        dp = dp_size(self.mesh)
        if pop_size % dp:
            raise ValueError(
                f"pop_size={pop_size} not divisible by mesh dp={dp}"
            )
        if config.updates_per_call < 1:
            raise ValueError(
                f"updates_per_call={config.updates_per_call} must be >= 1"
            )
        validate_qlearn_config(config)
        self.config = config
        self.pop_size = pop_size
        self.env = make_env(config.env_id, config)
        self.model = build_model(config, self.env.spec)
        # Same eager geometry/consistency validation as Learner.__init__
        # (clearer than a trace-time failure inside the first update).
        # Recurrent members work like recurrent single runs: the core rides
        # the per-member actor state through the vmapped train step.
        # Self-play likewise: each member carries its OWN frozen rival
        # (opponent_params is just another vmapped TrainState leaf) and
        # promotes it on its own update counter — a population of K
        # independent self-play ladders.
        validate_selfplay_config(config, self.env, self.model)
        validate_recurrent_config(config, self.model)
        validate_ppo_geometry(
            config, config.num_envs, "per-member",
            recurrent=is_recurrent(self.model),
        )
        validate_grad_accum_config(config, config.num_envs)
        if learning_rates is None:
            self.optimizer = make_optimizer(config)
            self._member_lrs = None
        else:
            import optax

            if config.lr_schedule != "constant":
                # Validate the schedule string FIRST (make_optimizer raises
                # the precise error for unknown values), so a typo isn't
                # misreported as a feature conflict.
                make_optimizer(config)
                raise NotImplementedError(
                    "per-member learning_rates and lr_schedule are mutually "
                    "exclusive (the injected rate is a constant per member)"
                )
            # Same chain as make_optimizer, but with the base step's rate
            # injected through opt_state so it can differ per member.
            from asyncrl_tpu.learn.learner import base_optimizer

            base, base_kwargs = base_optimizer(config)
            self.optimizer = optax.chain(
                optax.clip_by_global_norm(config.max_grad_norm),
                optax.inject_hyperparams(base)(
                    learning_rate=config.learning_rate, **base_kwargs
                ),
            )
            self._member_lrs = jnp.asarray(learning_rates, jnp.float32)

        # Self-contained body (axes=()) -> K-fused (updates_per_call, the
        # shared fuse_updates wrapper — one host dispatch advances every
        # member K updates; metrics leaves become [pop, K]) -> vmap over
        # members -> shard_map the member axis over dp.
        body = fuse_updates(
            make_train_step(
                config, self.env, self.model.apply, self.optimizer,
                self.mesh, axes=(),
            ),
            config.updates_per_call,
        )
        axes = dp_axes(self.mesh)
        spec = TrainState(
            params=P(axes),
            actor_params=P(axes),
            opt_state=P(axes),
            actor=P(axes),
            update_step=P(axes),
            obs_stats=P(axes),
            ret_stats=P(axes),
            # Unlike the single-run learner (replicated, P()): each member
            # owns a rival, so the member axis shards over dp like params.
            opponent_params=P(axes),
        )
        self._step = jax.jit(
            shard_map(
                jax.vmap(body),
                mesh=self.mesh,
                in_specs=(spec, P(axes)),
                out_specs=(spec, P(axes)),
                **fused_smap_opts(config),
            ),
            donate_argnums=(0,) if config.donate_buffers else (),
        )
        # Per-member seeds: member i must reproduce a standalone run with
        # seed base+i (init AND in-update PRNG streams, e.g. the PPO
        # minibatch shuffle) — asserted by tests/test_population.py.
        self.member_seeds = jnp.arange(
            config.seed, config.seed + pop_size, dtype=jnp.int32
        )
        self._eval_fns: dict[tuple[int, int], Callable] = {}
        self.state = self._place(self._init_population(config.seed))

        # Checkpointing: the stacked population state is one pytree, so the
        # shared trainer wiring handles it unchanged — including
        # auto-resume from checkpoint_dir's latest step after a crash and
        # the ahead-of-history guard (utils/checkpoint.py::setup).
        from asyncrl_tpu.utils import checkpoint as checkpoint_mod

        self._ckpt, state, self._env_steps = checkpoint_mod.setup(
            config, restore, self.state
        )
        self.state = self._place(state)

    def _place(self, state: TrainState) -> TrainState:
        """Commit every leaf to the population sharding (leading member
        axis over the mesh's dp axes) — restored or freshly-built arrays
        otherwise arrive committed to one device, which conflicts with the
        shard_map'd step."""
        return jax.device_put(state, dp_sharded(self.mesh))

    def _member_init(
        self, key: jax.Array, lr: jax.Array | None = None
    ) -> TrainState:
        """Identical state derivation to Learner.init_state (dp=1 case),
        via the shared helpers — see learn.learner.derive_init_keys."""
        cfg = self.config
        pkey, akey = derive_init_keys(key)
        params = init_params(self.model, self.env, pkey)
        opt_state = self.optimizer.init(params)
        if lr is not None:
            # inject_hyperparams keeps the rate in opt_state: the chain's
            # second element carries hyperparams["learning_rate"].
            inject = opt_state[1]
            opt_state = (
                opt_state[0],
                inject._replace(
                    hyperparams={**inject.hyperparams, "learning_rate": lr}
                ),
            )
        # Matches init_state's per-device key derivation at dp=1:
        # split(akey, dp)[device] with dp=1, device=0.
        actor = actor_init(
            self.env, cfg.num_envs, jax.random.split(akey, 1)[0],
            model=self.model, track_returns=cfg.normalize_returns,
            selfplay=cfg.selfplay,
        )
        from asyncrl_tpu.ops.normalize import init_stats

        return TrainState(
            params=params,
            actor_params=params,
            opt_state=opt_state,
            actor=actor,
            update_step=jnp.zeros((), jnp.int32),
            obs_stats=(
                init_stats(self.env.spec.obs_shape)
                if cfg.normalize_obs
                else None
            ),
            ret_stats=init_stats(()) if cfg.normalize_returns else None,
            # Self-play: the member's first rival is its own init snapshot
            # (same derivation as Learner.init_state).
            opponent_params=params if cfg.selfplay else None,
        )

    def _init_population(self, base_seed: int) -> TrainState:
        keys = jnp.stack(
            [jax.random.PRNGKey(base_seed + i) for i in range(self.pop_size)]
        )
        if self._member_lrs is None:
            return jax.jit(jax.vmap(self._member_init))(keys)
        return jax.jit(jax.vmap(self._member_init))(keys, self._member_lrs)

    def update(self) -> dict[str, jax.Array]:
        """Advance every member one CALL (= ``updates_per_call`` fused
        updates); metrics leaves are [pop_size] (or [pop_size, K] when
        K > 1)."""
        self.state, metrics = self._step(self.state, self.member_seeds)
        return metrics

    def evaluate(
        self, num_episodes: int = 32, max_steps: int = 3200, seed: int = 1234
    ) -> np.ndarray:
        """Per-member mean greedy return, ``[pop_size]`` — ONE vmapped
        on-device rollout evaluates the whole population (the ranking the
        reference would get from K sequential eval jobs)."""
        from asyncrl_tpu.api.trainer import make_eval_rollout

        cache_key = (num_episodes, max_steps)
        if cache_key not in self._eval_fns:
            rollout = make_eval_rollout(
                self.config, self.env, self.model, num_episodes, max_steps
            )
            stats_axes = 0 if self.config.normalize_obs else None
            self._eval_fns[cache_key] = jax.jit(
                jax.vmap(rollout, in_axes=(0, stats_axes, None))
            )
        returns = self._eval_fns[cache_key](
            self.state.params,
            self.state.obs_stats,
            jax.random.PRNGKey(seed),
        )
        return np.asarray(returns).mean(axis=1)

    def train(
        self, callback: Callable[[dict], Any] | None = None
    ) -> list[dict]:
        """Run the full budget (``total_env_steps`` PER MEMBER), reporting
        per-member metric vectors every ``log_every`` updates.

        Episode statistics accumulate across the WHOLE window (as in
        Trainer.train): every completed episode since the last report
        counts, so members with long episodes are not spuriously zeroed by
        whichever fragment happened to land on the logging step.
        """
        cfg = self.config
        frames_per_call = (
            cfg.num_envs * cfg.unroll_len * cfg.updates_per_call
        )
        # Run UNTIL the budget is met (ceil), matching Trainer.train's
        # while-loop semantics for budgets that aren't exact multiples.
        num_calls = max(1, -(-cfg.total_env_steps // frames_per_call))
        # Resume: a restored run continues from its recorded env budget.
        start_call = self._env_steps // frames_per_call
        try:
            history = self._train_loop(
                start_call, num_calls, frames_per_call, callback
            )
        finally:
            # Crash path included: flush the final state (no-op without a
            # checkpoint_dir; idempotent when the run is already complete).
            self._ckpt.finalize(self.state, self._env_steps)
        return history

    def _train_loop(
        self, start_call, num_calls, frames_per_call, callback
    ) -> list[dict]:
        cfg = self.config
        history: list[dict] = []
        pending: list[dict] = []
        calls_at_eval = 0
        for step in range(start_call + 1, num_calls + 1):
            pending.append(self.update())
            # Track consumed budget EVERY call (not just at log windows):
            # the crash-path finalize stamps env_steps into the checkpoint,
            # and a stale value would make auto-resume re-run updates.
            self._env_steps = step * frames_per_call
            self._ckpt.after_update(self.state, self._env_steps)
            if step % cfg.log_every == 0 or step == num_calls:
                # One host sync per window, not per update. Fused calls
                # stack a [K] axis behind the member axis: reduce it here
                # (sums/counts add over the K fused updates; everything
                # else averages) so window leaves are [pop] either way.
                drained = [
                    {
                        k: self._reduce_fused(k, np.asarray(v))
                        for k, v in m.items()
                    }
                    for m in pending
                ]
                pending = []
                window = {
                    k: np.mean([m[k] for m in drained], axis=0)
                    for k in drained[0]
                    if not k.endswith("_sum") and k != "episode_count"
                }
                counts = sum(m["episode_count"] for m in drained)
                ret_sum = sum(m["episode_return_sum"] for m in drained)
                len_sum = sum(m["episode_length_sum"] for m in drained)
                safe = np.maximum(counts, 1)
                window["episode_return"] = ret_sum / safe
                window["episode_length"] = len_sum / safe
                window["episode_count"] = counts
                window["env_steps"] = step * frames_per_call
                # Per-member in-training eval on the log boundary; the
                # BEST member's score gates best-slot retention (the
                # population answer to checkpoint_best — VERDICT r2
                # Next #4), with the member index in the slot metadata.
                if (
                    cfg.eval_every > 0
                    and step - calls_at_eval >= cfg.eval_every
                ):
                    calls_at_eval = step
                    ev = self.evaluate(num_episodes=cfg.eval_episodes)
                    window["eval_return"] = ev
                    best = int(np.argmax(ev))
                    self._ckpt.maybe_save_best(
                        self.state,
                        self._env_steps,
                        float(ev[best]),
                        best_member=best,
                    )
                history.append(window)
                if callback is not None:
                    callback(window)
        return history

    @staticmethod
    def _reduce_fused(key: str, v: np.ndarray) -> np.ndarray:
        """Collapse the fused-updates axis ([pop, K] -> [pop])."""
        if v.ndim < 2:
            return v
        if key.endswith("_sum") or key == "episode_count":
            return v.sum(axis=1)
        return v.mean(axis=1)

    def close(self) -> None:
        """Release checkpoint resources (orbax background threads)."""
        self._ckpt.close()

    def member_params(self, i: int):
        """Extract one member's params (e.g. the best seed, for eval)."""
        return jax.tree.map(lambda x: x[i], self.state.params)

    def publish_policies(self, router, prefix: str = "member") -> list[str]:
        """Install every member's CURRENT params into a serve-core policy
        router (asyncrl_tpu/serve/router.py) as ``<prefix>/<i>`` — the
        whole population served from one :class:`~asyncrl_tpu.serve.ServeCore`.
        First call registers; later calls are zero-drain generation swaps
        (in-flight batches finish on the old weights, new dispatches pick
        up the new ones — no serving pause at publish time). Returns the
        policy ids, index-aligned with members."""
        ids = []
        for i in range(self.pop_size):
            policy = f"{prefix}/{i}"
            router.install(policy, self.member_params(i))
            ids.append(policy)
        return ids
