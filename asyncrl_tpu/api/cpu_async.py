"""``backend="cpu_async"``: the thread-based CPU actor-learner parity path.

This mirrors the reference's DEFAULT architecture — N asynchronous CPU actor
workers, each with its own env(s), feeding a learner through a bounded queue
(BASELINE.json:5,7 "4 async CPU actors"; SURVEY.md §3.1) — with every tensor
pinned to the host CPU backend, so it runs identically with or without a TPU
attached. Its purpose (SURVEY.md §7.2 M4): a differential-testing baseline
for ``backend="tpu"``/``"sebulba"`` and a faithful stand-in for the
reference's behavior under matched hyperparameters (§8-Q7).

Architecture notes vs. the reference (reconstructed, SURVEY.md §3.1):
- ``ActorWorker.run`` = the per-thread env-stepping loop filling a
  ``RolloutBuffer`` and putting fragments on the actor→learner queue. Here
  that is exactly ``rollout.sebulba.ActorThread`` (re-exported as
  ``ActorWorker``) over a 1-device CPU pool slice + the explicit
  ``rollout.buffer.RolloutBuffer``.
- The learner is the same ``RolloutLearner.update`` all backends share
  (V-trace/A3C/PPO + Adam), compiled for a 1-device CPU mesh.
- Weight publishing is the ``ParamStore`` swap (the reference's shared-
  weights re-read); classic Hogwild racing is intentionally NOT reproduced —
  a fragment is always produced under one consistent behaviour policy, and
  V-trace corrects the staleness (SURVEY.md §5.2: race-free by construction).
"""

from __future__ import annotations

import jax

from asyncrl_tpu.api.sebulba_trainer import SebulbaTrainer
from asyncrl_tpu.parallel.mesh import make_mesh
from asyncrl_tpu.rollout.buffer import RolloutBuffer  # noqa: F401  (API parity)
from asyncrl_tpu.rollout.sebulba import ActorThread
from asyncrl_tpu.utils.config import Config

# Name parity with the reference's per-thread actor class (BASELINE.json:5).
ActorWorker = ActorThread


class CpuAsyncTrainer(SebulbaTrainer):
    """Thread-based CPU actor-learner trainer (reference parity backend).

    A ``SebulbaTrainer`` whose mesh is pinned to one host-CPU device: learner
    state, compiled update step, and (because params live on the CPU device)
    the actors' batched inference all execute on CPU regardless of what
    accelerator is attached. Everything else — ActorWorker threads, bounded
    queue, ParamStore publishing, supervision (§5.3), checkpointing (§5.4) —
    is the shared host-actor runtime.

    Placement contract: all COMPUTATION is CPU-pinned, which deliberately
    still allows other trainers (e.g. ``backend="tpu"``) in the same process
    — the §8-Q7 differential test runs both side by side. JAX's first device
    query does globally initialize every registered platform, so merely
    constructing this trainer can start (but never compute on) an attached
    accelerator's runtime; a process that must not touch the accelerator at
    all should restrict ``jax.config.update("jax_platforms", "cpu")`` before
    any JAX use, as the CLI does for cpu_async presets.
    """

    def __init__(
        self, config: Config, spec=None, model=None, mesh=None, restore=None
    ):
        cpu = jax.devices("cpu")[0]
        if mesh is None:
            mesh = make_mesh((1,), ("dp",), devices=[cpu])
        # Pin DEFAULT placement to CPU for the whole construction (model
        # init, probe pools, checkpoint restore): no computation — not even
        # a throwaway init later device_put back to host — may land on an
        # attached accelerator (see class docstring placement contract).
        with jax.default_device(cpu):
            super().__init__(
                config, spec=spec, model=model, mesh=mesh, restore=restore
            )
        self._actor_device = cpu

    def train(self, *args, **kwargs):
        with jax.default_device(jax.devices("cpu")[0]):
            return super().train(*args, **kwargs)

    def evaluate(self, *args, **kwargs):
        with jax.default_device(jax.devices("cpu")[0]):
            return super().evaluate(*args, **kwargs)
