"""Shared inference server: ONE device call serving every actor thread.

The podracer/Sebulba architecture dedicates an inference thread so the
accelerator sees one large action-selection batch per env step instead of
one small batch per actor (SURVEY.md §7.3 "host↔device throughput"). With
per-thread inference (the default), T actor threads cost T dispatches per
step; on a high-latency link (the tunneled chip here pays ~8 ms per
dispatch — see bench.py's sync-discipline note) that serializes into the
hot loop T times over. The server coalesces: actor threads submit their
observation slices, a dedicated thread concatenates them, runs the SAME
jitted ``make_inference_fn`` callable once over the combined batch, and
hands each client its slice of the results.

Batching policy: serve once every live client has a request pending, or
after ``max_wait_s`` — whichever comes first. In steady state all actors
block on inference every step, so full batches are the norm; the timeout
only covers clients that are mid-fragment-emit, dead, or restarting.
Partial batches change the call's batch size and recompile once per
distinct size (jit cache keyed on shape) — rare by construction, and
since ISSUE 8 *measured* rather than assumed: the trainer wraps the
shared inference callable in ``obs.introspect.instrument``, so every
distinct batch shape lands in the ``infer_recompile`` counter (exported
next to ``infer_coalesce_batch``) and a ``kind=event`` compile
annotation with static-shape blame in ``timeseries.jsonl``.

Semantics note vs per-thread inference: the server always evaluates under
the LATEST published params, so behaviour params can refresh mid-fragment
(per-thread actors pin params for a whole fragment). The per-step
``behaviour_logp`` recorded with each action remains exact — which is all
V-trace / the ε-greedy Q recording need — and this is precisely the
published-weights semantics of the podracer inference thread.

Client façade: ``server.client(i)`` returns a callable with the exact
``make_inference_fn`` signature (params and key arguments are accepted and
ignored — the server uses the ParamStore and its own key stream), so
``ActorThread`` runs unchanged whether it holds the jitted function or a
server client.

Slab coalescing: clients submit raw HOST arrays (no per-client
``jnp.asarray`` — that was one device transfer per client per round); the
server packs them into a preallocated host batch slab and the jitted call
transfers the whole slab ONCE per round. Device-resident request leaves
(the recurrent core on an accelerator) still concatenate on device — they
never round-trip through the host. Results slice on host: actions/logp
are numpy row-slices, and on a CPU-backed server (cpu_async) the core
slices are numpy VIEWS of the device buffer too — no copy-through-device
per client (the ``_slice`` fix). ``coalesce_rounds``/``coalesce_rows``
feed the ``infer_coalesce_batch`` metric.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from asyncrl_tpu.obs import spans as span_names
from asyncrl_tpu.obs import trace
from asyncrl_tpu.utils import faults


class ServerClosed(RuntimeError):
    """Raised into clients when the server stops while they wait."""


class InvariantViolation(RuntimeError):
    """§5.2b debug-mode failure: the serve/consume handshake discipline is
    broken. FATAL — kills the server thread and surfaces to every client;
    never downgraded to a per-request error (a transport-integrity bug must
    abort the run, not feed the actor-restart loop)."""


def _on_cpu(tree) -> bool:
    """True when every device leaf of ``tree`` lives on a CPU device (the
    cpu_async host-pinned server). Numpy leaves count as CPU."""
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, np.ndarray):
            continue
        try:
            if any(d.platform != "cpu" for d in leaf.devices()):
                return False
        except AttributeError:
            return False
    return True


def _slice(tree, start, stop):
    """Row-slice every leaf. Numpy leaves give zero-copy views; device
    leaves give device-side slices (small, and they stay resident for the
    client's next submit)."""
    return jax.tree.map(lambda x: x[start:stop], tree)


def pack_rows(slabs: dict, key, parts, total_rows: int) -> np.ndarray:
    """Copy ``parts`` back-to-back into the slab registered under ``key``
    in ``slabs``; returns the ``[total_rows, ...]`` view. The slab grows to
    the largest (rows, tail-shape, dtype) seen and is then reused forever —
    steady state allocates nothing. Shared by the InferenceServer (keys are
    leaf positions) and the serve core (keys are (policy, position) pairs,
    so policies with different request shapes never thrash one slab)."""
    tail, dtype = parts[0].shape[1:], parts[0].dtype
    slab = slabs.get(key)
    if (
        slab is None
        or slab.shape[0] < total_rows
        or slab.shape[1:] != tail
        or slab.dtype != dtype
    ):
        slab = np.empty((total_rows, *tail), dtype)
        slabs[key] = slab
    offset = 0
    for part in parts:
        n = part.shape[0]
        np.copyto(slab[offset:offset + n], part)
        offset += n
    return slab[:total_rows]


def coalesce_args(slabs: dict, key_prefix, args_list, total_rows: int):
    """Merge per-client request pytrees into one batch pytree.

    Host (numpy) leaves pack into the caller's preallocated slabs — a host
    memcpy per client, then ONE device transfer of the slab when the jitted
    call consumes it. Device-resident leaves (the recurrent core on an
    accelerator) concatenate on device; bouncing them through the host
    would add a D2H sync per round."""
    flats = [jax.tree.flatten(args)[0] for args in args_list]
    treedef = jax.tree.structure(args_list[0])
    merged = []
    for pos in range(len(flats[0])):
        parts = [flat[pos] for flat in flats]
        if all(isinstance(p, np.ndarray) for p in parts):
            merged.append(
                pack_rows(slabs, (key_prefix, pos), parts, total_rows)
            )
        else:
            merged.append(jnp.concatenate(parts, axis=0))
    return jax.tree.unflatten(treedef, merged)


class InferenceServer(threading.Thread):
    """Coalesces actor-thread inference requests into one batched call.

    ``mode`` names the wrapped callable's signature (the four
    ``make_inference_fn`` variants):

    - ``"ff"``:      (params, obs, key)                    -> (a, logp, key)
    - ``"eps"``:     (params, obs, key, eps)               -> (a, logp, key)
    - ``"rec"``:     (params, obs, key, core, done)        -> (..., core)
    - ``"rec_eps"``: (params, obs, key, core, done, eps)   -> (..., core)
    """

    MODES = ("ff", "eps", "rec", "rec_eps")

    def __init__(
        self,
        inference_fn: Callable,
        store,
        num_clients: int,
        stop_event: threading.Event,
        mode: str = "ff",
        seed: int = 0,
        max_wait_s: float = 0.002,
        device=None,
    ):
        super().__init__(name="inference-server", daemon=True)
        if mode not in self.MODES:
            raise ValueError(f"unknown mode {mode!r}; expected {self.MODES}")
        if num_clients < 1:
            raise ValueError(f"num_clients must be >= 1, got {num_clients}")
        self._fn = inference_fn
        self._store = store
        self._n = num_clients
        self._stop_event = stop_event
        self._mode = mode
        self._max_wait = max_wait_s
        # ``jax.default_device`` is thread-local (same constraint as
        # ActorThread.device): cpu_async pins the server to host CPU so its
        # concat/dispatch cannot land on an attached accelerator.
        self._device = device
        self._key = jax.random.PRNGKey(seed ^ 0x5E21EA)
        self._cond = threading.Condition()
        self._pending: list[Any] = [None] * num_clients  # guarded-by: _cond
        # Result/error slots are event-handshake-owned, not lock-guarded:
        # the server owns slot i from collect to event.set(), the client
        # owns it from its wait() returning to the consuming swap.
        # lint: thread-shared-ok(event handshake: Event.set/wait is the ownership hand-off; §5.2b debug mode asserts the discipline)
        self._results: list[Any] = [None] * num_clients
        # lint: thread-shared-ok(event handshake, same protocol as _results)
        self._errors: list[BaseException | None] = [None] * num_clients
        self._events = [threading.Event() for _ in range(num_clients)]
        from asyncrl_tpu.utils.debug import sync_debug_enabled

        # §5.2b debug mode: a result slot must be EMPTY when served (a
        # non-empty slot means a double-serve or an unconsumed reply —
        # the handshake discipline is broken).
        self._debug = sync_debug_enabled()
        # The exception that killed the server thread, whatever its type:
        # clients re-raise the REAL cause from _submit instead of a bland
        # ServerClosed, and the trainer's supervisor reads it to decide
        # abort (InvariantViolation) vs rebuild (anything else).
        # lint: thread-shared-ok(single-writer latch: only the dying server thread writes; readers re-read after is_alive() turns false)
        self._fatal: BaseException | None = None
        # Progress stamp for the trainer's heartbeat watchdog (refreshed
        # every collect/serve loop iteration).
        # lint: thread-shared-ok(GIL-atomic float stamp; the watchdog reads staleness only)
        self.heartbeat = time.monotonic()
        self._fault_serve = faults.site("server.serve")
        # Preallocated host batch slabs, one per flattened request-leaf
        # position (grown to the largest batch seen); server-thread-only.
        self._slabs: dict[Any, np.ndarray] = {}
        # Coalescing counters for the infer_coalesce_batch metric: total
        # served rounds and total request rows (plain ints under the GIL;
        # the trainer only reads them).
        self.coalesce_rounds = 0  # lint: thread-shared-ok(GIL-atomic int; single-writer, metrics-only reader)
        self.coalesce_rows = 0  # lint: thread-shared-ok(GIL-atomic int; single-writer, metrics-only reader)

    # ------------------------------------------------------------- client

    def client(self, index: int) -> Callable:
        """A drop-in replacement for the jitted inference callable (same
        signature per ``mode``; params/key arguments are ignored)."""
        if not 0 <= index < self._n:
            raise IndexError(f"client index {index} out of range 0..{self._n - 1}")

        def call(params, obs, key, *rest):
            del params  # server reads the ParamStore
            # Host arrays pass through untouched — the server packs them
            # into its batch slab for ONE transfer per round (a client-side
            # jnp.asarray here would be a per-client device transfer).
            out = self._submit(index, (np.asarray(obs), *rest))
            if self._mode in ("rec", "rec_eps"):
                actions, logp, core = out
                return actions, logp, key, core
            actions, logp = out
            return actions, logp, key

        return call

    def _submit(self, index: int, args):  # thread-entry: infer-client@actor
        event = self._events[index]
        event.clear()
        with self._cond:
            self._pending[index] = args
            self._cond.notify_all()
        while not event.wait(timeout=0.2):
            if self._stop_event.is_set() or not self.is_alive():
                if self._fatal is not None:
                    raise self._fatal
                raise ServerClosed("inference server stopped")
        if self._fatal is not None:
            # Integrity violation: no slot content can be trusted anymore
            # (including a stale result that was about to be consumed).
            raise self._fatal
        err = self._errors[index]
        if err is not None:
            self._errors[index] = None
            raise err
        result, self._results[index] = self._results[index], None
        if result is None:
            # The event can also fire from run()'s shutdown wakeup with
            # neither a result nor an error written (stop raced our wait).
            if self._fatal is not None:
                raise self._fatal
            raise ServerClosed("inference server stopped")
        return result

    # ------------------------------------------------------------- server

    def run(self) -> None:  # thread-entry: infer-server@server
        try:
            if self._device is not None:
                with jax.default_device(self._device):
                    self._run()
            else:
                self._run()
        # lint: broad-except-ok(thread boundary: the cause is latched in _fatal and re-raised into every client; see below)
        except BaseException as e:
            # Fatal: remember why the server died so every subsequent
            # client call re-raises the REAL cause (not a bland
            # ServerClosed) — an InvariantViolation aborts the run, any
            # other death lets the trainer's supervisor rebuild the server
            # and re-wire clients. The exception is NOT re-raised out of
            # the thread: delivery to clients is the contract, and an
            # escaping thread exception would only feed Python's
            # unhandled-thread hook (and, under pytest, a warning that can
            # mask a REAL stray thread crash in the same run — VERDICT r2
            # Weak #5). Log it instead.
            self._fatal = e
            print(
                f"InferenceServer: fatal {type(e).__name__}: {e}",
                file=sys.stderr,
            )
        finally:
            # Wake anyone still waiting so they observe the closed server.
            for event in self._events:
                event.set()

    def _run(self) -> None:
        while not self._stop_event.is_set():
            self.heartbeat = time.monotonic()
            with trace.span(span_names.SERVER_COLLECT_WAIT):
                batch = self._collect()
            if batch:
                if self._fault_serve is not None:
                    # Outside _serve's per-request try: an injected crash
                    # kills the SERVER (recorded in _fatal, recovered by
                    # the trainer's rebuild), not just one batch.
                    self._fault_serve.fire(stop=self._stop_event.is_set)
                with trace.span(span_names.SERVER_SERVE):
                    self._serve(batch)

    def _collect(self):
        """Wait for requests; return [(client_index, args), ...] in index
        order, clearing the pending slots."""
        with self._cond:
            self._cond.wait_for(
                lambda: self._stop_event.is_set()
                or any(p is not None for p in self._pending),
                timeout=0.1,
            )
            if self._stop_event.is_set():
                return []
            deadline = time.monotonic() + self._max_wait
            while any(p is None for p in self._pending):
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop_event.is_set():
                    break
                self._cond.wait_for(
                    lambda: self._stop_event.is_set()
                    or all(p is not None for p in self._pending),
                    timeout=remaining,
                )
            batch = [
                (i, p) for i, p in enumerate(self._pending) if p is not None
            ]
            for i, _ in batch:
                self._pending[i] = None
            return batch

    def _coalesce(self, args_list, total_rows: int):
        """Merge per-client request pytrees into one batch pytree (the
        shared :func:`coalesce_args`; this server's slabs are keyed on
        leaf position alone — one client population, one shape family)."""
        return coalesce_args(self._slabs, None, args_list, total_rows)

    def _serve(self, batch) -> None:
        if self._debug:
            # Checked for the WHOLE batch before any slot is written, so a
            # violation can't poison already-served clients; raised outside
            # the per-request try so it escalates (fatal) instead of being
            # delivered as an ordinary per-client error.
            occupied = [i for i, _ in batch if self._results[i] is not None]
            if occupied:
                raise InvariantViolation(
                    f"inference-server handshake invariant broken: result "
                    f"slot(s) {occupied} served while occupied"
                )
        indices = [i for i, _ in batch]
        try:
            sizes = [int(args[0].shape[0]) for _, args in batch]
            merged = self._coalesce([args for _, args in batch], sum(sizes))
            params, _ = self._store.get()
            out = self._fn(params, merged[0], self._key, *merged[1:])
            if self._mode in ("rec", "rec_eps"):
                actions, logp, self._key, core = out
            else:
                actions, logp, self._key = out
                core = None

            offsets = np.cumsum([0] + sizes)
            # This blocks until the batched call finishes — which also
            # means the input slabs are consumed and safe to overwrite at
            # the next round's pack.
            actions = np.asarray(actions)
            logp = np.asarray(logp)
            if core is not None and _on_cpu(core):
                # cpu_async bugfix: a host-pinned server must hand back
                # numpy VIEWS (np.asarray of a CPU jax array is zero-copy),
                # not per-client device-sliced arrays — the old path paid
                # one device slice op per client per round.
                core = jax.tree.map(np.asarray, core)
            self.coalesce_rounds += 1
            self.coalesce_rows += int(offsets[-1])
            for (i, _), a, b in zip(batch, offsets[:-1], offsets[1:]):
                if core is None:
                    self._results[i] = (actions[a:b], logp[a:b])
                else:
                    self._results[i] = (
                        actions[a:b], logp[a:b], _slice(core, a, b)
                    )
                self._events[i].set()
        # lint: broad-except-ok(per-request boundary: the failure is delivered to every waiting client, then the server keeps serving)
        except BaseException as e:
            for i in indices:
                self._errors[i] = e
                self._events[i].set()
