"""Anakin rollout: envs resident in HBM, unrolled with ``vmap`` + ``lax.scan``.

This is the TPU-native replacement for the reference's per-thread
``ActorWorker.run`` loop (BASELINE.json:5): instead of N Python threads each
stepping one env, a single XLA program steps B envs in lockstep for T steps.
The policy forward, action sample, env physics, auto-reset, and trajectory
write all fuse into one compiled scan — zero host round-trips per fragment.

PRNG design: every env slot carries its own raw uint32 key ([B, 2]), so the
whole ``ActorState`` pytree shards over the mesh's ``dp`` axis with a single
``P('dp')`` prefix spec — no replicated-key divergence problems inside
``shard_map`` (SURVEY.md §7.3 "mesh-size-agnostic").
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import struct

from asyncrl_tpu.envs.core import Environment
from asyncrl_tpu.models.networks import is_recurrent, reset_core
from asyncrl_tpu.rollout.buffer import EpisodeStats, Rollout


@struct.dataclass
class ActorState:
    """Carry for the rollout scan: env states + current obs + per-env PRNG
    keys + running per-env episode accumulators (device-resident metrics).
    ``core`` is the policy's recurrent (c, h) carry for LSTM agents — None
    for feed-forward policies (an empty pytree subtree, so all partition
    specs apply unchanged)."""

    env_state: Any  # vmapped env-state pytree, leading dim B
    obs: jax.Array  # [B, *obs_shape]
    keys: jax.Array  # [B, 2] uint32 raw PRNG keys
    running_return: jax.Array  # [B] f32
    running_length: jax.Array  # [B] f32
    # Per-env DISCOUNTED return accumulator (G = discount*G + r, reset at
    # done) — the statistic behind normalize_returns reward scaling
    # (VecNormalize/Brax recipe). None (empty subtree) unless tracking:
    # an always-present leaf would break restore of checkpoints saved
    # before the field existed, even with the feature off.
    disc_return: Any = None  # [B] f32 when tracking
    core: Any = None  # recurrent policy carry, leading dim B
    # Frozen-rival recurrent carry (selfplay x lstm): the opponent snapshot
    # plays through its OWN (c, h), reset at episode ends like the agent's
    # and zeroed on ladder promotion (the old carry means nothing to the
    # newly frozen params). None unless both selfplay and recurrent — the
    # empty-subtree trick keeps old checkpoints restorable, like
    # disc_return above.
    opp_core: Any = None


def actor_init(
    env: Environment,
    num_envs: int,
    seed_key: jax.Array,
    model=None,
    track_returns: bool = False,
    selfplay: bool = False,
) -> ActorState:
    init_keys, carry_keys = jax.random.split(seed_key)
    env_keys = jax.random.split(init_keys, num_envs)
    env_state = jax.vmap(env.init)(env_keys)
    obs = jax.vmap(env.observe)(env_state)
    zeros = jnp.zeros((num_envs,), jnp.float32)
    core = (
        model.initial_core(num_envs)
        if model is not None and is_recurrent(model)
        else None
    )
    return ActorState(
        env_state=env_state,
        obs=obs,
        keys=jax.random.split(carry_keys, num_envs),
        running_return=zeros,
        running_length=zeros,
        disc_return=zeros if track_returns else None,
        core=core,
        opp_core=core if selfplay and core is not None else None,
    )


def unroll(
    apply_fn: Callable[[Any, jax.Array], tuple[jax.Array, jax.Array]],
    params: Any,
    env: Environment,
    actor_state: ActorState,
    unroll_len: int,
    dist=None,
    reward_scale: float = 1.0,
    step_cost: float = 0.0,
    dist_extra: jax.Array | None = None,
    return_discount: float = 0.0,
    opponent_params: Any = None,
) -> tuple[ActorState, Rollout, EpisodeStats]:
    """Roll the policy forward ``unroll_len`` steps over the env batch.

    ``apply_fn(params, obs[B]) -> (dist_params[B, P], value[B])``. The value
    head output is discarded here (the learner recomputes values under its
    own params); only the behaviour log-prob is recorded — exactly what
    V-trace needs (SURVEY.md §3.3). ``dist`` (ops.distributions) interprets
    the policy head; defaults to the spec's distribution.

    ``dist_extra`` ([B, E], optional) is concatenated onto the model's
    dist_params at every step — the channel for per-env, training-schedule-
    dependent behaviour knobs the frozen ``dist`` object can't carry (the
    Q-learning family's annealed per-env ε rides here, constant across the
    fragment).

    The discounted-return stream ``G_t = return_discount * G_{t-1} + r_t``
    (reset at episode ends; built from the learner's SCALED reward view)
    records into ``rollout.disc_returns`` whenever the actor state tracks
    it (``actor_init(track_returns=True)``) — ONE predicate, shared with
    the learner's stats fold, so the carry, the stream, and the consumer
    cannot disagree (a ``return_discount`` of 0 degrades to reward-std
    tracking rather than crashing).

    ``opponent_params`` (self-play, Config.selfplay): the env must be a
    duel env (``observe_opponent`` + ``step_duel``); each step the SAME
    ``apply_fn`` evaluates the frozen opponent snapshot on the mirrored
    observation and its sampled action drives the rival paddle. The
    fragment records only the AGENT's side (actions/logp/rewards), so
    every learner consumes it unchanged. When None (the default), the
    PRNG stream and the compiled program are bit-identical to before the
    feature existed.
    """
    if dist is None:
        from asyncrl_tpu.ops import distributions

        dist = distributions.for_spec(env.spec)

    recurrent = actor_state.core is not None
    track_returns = actor_state.disc_return is not None

    selfplay = opponent_params is not None

    def step_fn(carry: ActorState, _):
        n_keys = 4 if selfplay else 3
        split = jax.vmap(lambda k: jax.random.split(k, n_keys))(carry.keys)
        next_keys, act_keys, step_keys = split[:, 0], split[:, 1], split[:, 2]

        if recurrent:
            dist_params, _, core = apply_fn(params, carry.obs, carry.core)
        else:
            dist_params, _ = apply_fn(params, carry.obs)
            core = None
        if dist_extra is not None:
            dist_params = jnp.concatenate(
                [dist_params, dist_extra.astype(dist_params.dtype)], axis=-1
            )
        actions = jax.vmap(dist.sample)(act_keys, dist_params)
        behaviour_logp = dist.logp(dist_params, actions)

        if selfplay:
            opp_obs = jax.vmap(env.observe_opponent)(carry.env_state)
            if carry.opp_core is not None:
                opp_dist_params, _, opp_core = apply_fn(
                    opponent_params, opp_obs, carry.opp_core
                )
            else:
                opp_dist_params, _ = apply_fn(opponent_params, opp_obs)
                opp_core = None
            if dist_extra is not None:
                # The rival samples under the SAME behaviour knobs as the
                # agent (e.g. the Q-family's annealed ε) — without this, an
                # EpsilonGreedy dist would default the opponent to ε=0 and
                # the frozen snapshot would play deterministic argmax.
                opp_dist_params = jnp.concatenate(
                    [
                        opp_dist_params,
                        dist_extra.astype(opp_dist_params.dtype),
                    ],
                    axis=-1,
                )
            opp_actions = jax.vmap(dist.sample)(split[:, 3], opp_dist_params)
            env_state, ts = jax.vmap(env.step_duel)(
                carry.env_state, actions, opp_actions, step_keys
            )
        else:
            env_state, ts = jax.vmap(env.step)(
                carry.env_state, actions, step_keys
            )
            opp_core = None

        if recurrent:
            core = reset_core(core, ts.done)
            if opp_core is not None:
                opp_core = reset_core(opp_core, ts.done)

        done_f = ts.done.astype(jnp.float32)
        ep_return = carry.running_return + ts.reward
        ep_length = carry.running_length + 1.0
        # Discounted-return stream for reward normalization (scaled view).
        learner_reward = (ts.reward - step_cost) * reward_scale
        # The return-std stream deliberately EXCLUDES step_cost (scaled raw
        # rewards only): the host backends' actor-built streams cannot
        # reconstruct the cost's time-since-reset-dependent offset, so both
        # paths track the same cost-free stream and stay comparable; the
        # constant living cost is not what return normalization exists to
        # equalize anyway.
        g = (
            carry.disc_return * return_discount + ts.reward * reward_scale
            if track_returns
            else None
        )
        new_carry = ActorState(
            env_state=env_state,
            obs=ts.obs,
            keys=next_keys,
            running_return=ep_return * (1.0 - done_f),
            running_length=ep_length * (1.0 - done_f),
            disc_return=g * (1.0 - done_f) if track_returns else None,
            core=core,
            opp_core=opp_core,
        )
        out = (
            carry.obs,
            actions,
            behaviour_logp,
            learner_reward,  # learner's view (cost + scale); metrics stay raw
            ts.terminated,
            ts.truncated,
            ep_return * done_f,
            ep_length * done_f,
            done_f,
            g,
        )
        return new_carry, out

    final_state, outs = jax.lax.scan(step_fn, actor_state, None, length=unroll_len)
    (obs, actions, behaviour_logp, rewards, terminated, truncated,
     done_returns, done_lengths, dones, disc_returns) = outs

    rollout = Rollout(
        obs=obs,
        actions=actions,
        behaviour_logp=behaviour_logp,
        rewards=rewards,
        terminated=terminated,
        truncated=truncated,
        bootstrap_obs=final_state.obs,
        # Fragment-initial recurrent carry (behaviour policy's), for the
        # learner's re-forward — the IMPALA "stale core state" recipe.
        init_core=actor_state.core,
        disc_returns=disc_returns,
    )
    stats = EpisodeStats(
        completed_return_sum=jnp.sum(done_returns),
        completed_length_sum=jnp.sum(done_lengths),
        completed_count=jnp.sum(dones),
    )
    return final_state, rollout, stats
