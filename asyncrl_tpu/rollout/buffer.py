"""Rollout fragment storage.

The reference's ``RolloutBuffer`` is a Python object actors append to
step-by-step (BASELINE.json:5; SURVEY.md §2). TPU-native, the buffer is just
the stacked output pytree of a ``lax.scan`` — time-major [T, B, ...] arrays
produced in one XLA program, with no per-step Python. The same struct is the
unit carried by the Sebulba double buffer and by the ``cpu_async`` backend's
queue, so all three backends feed an identical learner.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class Rollout:
    """One rollout fragment, time-major [T, B, ...].

    ``obs[t]`` is the observation the policy saw when choosing ``actions[t]``;
    ``bootstrap_obs`` is the observation after the final transition, used for
    V(x_T) bootstrapping. ``behaviour_logp`` is recorded at action time for
    V-trace / PPO ratios (BASELINE.json:5).
    """

    obs: jax.Array  # [T, B, *obs_shape]
    actions: jax.Array  # [T, B] int32 (discrete) | [T, B, D] f32 (continuous)
    behaviour_logp: jax.Array  # [T, B] float32
    rewards: jax.Array  # [T, B] float32
    terminated: jax.Array  # [T, B] bool
    truncated: jax.Array  # [T, B] bool
    bootstrap_obs: jax.Array  # [B, *obs_shape]
    # Recurrent policies only: the (c, h) carry at the fragment's first step
    # (behaviour policy's), used by the learner to re-forward the fragment.
    # None (empty subtree) for feed-forward policies.
    init_core: Any = None
    # Per-step discounted-return stream [T, B] for reward normalization
    # (rollout.anakin.unroll with return_discount > 0); None otherwise
    # (host fragments, or the feature disabled).
    disc_returns: Any = None

    @property
    def done(self) -> jax.Array:
        return jnp.logical_or(self.terminated, self.truncated)

    def discounts(self, gamma: float) -> jax.Array:
        """gamma * (1 - done): cuts bootstrap at episode ends.

        Truncated episodes are treated like terminated ones (no bootstrap
        through the reset boundary) — the standard Anakin simplification; the
        exact truncation-bootstrap correction (add gamma*V(last_obs) to the
        reward at truncated steps) is a possible future option and would need
        one extra forward pass.
        """
        return gamma * (1.0 - self.done.astype(jnp.float32))

    @property
    def num_steps(self) -> int:
        return self.actions.shape[0] * self.actions.shape[1]


class RolloutBuffer:
    """Host-side fixed-length fragment buffer actors append to step-by-step —
    direct parity with the reference's ``RolloutBuffer`` (BASELINE.json:5;
    SURVEY.md §2). Used by the ``sebulba`` and ``cpu_async`` host-actor
    backends; the Anakin path needs no host buffer (the scan's stacked
    outputs ARE the fragment).

    Reusable: numpy storage is allocated once (action storage lazily, on the
    first append, when dtype/shape are known) and overwritten each fragment;
    ``emit`` copies, so fragments are safe to retain after ``reset``.

    Slab mode (``storage=``): the buffer writes into CALLER-OWNED arrays —
    one staging-slab row's views (rollout/staging.py) — and ``emit``
    becomes a zero-copy pointer hand-off: the emitted ``Rollout`` shares
    the storage, and the staging ring's lease protocol (not a copy) is
    what makes retaining it safe. ``guard`` (if given) runs before every
    append so a voided lease fails the write instead of scribbling on a
    re-leased row.
    """

    def __init__(
        self,
        unroll_len: int,
        num_envs: int,
        obs_shape,
        obs_dtype,
        track_returns: bool = False,
        storage: "Rollout | None" = None,
        guard=None,
    ):
        T, B = unroll_len, num_envs
        self.unroll_len = T
        self.num_envs = B
        self._guard = guard
        if storage is not None:
            if track_returns != (storage.disc_returns is not None):
                raise ValueError(
                    "storage disc_returns presence must match track_returns"
                )
            self.obs = storage.obs
            self.behaviour_logp = storage.behaviour_logp
            self.rewards = storage.rewards
            self.terminated = storage.terminated
            self.truncated = storage.truncated
            self.disc_returns = storage.disc_returns
            self.actions = storage.actions
            self._bootstrap = storage.bootstrap_obs
            self._t = 0
            return
        self._bootstrap = None
        self.obs = np.empty((T, B, *obs_shape), obs_dtype)
        self.behaviour_logp = np.empty((T, B), np.float32)
        self.rewards = np.empty((T, B), np.float32)
        self.terminated = np.empty((T, B), bool)
        self.truncated = np.empty((T, B), bool)
        # Per-step discounted-return stream for reward normalization
        # (mirrors rollout.anakin's disc_returns); None unless tracked.
        self.disc_returns = (
            np.empty((T, B), np.float32) if track_returns else None
        )
        self.actions: np.ndarray | None = None
        self._t = 0

    def __len__(self) -> int:
        return self._t

    @property
    def full(self) -> bool:
        return self._t == self.unroll_len

    def append(
        self, obs, action, logp, reward, terminated, truncated,
        disc_return=None,
    ) -> None:
        """Record one transition: ``obs`` is what the policy saw choosing
        ``action``; reward/terminated/truncated describe the step outcome.
        ``disc_return`` is required exactly when the buffer tracks the
        discounted-return stream."""
        if self._guard is not None:
            self._guard()
        t = self._t
        if t >= self.unroll_len:
            raise IndexError(f"buffer full at t={t}; call emit()/reset()")
        if (disc_return is None) != (self.disc_returns is None):
            raise ValueError(
                "disc_return must be passed iff the buffer was built with "
                "track_returns=True"
            )
        action = np.asarray(action)
        if self.actions is None:
            self.actions = np.empty(
                (self.unroll_len, self.num_envs, *action.shape[1:]),
                action.dtype,
            )
        self.obs[t] = obs
        self.actions[t] = action
        self.behaviour_logp[t] = logp
        self.rewards[t] = reward
        self.terminated[t] = terminated
        self.truncated[t] = truncated
        if self.disc_returns is not None:
            self.disc_returns[t] = disc_return
        self._t = t + 1

    def emit(self, bootstrap_obs) -> Rollout:
        """Emit the completed fragment and reset for the next one: a copy
        when the buffer owns its storage, a zero-copy view hand-off in
        slab mode (the staging lease gates reuse instead)."""
        if not self.full:
            raise ValueError(
                f"fragment incomplete: {self._t}/{self.unroll_len} steps"
            )
        if self._bootstrap is not None:
            # Slab mode: emit WRITES the row (bootstrap_obs), so it must
            # re-validate the lease like every append — a zombie actor
            # voided mid-emit would otherwise scribble a full [B, obs]
            # array over the replacement's committed row (static-analysis
            # era review finding; append/write_init_core already guard).
            if self._guard is not None:
                self._guard()
            np.copyto(self._bootstrap, np.asarray(bootstrap_obs))
            rollout = Rollout(
                obs=self.obs,
                actions=self.actions,
                behaviour_logp=self.behaviour_logp,
                rewards=self.rewards,
                terminated=self.terminated,
                truncated=self.truncated,
                bootstrap_obs=self._bootstrap,
                disc_returns=self.disc_returns,
            )
            self._t = 0
            return rollout
        rollout = Rollout(
            obs=self.obs.copy(),
            actions=self.actions.copy(),
            behaviour_logp=self.behaviour_logp.copy(),
            rewards=self.rewards.copy(),
            terminated=self.terminated.copy(),
            truncated=self.truncated.copy(),
            bootstrap_obs=np.asarray(bootstrap_obs).copy(),
            disc_returns=(
                None if self.disc_returns is None else self.disc_returns.copy()
            ),
        )
        self._t = 0
        return rollout

    def reset(self) -> None:
        self._t = 0


@struct.dataclass
class EpisodeStats:
    """Streaming episode-return/length statistics, computed inside jit.

    ``completed_*`` are per-fragment sums over episodes that finished during
    the fragment; divide by ``completed_count`` host-side (guard zero).
    """

    completed_return_sum: jax.Array  # scalar f32
    completed_length_sum: jax.Array  # scalar f32
    completed_count: jax.Array  # scalar f32
