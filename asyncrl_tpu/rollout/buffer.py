"""Rollout fragment storage.

The reference's ``RolloutBuffer`` is a Python object actors append to
step-by-step (BASELINE.json:5; SURVEY.md §2). TPU-native, the buffer is just
the stacked output pytree of a ``lax.scan`` — time-major [T, B, ...] arrays
produced in one XLA program, with no per-step Python. The same struct is the
unit carried by the Sebulba double buffer and by the ``cpu_async`` backend's
queue, so all three backends feed an identical learner.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class Rollout:
    """One rollout fragment, time-major [T, B, ...].

    ``obs[t]`` is the observation the policy saw when choosing ``actions[t]``;
    ``bootstrap_obs`` is the observation after the final transition, used for
    V(x_T) bootstrapping. ``behaviour_logp`` is recorded at action time for
    V-trace / PPO ratios (BASELINE.json:5).
    """

    obs: jax.Array  # [T, B, *obs_shape]
    actions: jax.Array  # [T, B] int32 (discrete) | [T, B, D] f32 (continuous)
    behaviour_logp: jax.Array  # [T, B] float32
    rewards: jax.Array  # [T, B] float32
    terminated: jax.Array  # [T, B] bool
    truncated: jax.Array  # [T, B] bool
    bootstrap_obs: jax.Array  # [B, *obs_shape]

    @property
    def done(self) -> jax.Array:
        return jnp.logical_or(self.terminated, self.truncated)

    def discounts(self, gamma: float) -> jax.Array:
        """gamma * (1 - done): cuts bootstrap at episode ends.

        Truncated episodes are treated like terminated ones (no bootstrap
        through the reset boundary) — the standard Anakin simplification; the
        exact truncation-bootstrap correction (add gamma*V(last_obs) to the
        reward at truncated steps) is a possible future option and would need
        one extra forward pass.
        """
        return gamma * (1.0 - self.done.astype(jnp.float32))

    @property
    def num_steps(self) -> int:
        return self.actions.shape[0] * self.actions.shape[1]


@struct.dataclass
class EpisodeStats:
    """Streaming episode-return/length statistics, computed inside jit.

    ``completed_*`` are per-fragment sums over episodes that finished during
    the fragment; divide by ``completed_count`` host-side (guard zero).
    """

    completed_return_sum: jax.Array  # scalar f32
    completed_length_sum: jax.Array  # scalar f32
    completed_count: jax.Array  # scalar f32
