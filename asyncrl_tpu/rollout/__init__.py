from asyncrl_tpu.rollout.anakin import ActorState, actor_init, unroll
from asyncrl_tpu.rollout.buffer import EpisodeStats, Rollout

__all__ = ["ActorState", "EpisodeStats", "Rollout", "actor_init", "unroll"]
