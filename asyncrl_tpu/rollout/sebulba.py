"""Sebulba host-actor runtime: Python actor threads pipelined against the
device learner (SURVEY.md §7.2 M3, §5.8b).

This is the TPU-native analogue of the reference's thread-per-actor +
actor→learner queue design (BASELINE.json:5; SURVEY.md §3.1): each
``ActorThread`` owns a slice of the env batch as a *host* env pool (the C++
``NativeEnvPool``, a gymnasium adapter, or a CPU-jitted functional env),
steps it with batched device inference, assembles time-major ``Rollout``
fragments in reusable numpy buffers, and puts them on a bounded queue. The
learner thread drains the queue, ``device_put``s fragments batch-sharded onto
the mesh, and steps the ``RolloutLearner``. Weight "publishing" back to
actors is a ``ParamStore`` swap of device arrays — no tensor ever leaves HBM
for the publish path; actors read the store at fragment boundaries
(staleness = learner updates between publishes, the queue bound gives the
pipelining the reference got from true asynchrony — SURVEY.md §7.3).

Failure handling (SURVEY.md §5.3): actor threads never raise into nowhere —
exceptions land in an error sink the trainer polls; dead actors are restarted
with a fresh env pool.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from asyncrl_tpu.envs.core import Environment, EnvSpec
from asyncrl_tpu.models.networks import is_recurrent, reset_core
from asyncrl_tpu.ops import distributions
from asyncrl_tpu.ops.normalize import normalize
from asyncrl_tpu.obs import spans as span_names
from asyncrl_tpu.obs import trace
from asyncrl_tpu.rollout.buffer import Rollout, RolloutBuffer
from asyncrl_tpu.utils import faults


class ParamStore:
    """Latest published learner params (device arrays) + version counter.

    The reference's back-channel from learner to actors was shared memory /
    the actors re-reading updated weights (SURVEY.md §3.1); here it is a
    lock-guarded reference swap — actors fetch at fragment start, so a
    fragment is always generated under one consistent ``behaviour`` policy.
    """

    def __init__(
        self, params: Any, env_steps: int = 0, debug: bool | None = None
    ):
        self._lock = threading.Lock()
        self._params = params  # guarded-by: _lock
        self._version = 0  # guarded-by: _lock
        # Authoritative global frame counter, published by the trainer loop
        # alongside params. Epsilon/anneal schedules read THIS rather than
        # extrapolating from a single thread's frame count (which drifts
        # when threads progress unevenly or after an actor restart).
        self._env_steps = int(env_steps)  # guarded-by: _lock
        # §5.2b debug mode: seqlock-style write stamp around every mutation
        # (odd = publish in flight). With the lock held this is invisible;
        # if the lock discipline is ever broken, a concurrent get() observes
        # an odd or changed stamp and raises instead of serving a torn
        # params/version pair. Kept unconditionally cheap (two int adds);
        # the read-side verification only arms under ASYNCRL_DEBUG_SYNC=1.
        self._seq = 0  # guarded-by: _lock
        if debug is None:
            from asyncrl_tpu.utils.debug import sync_debug_enabled

            debug = sync_debug_enabled()
        self._debug = debug

    def publish(self, params: Any, env_steps: int | None = None) -> int:
        """Swap in new params; returns the new version number (the trainer
        records what update count each version was published at, for the
        param_lag metric)."""
        with self._lock:
            self._seq += 1
            self._params = params
            self._version += 1
            if env_steps is not None:
                self._env_steps = int(env_steps)
            self._seq += 1
            return self._version

    def _torn(self, s1: int, s2: int) -> bool:  # holds: _lock
        return s1 != s2 or s1 % 2 == 1

    def get(self) -> tuple[Any, int]:
        with self._lock:
            if self._debug:
                s1 = self._seq
                pair = (self._params, self._version)
                if self._torn(s1, self._seq):
                    raise RuntimeError(
                        "ParamStore torn read: a publish was observed mid-get"
                        " — the store's lock discipline is broken"
                    )
                return pair
            return self._params, self._version

    def env_steps(self) -> int:
        with self._lock:
            if self._debug:
                s1 = self._seq
                steps = self._env_steps
                if self._torn(s1, self._seq):
                    raise RuntimeError(
                        "ParamStore torn read: a publish was observed "
                        "mid-env_steps — the store's lock discipline is "
                        "broken"
                    )
                return steps
            return self._env_steps


class Fragment:
    """One host-side rollout fragment + the episode stats gathered while
    producing it. Arrays are owned copies, safe to retain. ``actor``/``seq``
    stamp the producing thread and its fragment counter for the §5.2b
    transport invariants (``FragmentSequenceChecker``)."""

    __slots__ = (
        "rollout", "return_sum", "length_sum", "count", "version",
        "actor", "gen", "seq", "lease",
    )

    def __init__(self, rollout: Rollout, return_sum: float, length_sum: float,
                 count: float, version: int, actor: int = 0, gen: int = 0,
                 seq: int = 0, lease=None):
        # lint: thread-shared-ok(queue hand-off: Queue.put/get is the happens-before edge; the producer only rebinds rollout before the put)
        self.rollout = rollout
        self.return_sum = return_sum
        self.length_sum = length_sum
        self.count = count
        self.version = version
        self.actor = actor
        self.gen = gen
        self.seq = seq
        # Staging-slab lease (rollout/staging.py) when the zero-copy path
        # is on: the rollout's arrays are views of the leased row; None on
        # the legacy copy path (the rollout owns its arrays).
        self.lease = lease


class FragmentSequenceChecker:
    """§5.2b debug invariant on the actor→learner transport: within one
    actor thread lifetime — keyed (actor, gen), where the trainer bumps
    ``gen`` on every restart — fragments must reach the learner gapless
    (seq 0,1,2,…), duplicate-free, and in production order; and per actor
    (across restarts) the behaviour-param version must never decrease.
    ``queue.Queue`` guarantees all of this today; the checker exists so a
    future transport swap or refactor that silently drops, duplicates, or
    reorders fragments fails loudly under ASYNCRL_DEBUG_SYNC=1 instead of
    corrupting training. Generations (not a reset) distinguish a restarted
    actor's fresh stream from its predecessor's fragments still queued.
    Single-consumer use (the trainer's learner loop)."""

    def __init__(self) -> None:
        self._next_seq: dict[tuple[int, int], int] = {}
        self._last_version: dict[int, int] = {}

    def check(self, fragment: "Fragment") -> None:
        key = (fragment.actor, fragment.gen)
        expect = self._next_seq.get(key, 0)
        if fragment.seq != expect:
            raise RuntimeError(
                f"fragment transport invariant broken: actor "
                f"{fragment.actor} (gen {fragment.gen}) delivered seq "
                f"{fragment.seq}, expected {expect} (fragments lost, "
                f"duplicated, or reordered)"
            )
        self._next_seq[key] = expect + 1
        last = self._last_version.get(fragment.actor, -1)
        if fragment.version < last:
            raise RuntimeError(
                f"fragment transport invariant broken: actor "
                f"{fragment.actor} param version went backwards "
                f"({last} -> {fragment.version})"
            )
        self._last_version[fragment.actor] = fragment.version


class JaxHostPool:
    """Host env pool wrapping a functional JAX env, stepped on the CPU
    backend. Lets every registry env drive the Sebulba path even without a
    native/gymnasium implementation (useful for tests and for pixel envs)."""

    def __init__(self, env: Environment, num_envs: int, seed: int = 0):
        self.num_envs = num_envs
        self.spec = env.spec
        self._seed = seed
        self._cpu = jax.devices("cpu")[0]
        with jax.default_device(self._cpu):
            self._init = jax.jit(lambda keys: _pool_init(env, keys))
            self._step = jax.jit(
                lambda state, actions, key: _pool_step(env, state, actions, key)
            )
            self._key = jax.random.PRNGKey(seed)
        self._state = None
        # Chaos layer (utils/faults.py): one handle fetch; None when
        # unarmed, so the hot step pays a single identity check. The owner
        # (ActorThread) wires ``fault_stop`` so an injected stall wakes
        # when the thread is stopped/abandoned.
        self._fault_step = faults.site("pool.step")
        self.fault_stop = None

    def reset(self) -> np.ndarray:  # thread-entry: env-pool@actor
        """Deterministic: restart the key stream from the construction
        seed, so a pool reused across evaluations replays the same initial
        states (matching the gymnasium adapter's reset(seed=...))."""
        with jax.default_device(self._cpu):
            self._key = jax.random.PRNGKey(self._seed)
            self._key, sub = jax.random.split(self._key)
            keys = jax.random.split(sub, self.num_envs)
            self._state, obs = self._init(keys)
        return np.asarray(obs)

    def step(self, actions: np.ndarray):  # thread-entry: env-pool@actor
        with jax.default_device(self._cpu):
            self._key, sub = jax.random.split(self._key)
            self._state, ts = self._step(self._state, jnp.asarray(actions), sub)
        out = (
            np.asarray(ts.obs),
            np.asarray(ts.reward),
            np.asarray(ts.terminated),
            np.asarray(ts.truncated),
        )
        if self._fault_step is not None:
            out = self._fault_step.fire(stop=self.fault_stop, payload=out)
        return out

    def disarm_faults(self) -> None:
        """Detach this pool from the chaos layer (evaluation pools step
        outside the supervised pipeline; see SebulbaTrainer.evaluate)."""
        self._fault_step = None

    def close(self) -> None:
        self._state = None


def _pool_init(env: Environment, keys):
    state = jax.vmap(env.init)(keys)
    return state, jax.vmap(env.observe)(state)


def _pool_step(env: Environment, state, actions, key):
    keys = jax.random.split(key, actions.shape[0])
    return jax.vmap(env.step)(state, actions, keys)


def _env_knobs_set(config) -> bool:
    """True when the config requests env-modifying knobs only the JAX
    registry implements (ALE semantics; opponent modes for the envs that
    HAVE an opponent — the pong_* knobs are inert on every other env and
    must not disqualify its native/gym pool)."""
    if config.frame_skip > 1 or config.sticky_actions > 0.0:
        return True
    return config.env_id in ("JaxPong-v0", "JaxPongPixels-v0") and (
        config.pong_opponent != "tracker"
        or config.pong_opponent_speed != 0.0
    )


def make_host_pool(config, num_envs: int, seed: int):
    """Pick the fastest available host pool for ``config.env_id``.

    Preference order for ``host_pool="auto"``: native C++ pool (GIL-releasing
    batched stepping) → gymnasium vector adapter → CPU-jitted JAX env.

    The ALE-semantics / opponent knobs (frame_skip, sticky_actions,
    pong_opponent*) exist only in the JAX registry: "auto" routes to the
    JAX pool when any is set, and an explicit native/gym pool choice
    REFUSES rather than silently training against the unmodified env.
    """
    kind = config.host_pool
    env_id = config.env_id

    if _env_knobs_set(config):
        if kind in ("native", "gym"):
            raise ValueError(
                f"host_pool={kind!r} cannot honor the configured env knobs "
                "(frame_skip/sticky_actions/pong_opponent*): they are "
                "implemented by the JAX env registry only. Use "
                "host_pool='jax' (or 'auto')."
            )
        kind = "jax"

    if kind in ("auto", "native"):
        from asyncrl_tpu.envs import native_pool

        if env_id in native_pool.NATIVE_ENV_IDS:
            try:
                return native_pool.NativeEnvPool(env_id, num_envs, seed=seed)
            # lint: broad-except-ok(auto mode falls through to the next pool backend; an explicit native choice re-raises)
            except Exception:
                if kind == "native":
                    raise
        elif kind == "native":
            raise KeyError(
                f"no native pool for {env_id!r}; have "
                f"{sorted(native_pool.NATIVE_ENV_IDS)}"
            )

    if kind in ("auto", "gym"):
        from asyncrl_tpu.envs import gym_adapter

        if gym_adapter.available(env_id):
            return gym_adapter.GymnasiumHostPool(env_id, num_envs, seed=seed)
        if kind == "gym":
            raise KeyError(f"no gymnasium env for {env_id!r}")

    if kind in ("auto", "jax"):
        from asyncrl_tpu.envs import registry

        return JaxHostPool(
            registry.make(env_id, config), num_envs, seed=seed
        )

    raise ValueError(
        f"unknown host_pool {kind!r}; expected auto|native|gym|jax"
    )


def inference_mode(config, model) -> str:
    """THE (config, model) -> inference-signature mapping — the single
    dispatch site shared by ``make_inference_fn`` (which builds the
    callable) and the ``InferenceServer`` (which must unpack the same
    arity): "ff" | "eps" | "rec" | "rec_eps"."""
    recurrent = is_recurrent(model)
    if config.algo == "qlearn":
        return "rec_eps" if recurrent else "eps"
    return "rec" if recurrent else "ff"


def make_inference_fn(model, spec: EnvSpec, config: Any) -> Callable:
    """Jitted batched action selection for ``model`` (a flax module; the
    signature follows ``inference_mode(config, model)``, so the wrong
    variant cannot be built). Feed-forward: (params, obs[B], key) ->
    (actions, behaviour_logp, new_key). Recurrent (LSTM) models:
    (params, obs, key, core, done_prev) -> (..., new_core) — the core stays
    ON DEVICE across calls (only actions/logp sync to host), and is reset
    where the PREVIOUS step ended an episode, mirroring the Anakin scan.

    With ``config.algo == "qlearn"`` the signature instead is
    (params, obs, key, eps[B]) — ε-greedy over the model's Q-values, the
    per-env ε appended onto dist_params exactly as the Anakin ``dist_extra``
    channel does (ops.distributions.EpsilonGreedy). Recurrent (DRQN) Q
    models combine both contracts: (params, obs, key, core, done_prev, eps)
    -> (actions, logp, key, core).

    With ``config.normalize_obs`` the ``params`` argument is the PUBLISHED
    BUNDLE ``(params, obs_stats)`` (what SebulbaTrainer puts in the
    ParamStore): observations normalize under the bundled stats before the
    model apply, so host actors act under exactly the learner's view."""
    dist = distributions.for_config(config, spec)
    if config.normalize_obs:
        raw_apply = model.apply

        def apply_fn(bundle, obs, *rest):
            params, stats = bundle
            return raw_apply(params, normalize(obs, stats), *rest)

    else:
        apply_fn = model.apply
    mode = inference_mode(config, model)

    if mode in ("eps", "rec_eps"):
        if mode == "rec_eps":

            @jax.jit
            def infer_eps_recurrent(params, obs, key, core, done_prev, eps):
                core = reset_core(core, done_prev)
                key, sub = jax.random.split(key)
                q, _, core = apply_fn(params, obs, core)
                dist_params = jnp.concatenate(
                    [q, eps[:, None].astype(q.dtype)], axis=-1
                )
                act_keys = jax.random.split(sub, obs.shape[0])
                actions = jax.vmap(dist.sample)(act_keys, dist_params)
                logp = dist.logp(dist_params, actions)
                return actions, logp, key, core

            return infer_eps_recurrent

        @jax.jit
        def infer_eps(params, obs, key, eps):
            key, sub = jax.random.split(key)
            q, _ = apply_fn(params, obs)
            dist_params = jnp.concatenate(
                [q, eps[:, None].astype(q.dtype)], axis=-1
            )
            act_keys = jax.random.split(sub, obs.shape[0])
            actions = jax.vmap(dist.sample)(act_keys, dist_params)
            logp = dist.logp(dist_params, actions)
            return actions, logp, key

        return infer_eps

    if mode == "rec":

        @jax.jit
        def infer_recurrent(params, obs, key, core, done_prev):
            core = reset_core(core, done_prev)
            key, sub = jax.random.split(key)
            dist_params, _, core = apply_fn(params, obs, core)
            act_keys = jax.random.split(sub, obs.shape[0])
            actions = jax.vmap(dist.sample)(act_keys, dist_params)
            logp = dist.logp(dist_params, actions)
            return actions, logp, key, core

        return infer_recurrent

    @jax.jit
    def infer(params, obs, key):
        key, sub = jax.random.split(key)
        dist_params, _ = apply_fn(params, obs)
        act_keys = jax.random.split(sub, obs.shape[0])
        actions = jax.vmap(dist.sample)(act_keys, dist_params)
        logp = dist.logp(dist_params, actions)
        return actions, logp, key

    return infer


class ActorThread(threading.Thread):
    """One host actor: a pool slice + the fragment production loop.

    The reference's ``ActorWorker.run`` (BASELINE.json:5) stepped ONE env per
    thread; here each thread steps a *batch* through a pool (the C++ engine
    releases the GIL during stepping, so threads overlap env physics with
    device inference — SURVEY.md §7.3 "host↔device throughput").
    """

    def __init__(
        self,
        index: int,
        pool,
        inference_fn: Callable,
        store: ParamStore,
        out_queue: "queue.Queue[Fragment]",
        unroll_len: int,
        seed: int,
        stop_event: threading.Event,
        errors: "queue.Queue[tuple[int, int, BaseException]]",
        device=None,
        initial_core: Callable[[int], Any] | None = None,
        epsilon_fn: Callable[[int], np.ndarray] | None = None,
        track_returns: bool = False,
        return_discount: float = 0.0,
        generation: int = 0,
        staging=None,
    ):
        super().__init__(name=f"actor-{index}", daemon=True)
        self.index = index
        # Restart counter for this actor slot (stamped into fragments so
        # the §5.2b checker can tell a restarted thread's fresh seq stream
        # from its predecessor's fragments still sitting in the queue).
        self.generation = generation
        self.pool = pool
        self.inference_fn = inference_fn
        self.store = store
        self.out_queue = out_queue
        self.unroll_len = unroll_len
        self.seed = seed
        self.stop_event = stop_event
        self.errors = errors
        # Recurrent policies: builds the initial (c, h) carry for B envs;
        # None for feed-forward.
        self.initial_core = initial_core
        # Q-learning family: maps this thread's cumulative env frames -> the
        # per-env behaviour ε vector [B] (the A3C paper's per-thread ε,
        # annealed). None for the policy-gradient algos.
        self.epsilon_fn = epsilon_fn
        # normalize_returns: when ``track_returns`` (the SAME predicate the
        # learner keys its stats on — a discount of 0 must degrade to
        # reward-std tracking, not disagree), record the per-env
        # discounted-return stream G = discount*G + r (RAW rewards; the
        # trainer scales the stream together with the rewards).
        self.track_returns = track_returns
        self.return_discount = return_discount
        # ``jax.default_device`` is thread-local, so a device pin must be
        # re-established INSIDE the thread: the cpu_async backend pins actors
        # to host CPU (never touching an attached accelerator); sebulba
        # leaves None (batched inference on the accelerator is the point).
        self.device = device
        # Per-thread retirement signal: the watchdog abandons a HUNG thread
        # through this (the cohort stop event would take every healthy
        # sibling down with it), and a deliberate elastic scale-down
        # (runtime/elastic.py) retires the highest slot through the SAME
        # event — one drain-clean exit path, two callers. An abandoned
        # thread exits at its next check and its late error/fragment
        # output is discarded.
        self.abandon = threading.Event()
        # Progress stamp for the trainer's heartbeat watchdog: refreshed
        # every iteration of the production loop (including the bounded-
        # queue retry loop — a backpressured actor is alive, not hung).
        # lint: thread-shared-ok(GIL-atomic float stamp; the watchdog reads staleness only and refreshes after server outages)
        self.heartbeat = time.monotonic()
        # queue.Full retries observed on the fragment handoff (exported via
        # the metrics window as ``queue_backpressure``): how often actors
        # out-ran the learner+queue. Plain int under the GIL; the trainer
        # only ever reads it.
        self.backpressure = 0  # lint: thread-shared-ok(GIL-atomic int; single-writer, metrics-only reader)
        # Zero-copy staging ring (rollout/staging.py); None = legacy
        # copy-on-emit path. The actor leases one slab row per fragment
        # and writes transitions straight into it; ``_open_lease`` is the
        # not-yet-queued lease the supervisor voids if this thread dies.
        # Under the elastic runtime this is a RingSwapHolder, not a bare
        # StagingRing — same acquire contract, but a mid-wait ring swap
        # wakes the acquire and retries on the new ring.
        self.staging = staging
        # lint: thread-shared-ok(supervisor reads it only after this thread is dead or abandoned; StagingRing.void re-checks generations under its lock)
        self._open_lease = None
        # Chaos layer handles (None when unarmed — hot loop pays one
        # identity check per iteration; utils/faults.py).
        self._fault_step = faults.site("actor.step")
        self._fault_put = faults.site("actor.queue_put")
        # An injected pool.step stall must wake when THIS thread is
        # stopped/abandoned (a chaos stall has to stay abandonable, like
        # the wedged engine it models); harmless no-op on pools without an
        # armed site.
        # lint: thread-shared-ok(written before Thread.start: publication happens-before the run loop)
        self.pool.fault_stop = self._stopped

    def _stopped(self) -> bool:
        """Cohort shutdown OR individual watchdog retirement."""
        return self.stop_event.is_set() or self.abandon.is_set()

    def run(self) -> None:  # thread-entry: actor
        try:
            if self.device is not None:
                with jax.default_device(self.device):
                    self._run()
            else:
                self._run()
        # lint: broad-except-ok(thread boundary: the failure is delivered to the supervisor's error sink, never swallowed — §5.3)
        except BaseException as e:
            # ...unless the run is shutting down (or the watchdog already
            # retired this thread): an inference call (or server client)
            # interrupted by stop()/abandonment is not a failure. The
            # generation stamp lets the supervisor drop an error from a
            # thread it ALREADY replaced (a wedged actor can both trip the
            # watchdog and deliver its exception — one failure, not two).
            if not self._stopped():
                self.errors.put((self.index, self.generation, e))
        finally:
            close = getattr(self.pool, "close", None)
            if close is not None:
                try:
                    close()
                # lint: broad-except-ok(best-effort teardown on a dying thread; the primary failure is already reported above)
                except Exception:
                    pass

    def _heartbeat(self) -> None:
        self.heartbeat = time.monotonic()

    def _run(self) -> None:
        pool = self.pool
        T, B = self.unroll_len, pool.num_envs
        obs = pool.reset()
        key = jax.random.PRNGKey(self.seed)

        track_returns = self.track_returns
        ring = self.staging
        buffer = None
        if ring is None:
            buffer = RolloutBuffer(
                T, B, obs.shape[1:], obs.dtype, track_returns=track_returns
            )
        disc_g = np.zeros((B,), np.float32)
        running_return = np.zeros((B,), np.float64)
        running_length = np.zeros((B,), np.float64)
        core = self.initial_core(B) if self.initial_core else None
        done_prev = np.zeros((B,), bool)
        frames = 0  # this thread's cumulative env frames (for epsilon_fn)
        seq = 0  # fragment counter (§5.2b transport invariant stamp)

        while not self._stopped():
            lease = None
            if ring is not None:
                # Lease one slab row for this fragment. A blocked acquire
                # (ring under pressure) refreshes the heartbeat: a back-
                # pressured actor is alive, not hung.
                with trace.span(span_names.ACTOR_LEASE_WAIT):
                    lease = ring.acquire(
                        stop=self._stopped, on_wait=self._heartbeat
                    )
                if lease is None:
                    break  # stopped/abandoned while waiting
                # lint: protocol-ok(sanctioned hand-off: the supervisor voids _open_lease when it retires this thread — the one escape the lease protocol is built around)
                self._open_lease = lease
                buffer = lease.buffer
            params, version = self.store.get()
            # ε is fragment-constant (same anneal granularity as Anakin).
            # Kept as numpy: it rides the same device dispatch as obs (no
            # extra round trip), and the inference server's slab coalescer
            # packs host arrays without a per-client transfer.
            eps = (
                np.asarray(self.epsilon_fn(frames))
                if self.epsilon_fn is not None
                else None
            )
            ret_sum = 0.0
            len_sum = 0.0
            count = 0.0
            # Fragment-initial core AFTER the pending episode-boundary reset
            # (the jitted inference applies the reset; mirror it here so the
            # recorded carry is the one the fragment actually starts from).
            if core is not None:
                core = reset_core(core, jnp.asarray(done_prev))
                done_prev = np.zeros((B,), bool)
                init_core = jax.tree.map(np.asarray, core)
            while not buffer.full:
                self.heartbeat = time.monotonic()
                if self._fault_step is not None:
                    self._fault_step.fire(stop=self._stopped)
                with trace.span(span_names.ACTOR_INFERENCE):
                    if core is not None and eps is not None:
                        actions_d, logp_d, key, core = self.inference_fn(
                            params, obs, key, core, done_prev, eps
                        )
                    elif core is not None:
                        actions_d, logp_d, key, core = self.inference_fn(
                            params, obs, key, core, done_prev
                        )
                    elif eps is not None:
                        actions_d, logp_d, key = self.inference_fn(
                            params, obs, key, eps
                        )
                    else:
                        actions_d, logp_d, key = self.inference_fn(
                            params, obs, key
                        )
                    # ONE batched device→host sync for both leaves (two
                    # np.asarray calls were two round trips on a high-
                    # latency link); numpy passes through untouched (server
                    # clients already hand back host arrays).
                    actions, logp = jax.device_get((actions_d, logp_d))
                prev_obs = obs
                with trace.span(span_names.ACTOR_ENV_STEP):
                    obs, rew, term, trunc = pool.step(actions)
                if track_returns:
                    disc_g = self.return_discount * disc_g + rew
                    buffer.append(
                        prev_obs, actions, logp, rew, term,
                        trunc, disc_return=disc_g,
                    )
                    disc_g = np.where(
                        np.logical_or(term, trunc), 0.0, disc_g
                    ).astype(np.float32)
                else:
                    buffer.append(
                        prev_obs, actions, logp, rew, term, trunc
                    )
                done_prev = np.logical_or(term, trunc)
                frames += B

                running_return += rew
                running_length += 1.0
                done = done_prev
                if done.any():
                    ret_sum += float(running_return[done].sum())
                    len_sum += float(running_length[done].sum())
                    count += float(done.sum())
                    running_return[done] = 0.0
                    running_length[done] = 0.0

            rollout = buffer.emit(bootstrap_obs=obs)
            if core is not None:
                if lease is not None:
                    rollout = lease.write_init_core(rollout, init_core)
                else:
                    rollout = rollout.replace(init_core=init_core)
            fragment = Fragment(
                rollout,
                ret_sum, len_sum, count, version,
                actor=self.index, gen=self.generation, seq=seq,
                lease=lease,
            )
            seq += 1
            if self._fault_put is not None:
                corrupted = self._fault_put.fire(
                    stop=self._stopped, payload=fragment.rollout.rewards
                )
                if corrupted is not fragment.rollout.rewards:
                    if lease is not None:
                        # Slab path: the drain reads the SLAB, so the
                        # injected damage must land there (write-through
                        # the view) — a detached copy would silently
                        # un-corrupt the payload.
                        np.copyto(fragment.rollout.rewards, corrupted)
                    else:
                        fragment.rollout = fragment.rollout.replace(
                            rewards=corrupted
                        )
            if lease is not None:
                # Content-complete: raises StaleLeaseError if the
                # supervisor voided this lease (thread already retired) —
                # caught by run()'s stopped-thread swallow.
                lease.commit()
            # Bounded put that stays responsive to shutdown (and to the
            # watchdog retiring this thread mid-backpressure). The span
            # covers the retry loop: its duration IS the backpressure
            # wait (a free queue slot makes it ~one put's epsilon).
            with trace.span(span_names.ACTOR_QUEUE_PUT):
                while not self._stopped():
                    try:
                        self.out_queue.put(fragment, timeout=0.1)
                        self._open_lease = None
                        break
                    except queue.Full:
                        self.backpressure += 1
                        self.heartbeat = time.monotonic()
                        continue
