"""Device-resident rollout hand-off: the HBM tier of the staging path.

The sebulba drain's H2D hand-off (``learner.put_rollout`` → barrier →
update) binds each transferred fragment to a bare local — nothing bounds
how many device-resident fragments can be in flight at once, and nothing
names the moment a fragment's HBM becomes reclaimable. On the host tier
the staging ring answers both with its slab ledger (``rollout/staging.py``:
generation-stamped leases, readiness-gated reuse); this module is the
same discipline one tier down. :class:`DeviceRolloutQueue` owns a fixed
set of HBM slots; ``enqueue`` claims a slot (blocking on the OLDEST
consumed slot's readiness handle when the drain has outrun the learner),
lands the host slab on the mesh through the learner's own sharded
transfer, and mints a generation-stamped :class:`DeviceLease`. The drain
reads the device fragment through the lease (``rollout()``), dispatches
the update, and ``consume``\\s the lease with the update's OUTPUT as the
readiness handle — the slot re-leases only once that update has
executed, the device-tier twin of ``StagingRing.retire``.

What this buys over the bare hand-off:

- **Bounded HBM residency.** At most ``slots`` fragments are device-
  resident at once, enforced by the ledger rather than by drain-loop
  timing. ``slots=2`` is the double-buffer: slot B's H2D overlaps slot
  A's update, and the third enqueue waits on A's handle.
- **A zero-copy replay publish path.** The fragment the replay ring
  publishes IS the queue slot's device pytree — with the queue active
  the ring can adopt it by reference (``DeviceReplayRing.publish(...,
  ref=True)``) instead of paying the device-to-device row install.
  jax arrays are immutable, so slot REUSE (rebinding the slot to the
  next fragment) can never corrupt an adopted reference; the one real
  hazard is buffer DONATION, which is why the trainer only enables ref
  publishing when ``config.donate_buffers`` is off (a donating update
  deletes the adopted buffers under the ring).
- **A machine-checked lifecycle.** The lease protocol is declared below
  and verified by the protocol-typestate pass (PROT001-004): a drain
  path that mints a lease and drops it without ``consume``/``void``
  gates in lint, not in review.

Host staging remains the CPU fallback: on backends where device arrays
alias host memory there is no HBM tier to manage, so ``config.
device_queue="auto"`` resolves off (trainer construction) and the drain
keeps the plain ``put_rollout`` path, bit-identically.

Threading: single-thread contract, like the replay ring — every method
runs on the trainer's drain thread. The actor threads never see this
object (they hand off HOST fragments through the staging ring).
"""

# protocol: devq-lease mint=DeviceRolloutQueue.enqueue ops=consume:held->consumed,void:held->voided open=held terminal=voided initial=held reads=rollout:held

from __future__ import annotations

from collections import deque
from typing import Callable

import jax

from asyncrl_tpu.rollout.buffer import Rollout
from asyncrl_tpu.rollout.staging import StaleLeaseError, _handle_ready


class DeviceLease:
    """One device-slot write-read-release permit, generation-stamped.

    States: ``held`` (fragment resident, update not yet dispatched) →
    ``consumed`` (update dispatched; slot frees when the update's output
    handle is ready) or ``voided`` (abandoned — reset/stop hygiene; the
    slot frees after the in-flight H2D is barriered out)."""

    __slots__ = ("queue", "slot", "gen", "_consumed", "_voided")

    def __init__(self, queue: "DeviceRolloutQueue", slot: int, gen: int):
        self.queue = queue
        self.slot = slot
        self.gen = gen
        self._consumed = False
        self._voided = False

    def valid(self) -> bool:
        return (
            not self._voided
            and self.queue._slot_gen[self.slot] == self.gen
        )

    def _check(self) -> None:
        if not self.valid():
            raise StaleLeaseError(
                f"device lease gen {self.gen} on slot {self.slot} is "
                "stale (queue reset, or the slot was re-leased); the "
                "fragment it named is gone"
            )

    def rollout(self) -> Rollout:
        """The leased slot's device-resident fragment pytree. Valid in
        ``held`` only — after ``consume`` the consuming update may have
        donated the buffers."""
        self._check()
        if self._consumed:
            raise StaleLeaseError(
                f"device lease on slot {self.slot} already consumed; "
                "the update may have donated the fragment"
            )
        return self.queue._slots[self.slot]

    def consume(self, ready_handle) -> None:
        """Release the slot, gated on ``ready_handle`` (the consuming
        update's OUTPUT — e.g. ``state.update_step``): the slot re-leases
        only once the handle's device work has executed, so the next
        enqueue can never race the update still reading this fragment.
        One-shot; raises :class:`StaleLeaseError` if stale."""
        self._check()
        if self._consumed:
            raise StaleLeaseError(
                f"device lease on slot {self.slot} consumed twice"
            )
        self._consumed = True
        self.queue._consume(self, ready_handle)

    def void(self) -> None:
        """Abandon the lease (reset/stop hygiene — the update was never
        dispatched). Idempotent. The slot's in-flight H2D is barriered
        before the slot frees: the host staging slab under the transfer
        may recycle the moment the drain drops its lease, and an
        unfinished async read of it would land a torn fragment in a
        recycled slot."""
        if self._voided:
            return
        self._voided = True
        self.queue._void(self)


class DeviceRolloutQueue:
    """Fixed-depth ledger of HBM-resident fragments between H2D and the
    consuming update.

    ``transfer`` is the learner's sharded host→device put
    (``RolloutLearner.put_rollout`` — ONE home for the mesh sharding of a
    fragment); ``slots`` is the residency bound, minimum 2 (a single slot
    cannot overlap slot i+1's transfer with slot i's update — the whole
    point of the tier).

    Slots hold REBOUND pytrees, not a preallocated stacked buffer: jax
    arrays are immutable, so "reuse" is ledger-level — the bound the
    queue enforces is *at most ``slots`` fragments resident*, with the
    old slab's HBM returned the moment its last reference (the slot
    binding, plus any replay-ring adoption) drops or its buffers are
    donated by the update that consumed it."""

    def __init__(
        self,
        transfer: Callable[[Rollout], Rollout],
        slots: int = 2,
    ):
        if slots < 2:
            raise ValueError(
                f"device_queue_slots={slots} must be >= 2: one slot "
                "serializes every transfer behind the previous update "
                "(no double-buffer), which is strictly worse than the "
                "host-staging fallback"
            )
        self._transfer = transfer
        self._slots: list[Rollout | None] = [None] * slots
        self._gen = 0
        self._slot_gen = [0] * slots
        self._free: deque[int] = deque(range(slots))
        # (slot, ready_handle) in consume order — reclamation waits on
        # the OLDEST, matching the drain's dispatch order.
        self._pending: deque[tuple[int, object]] = deque()
        self._out: dict[int, DeviceLease] = {}  # slot -> open lease
        # Times enqueue found no free slot and had to block on a pending
        # update's handle — the device-tier twin of the staging ring's
        # slab_reuse_waits signal (drain outran the learner).
        self.reuse_waits = 0

    @property
    def slots(self) -> int:
        return len(self._slots)

    # ----------------------------------------------------------- enqueue

    def enqueue(self, host_rollout: Rollout) -> DeviceLease:
        """Claim a slot, land ``host_rollout`` on the mesh through the
        learner's sharded transfer (async dispatch — the caller barriers
        where the host tier demands it), and mint the slot's lease."""
        slot = self._claim()
        self._slots[slot] = self._transfer(host_rollout)
        self._gen += 1
        self._slot_gen[slot] = self._gen
        lease = DeviceLease(self, slot, self._gen)
        self._out[slot] = lease
        return lease

    def _claim(self) -> int:
        self._reap()
        if not self._free:
            if not self._pending:
                # Every slot is HELD: the drain minted more leases than
                # slots without consuming — a drain-loop bug, not
                # backpressure. Blocking would deadlock (nothing pending
                # can ever free a slot).
                raise RuntimeError(
                    f"device queue exhausted: all {self.slots} slots "
                    "hold open leases; the drain must consume (or void) "
                    "a lease per enqueue"
                )
            # Backpressure: the drain outran the learner by the full
            # queue depth. Wait for the oldest consumed slot's update.
            self.reuse_waits += 1
            slot, handle = self._pending.popleft()
            jax.block_until_ready(handle)
            self._free.append(slot)
        return self._free.popleft()

    def _reap(self) -> None:
        """Free every consumed slot whose update has already executed —
        opportunistic, so steady-state enqueues never block at all."""
        while self._pending and _handle_ready(self._pending[0][1]):
            slot, _ = self._pending.popleft()
            self._free.append(slot)

    # ----------------------------------------------------------- release

    def _consume(self, lease: DeviceLease, ready_handle) -> None:
        if self._out.get(lease.slot) is lease:
            del self._out[lease.slot]
        self._pending.append((lease.slot, ready_handle))

    def _void(self, lease: DeviceLease) -> None:
        if self._out.get(lease.slot) is not lease:
            return
        del self._out[lease.slot]
        tree = self._slots[lease.slot]
        if tree is not None:
            jax.block_until_ready(tree)
        self._free.append(lease.slot)

    # ------------------------------------------------------------ facade

    def busy(self) -> bool:
        """Any open (held) lease outstanding?"""
        return bool(self._out)

    def reset(self) -> None:
        """Void every open lease and drain every pending handle (trainer
        ``stop()`` hygiene): straggler leases read as stale, and no
        async consumer of a slot outlives the queue's ledger."""
        for lease in list(self._out.values()):
            lease.void()
        while self._pending:
            _, handle = self._pending.popleft()
            jax.block_until_ready(handle)
        self._gen += 1
        self._slot_gen = [0] * self.slots
        self._free = deque(range(self.slots))
        self._slots = [None] * self.slots
