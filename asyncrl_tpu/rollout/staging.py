"""Pinned host staging rings for the actor→learner fragment path.

The legacy Sebulba data path paid three host-memory taxes per learner
update: every ``RolloutBuffer.emit`` copied a full fragment, every fused
drain re-allocated a ``[K, T, B, ...]`` stack (``np.stack``), and the freed
buffers churned the allocator at exactly the rate of the hot loop. IMPACT
(arXiv:1912.00167) and "Parallel Actors and Learners" (arXiv:2110.01101)
both identify this copy/dispatch overhead as the dominant tax in
asynchronous actor-learner systems, so this module removes it structurally:

- A :class:`StagingRing` owns a small pool of preallocated **slabs** —
  numpy pytrees shaped ``[K, T, B, ...]`` (K = ``updates_per_call``), one
  leaf per ``Rollout`` field, allocated once for the trainer's lifetime.
- Actors **lease** one slab row per fragment (:meth:`StagingRing.acquire`)
  and write transitions directly into the row's views through their
  ``RolloutBuffer`` — emit is a pointer hand-off, not a copy.
- The drain consumes a whole slab as the fused ``[K, T, B, ...]`` batch
  (:meth:`StagingRing.batch`) — the stack already exists, ``np.stack``
  never runs.
- A slab is only reused after the learner update that consumed it has
  executed on device (:meth:`StagingRing.retire` records a readiness
  handle; acquisition blocks on it under pressure). This gate is what
  makes the overlapped ``device_put`` safe even on backends where the
  device buffer aliases host memory (the CPU client's zero-copy path).

Lease protocol & generations
----------------------------
Every lease carries a ring-global **generation stamp**; the owning slab
row records the stamp of its current lease. A supervisor that retires a
crashed/hung actor *voids* the actor's open lease: the row re-opens for
the replacement actor under a fresh generation, and the zombie's stamp no
longer matches — its ``commit`` raises :class:`StaleLeaseError`, its
buffer ``append``s raise through the lease guard, and any fragment it
already queued is dropped at the drain (``lease.valid()`` is false). A
restarted actor therefore can never scribble on a slab row it no longer
owns (modulo the single-store race inherent to abandoning a live thread,
which the watchdog design already accepts; the guard shrinks the window
from a whole fragment to one array store).

The same generation/lease discipline is applied at the DEVICE tier by
the IMPACT replay ring (learn/replay.py): generation-stamped rows,
oldest-generation eviction, and zombie reads fenced to
:class:`StaleLeaseError` (its ``ReplayStaleError`` subclass) — one
error family for "your row was re-leased under you", host or device.

Ring resize (elastic runtime)
-----------------------------
:class:`RingSwapHolder` makes the ring itself replaceable at runtime: a
fleet-scale event installs a fresh ring sized for the new fleet while
in-flight leases finish on the old one (every lease pins its minting ring
via ``lease.ring``). See the class docstring for the swap protocol.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

import jax
import numpy as np

from asyncrl_tpu.obs import spans as span_names
from asyncrl_tpu.obs import trace
from asyncrl_tpu.rollout.buffer import Rollout, RolloutBuffer


def _handle_ready(handle) -> bool:
    """Has the readiness handle's device work executed? A deleted
    (donated/consumed) or handle-less array can only mean the update
    already ran: ready. ONE home for this rule — the reclamation paths in
    ``retire`` and ``_await_release`` must never diverge on which
    exceptions mean "deleted"."""
    try:
        return bool(handle.is_ready())
    except (RuntimeError, ValueError, AttributeError):
        return True


class StaleLeaseError(RuntimeError):
    """A voided/superseded lease was used to write or commit: the owning
    actor was retired by the supervisor and its slab row re-leased. The
    raising thread must stop producing — its output is already orphaned."""


def fragment_template(config, spec, model, num_envs: int) -> Rollout:
    """The ``jax.ShapeDtypeStruct`` pytree of ONE host fragment for this
    (config, spec, model) — the single source of slab geometry, derived the
    same way the learner derives its shapes (so a slab mismatch is
    impossible by construction rather than checked at runtime)."""
    from asyncrl_tpu.models.networks import is_recurrent
    from asyncrl_tpu.ops import distributions

    T, B = config.unroll_len, num_envs
    obs_dtype = np.dtype(spec.obs_dtype)
    f32 = np.dtype(np.float32)
    dist = distributions.for_config(config, spec)
    act_shape = (T, B, spec.action_dim) if spec.continuous else (T, B)
    init_core = None
    if model is not None and is_recurrent(model):
        init_core = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(tuple(x.shape), np.dtype(x.dtype)),
            model.initial_core(num_envs),
        )
    return Rollout(
        obs=jax.ShapeDtypeStruct((T, B, *spec.obs_shape), obs_dtype),
        actions=jax.ShapeDtypeStruct(act_shape, np.dtype(dist.action_dtype)),
        behaviour_logp=jax.ShapeDtypeStruct((T, B), f32),
        rewards=jax.ShapeDtypeStruct((T, B), f32),
        terminated=jax.ShapeDtypeStruct((T, B), np.dtype(bool)),
        truncated=jax.ShapeDtypeStruct((T, B), np.dtype(bool)),
        bootstrap_obs=jax.ShapeDtypeStruct((B, *spec.obs_shape), obs_dtype),
        init_core=init_core,
        disc_returns=(
            jax.ShapeDtypeStruct((T, B), f32)
            if config.normalize_returns
            else None
        ),
    )


class _Slab:
    """One preallocated ``[K, T, B, ...]`` numpy pytree + its row ledger."""

    __slots__ = ("arrays", "row_gen", "committed", "phase")

    def __init__(self, template: Rollout, rows: int):
        self.arrays = jax.tree.map(
            lambda sds: np.empty((rows, *sds.shape), np.dtype(sds.dtype)),
            template,
        )
        self.row_gen = [-1] * rows  # guarded-by: StagingRing._cond
        self.committed = [False] * rows  # guarded-by: StagingRing._cond
        # "free" | "filling" | "inflight"
        self.phase = "free"  # guarded-by: StagingRing._cond

    def row(self, k: int) -> Rollout:
        """Row ``k`` as a pytree of VIEWS (numpy basic slicing)."""
        return jax.tree.map(lambda a: a[k], self.arrays)


class SlabLease:  # thread-entry: slab-lease@actor
    """One actor's write permit for one slab row, generation-stamped.
    Methods run on the owning actor thread (closure-dispatched through
    the buffer guard, hence the explicit thread-entry declaration);
    ``StagingRing.void`` is the supervisor's cross-thread path."""

    __slots__ = ("ring", "slab", "row", "gen", "_buffer")

    def __init__(self, ring: "StagingRing", slab: int, row: int, gen: int):
        self.ring = ring
        self.slab = slab
        self.row = row
        self.gen = gen
        self._buffer: RolloutBuffer | None = None

    def valid(self) -> bool:
        """Still the row's current lease? Lock-free read (a list-element
        load is atomic under the GIL; staleness here only delays, never
        corrupts — the locked commit is the authoritative check)."""
        # lint: unguarded-ok(GIL-atomic list-element load; the locked commit is the authoritative check)
        return self.ring._slabs[self.slab].row_gen[self.row] == self.gen

    def _check(self) -> None:
        if not self.valid():
            raise StaleLeaseError(
                f"lease gen {self.gen} on slab {self.slab} row {self.row} "
                "was voided (its actor was retired); refusing to write"
            )

    @property
    def buffer(self) -> RolloutBuffer:
        """A ``RolloutBuffer`` whose storage IS this row (zero-copy emit);
        every append re-validates the lease through the guard."""
        if self._buffer is None:
            storage = self.ring._slabs[self.slab].row(self.row)
            T, B = storage.obs.shape[:2]
            self._buffer = RolloutBuffer(
                T, B, storage.obs.shape[2:], storage.obs.dtype,
                track_returns=storage.disc_returns is not None,
                storage=storage, guard=self._check,
            )
        return self._buffer

    def write_init_core(self, rollout: Rollout, init_core: Any) -> Rollout:
        """Copy the fragment-initial recurrent carry into this row's slab
        storage and return the rollout viewing it (the batched drain reads
        ``init_core`` from the slab like every other leaf)."""
        self._check()
        views = jax.tree.map(
            lambda a: a[self.row],
            self.ring._slabs[self.slab].arrays.init_core,
        )
        jax.tree.map(
            lambda dst, src: np.copyto(dst, np.asarray(src)), views, init_core
        )
        return rollout.replace(init_core=views)

    def commit(self) -> None:
        self.ring._commit(self)


class StagingRing:
    """The slab pool + lease ledger shared by all actors and the drain.

    Thread-safety: one condition guards all ledger state; slab *contents*
    are unguarded by design — the lease protocol guarantees single-writer
    rows and reader/writer phase separation (filling → drained → inflight
    → free)."""

    def __init__(self, template: Rollout, rows_per_slab: int, num_slabs: int):
        if rows_per_slab < 1:
            raise ValueError(f"rows_per_slab={rows_per_slab} must be >= 1")
        if num_slabs < 2:
            # One slab cannot double-buffer: the fill of batch i+1 would
            # wait for batch i's device consumption every time.
            raise ValueError(f"num_slabs={num_slabs} must be >= 2")
        self._K = rows_per_slab
        self._slabs = [_Slab(template, rows_per_slab) for _ in range(num_slabs)]
        self._cond = threading.Condition()
        # Rows open for leasing: the current fill slab's rows in order,
        # plus voided rows of older incomplete slabs (prepended, so old
        # slabs complete before new ones open — the anti-starvation rule).
        self._avail: "deque[tuple[int, int]]" = deque()  # guarded-by: _cond
        # Retired slabs awaiting device readiness: (slab_index, handle).
        self._inflight: "deque[tuple[int, Any]]" = deque()  # guarded-by: _cond
        self._gen = 0  # guarded-by: _cond
        # Times an acquire had to wait on an in-flight slab's readiness
        # (the ring was too shallow for the moment's pipeline depth).
        self.reuse_waits = 0  # guarded-by: _cond
        self.slab_nbytes = int(
            sum(leaf.nbytes for leaf in jax.tree.leaves(self._slabs[0].arrays))
        )

    @property
    def rows_per_slab(self) -> int:
        return self._K

    @property
    def num_slabs(self) -> int:
        return len(self._slabs)

    # ------------------------------------------------------------ actors

    def acquire(
        self,
        stop: Callable[[], bool] | None = None,
        on_wait: Callable[[], None] | None = None,
    ) -> SlabLease | None:
        """Lease the next free slab row, blocking until one exists.

        Returns ``None`` when ``stop()`` turns true (cohort shutdown or
        watchdog abandonment). ``on_wait`` is invoked on every internal
        wait iteration — actors refresh their heartbeat through it so a
        back-pressured acquire reads as alive, not hung."""
        while True:
            head = None
            with self._cond:
                if stop is not None and stop():
                    return None
                if not self._avail:
                    for i, slab in enumerate(self._slabs):
                        if slab.phase == "free":
                            slab.phase = "filling"
                            self._avail.extend(
                                (i, r) for r in range(self._K)
                            )
                            break
                if self._avail:
                    s, r = self._avail.popleft()
                    self._gen += 1
                    self._slabs[s].row_gen[r] = self._gen
                    self._slabs[s].committed[r] = False
                    return SlabLease(self, s, r, self._gen)
                if self._inflight:
                    head = self._inflight[0]
                    self.reuse_waits += 1
            if on_wait is not None:
                on_wait()
            if head is not None:
                self._await_release(head, stop, on_wait)
            else:
                # All rows are leased out or committed-but-undrained: the
                # drain will retire their slabs; nothing to block on yet.
                with self._cond:
                    self._cond.wait(0.05)

    def _await_release(self, head, stop, on_wait) -> None:
        """Wait for the oldest in-flight slab's readiness handle WITHOUT
        holding the ring lock, then release it. Polled (not a single
        ``block_until_ready``) so a stopping run and the heartbeat stay
        responsive even under a slow device."""
        s, handle = head
        with trace.span(span_names.STAGING_REUSE_WAIT):
            while True:
                if _handle_ready(handle):
                    break
                if stop is not None and stop():
                    return
                if on_wait is not None:
                    on_wait()
                time.sleep(0.002)
        with self._cond:
            if self._inflight and self._inflight[0] is head:
                self._inflight.popleft()
                self._release_locked(s)

    def void(self, lease: SlabLease) -> None:
        """Supervisor path: invalidate a retired actor's open lease and
        re-open its row for the replacement (fresh generation on the next
        acquire). Idempotent; a superseded lease is a no-op."""
        with self._cond:
            slab = self._slabs[lease.slab]
            if slab.row_gen[lease.row] != lease.gen:
                return
            slab.row_gen[lease.row] = -1
            slab.committed[lease.row] = False
            if slab.phase == "filling":
                self._avail.appendleft((lease.slab, lease.row))
            self._cond.notify_all()

    def _commit(self, lease: SlabLease) -> None:
        with self._cond:
            slab = self._slabs[lease.slab]
            if slab.row_gen[lease.row] != lease.gen:
                raise StaleLeaseError(
                    f"commit on voided lease gen {lease.gen} "
                    f"(slab {lease.slab} row {lease.row})"
                )
            slab.committed[lease.row] = True

    # ------------------------------------------------------------- drain

    def batch(self, slab_id: int) -> Rollout:
        """The consumable batch for a fully-committed slab: the raw
        ``[K, T, B, ...]`` pytree (K > 1), or row 0's plain ``[T, B, ...]``
        views (K == 1 — the unfused learner layout). Zero copies.

        The committed-ledger check runs under the ring lock (a static-
        analysis finding: the queue hand-off makes the drain's view of the
        K commits it consumed consistent, but a CONCURRENT void/commit on
        another row of the same slab could tear the unguarded list read)."""
        slab = self._slabs[slab_id]
        with self._cond:
            uncommitted = [i for i, c in enumerate(slab.committed) if not c]
        if uncommitted:
            raise RuntimeError(
                f"slab {slab_id} batched with uncommitted rows {uncommitted}"
            )
        if self._K == 1:
            return slab.row(0)
        return slab.arrays

    def retire(self, slab_id: int, ready: Any) -> None:
        """Hand a consumed slab to the in-flight ledger. ``ready`` is any
        device array produced by the update that read the slab (the
        trainer passes the new ``update_step``): once it is ready the
        update has executed, so no device-side reader — including a
        zero-copy CPU alias — can still see the slab's memory."""
        with self._cond:
            self._slabs[slab_id].phase = "inflight"
            self._inflight.append((slab_id, ready))
            # Opportunistic reclamation: anything already ready frees now,
            # so steady state never routes through the blocking path.
            while self._inflight:
                s, handle = self._inflight[0]
                if not _handle_ready(handle):
                    break
                self._inflight.popleft()
                self._release_locked(s)

    def _release_locked(self, slab_id: int) -> None:  # holds: _cond
        slab = self._slabs[slab_id]
        slab.phase = "free"
        slab.row_gen = [-1] * self._K
        slab.committed = [False] * self._K
        self._cond.notify_all()

    def busy(self) -> bool:
        """Any open lease or committed-but-undrained row? The elastic
        :class:`RingSwapHolder`'s safe-to-reset test for a retired ring:
        ``False`` means resetting cannot invalidate a lease an actor or
        the drain still holds. Only ``"filling"`` slabs can carry such
        state — ``"inflight"`` slabs were fully consumed (batched +
        retired) and ``"free"`` ones hold nothing. Conservative for
        never-re-leased rows still carrying an old generation (K > 1
        slabs); exact in the elastic configuration, which requires
        ``updates_per_call=1`` (K=1)."""
        with self._cond:
            for slab in self._slabs:
                if slab.phase != "filling":
                    continue
                for r in range(self._K):
                    if slab.committed[r] or slab.row_gen[r] > 0:
                        return True
            return False

    def reset(self) -> None:
        """Invalidate every lease and free every slab (trainer ``stop()``:
        actors are joined/abandoned, queued fragments discarded — any
        straggler lease must read as stale, never as a live row)."""
        with self._cond:
            self._gen += 1
            self._avail.clear()
            self._inflight.clear()
            for i in range(len(self._slabs)):
                self._release_locked(i)


class RingSwapHolder:
    """A swappable staging-ring façade for the elastic runtime.

    Actors acquire through the holder; every :class:`SlabLease` carries a
    hard reference to the :class:`StagingRing` it was minted from
    (``lease.ring``), so an in-flight lease keeps committing — and the
    drain keeps batching/retiring — on the OLD ring while new acquires
    land on the new one. This is the ParamSlots generation trick
    (serve/params.py) applied to whole rings: a resize installs ring g+1
    concurrently while ring g's leases finish; no lease is ever dropped
    and no batch ever mixes rows from two rings (the drain keys slab
    groups by ring identity).

    :meth:`swap` also *interrupts* acquires blocked on the outgoing ring:
    the holder threads a swapped-out predicate into ``StagingRing.acquire``'s
    stop hook, so a back-pressured actor wakes and retries on the new ring
    instead of leasing a row no drain will ever complete.

    Retired rings are swept on every swap: a ring that has fully drained
    (``StagingRing.busy()`` false — no open lease, no committed row the
    drain still owes) is reset, turning any stale lease object still
    referencing it into :class:`StaleLeaseError` on every write path; a
    ring that is NOT drained (an actor mid-write across the swap, a
    fragment still queued) is retained untouched — a live lease is never
    invalidated by a deliberate scale, no matter how closely two scale
    events follow each other. Retention is bounded at
    ``MAX_RETIRED_RINGS``: beyond it the oldest ring is force-reset — its
    straggler (a thread wedged across that many scale windows, which the
    heartbeat watchdog would have retired anyway) is fenced to
    ``StaleLeaseError`` and the supervisor treats the fallout as a crash,
    the pre-elastic semantics.
    """

    # Slabs are large (whole [K, T, B, ...] rollouts); a handful of
    # retained retired rings is memory-bounded churn, unbounded retention
    # is a leak.
    MAX_RETIRED_RINGS = 4

    def __init__(self, ring: StagingRing):
        self._lock = threading.Lock()
        self._ring = ring  # guarded-by: _lock
        self._retired: list[StagingRing] = []  # guarded-by: _lock
        self._reuse_base = 0  # guarded-by: _lock

    # ------------------------------------------------------------- facade

    def current(self) -> StagingRing:
        with self._lock:
            return self._ring

    @property
    def rows_per_slab(self) -> int:
        return self.current().rows_per_slab

    @property
    def num_slabs(self) -> int:
        return self.current().num_slabs

    @property
    def slab_nbytes(self) -> int:
        # Slab GEOMETRY ([K, T, B, ...]) is invariant across swaps — only
        # the slab count changes — so the current ring's nbytes is exact
        # for old-ring batches too.
        return self.current().slab_nbytes

    @property
    def reuse_waits(self) -> int:
        with self._lock:
            return self._reuse_base + self._ring.reuse_waits

    # ------------------------------------------------------------- actors

    def acquire(
        self,
        stop: Callable[[], bool] | None = None,
        on_wait: Callable[[], None] | None = None,
    ) -> SlabLease | None:
        """Lease a row from the CURRENT ring; a swap arriving mid-wait
        wakes the acquire and retries on the new ring. Same contract as
        ``StagingRing.acquire`` (None = stopped/abandoned)."""
        while True:
            ring = self.current()

            def stop_or_swapped(ring=ring):
                if stop is not None and stop():
                    return True
                # Deliberately UNLOCKED read (GIL-atomic attribute load):
                # this predicate runs inside StagingRing.acquire UNDER
                # ring._cond, and taking the holder lock here would invert
                # swap()'s holder->ring nesting (its busy() sweep) into an
                # ABBA deadlock between an actor and the window-close
                # thread. A stale read only costs one extra 50ms wait tick.
                # lint: unguarded-ok(GIL-atomic reference read; locking here would invert the holder->ring nesting into an ABBA deadlock; staleness bounded by the acquire wait timeout)
                return self._ring is not ring

            lease = ring.acquire(stop=stop_or_swapped, on_wait=on_wait)
            if lease is not None:
                return lease
            if stop is not None and stop():
                return None
            # Swapped out from under the wait: retry on the new ring.

    def void(self, lease: SlabLease) -> None:
        """Supervisor path: void on whatever ring minted the lease."""
        lease.ring.void(lease)

    # ------------------------------------------------------------ control

    def swap(self, new_ring: StagingRing) -> None:
        """Install ``new_ring`` for all future acquires. The outgoing ring
        keeps serving its in-flight leases; previously retired rings are
        swept — drained ones reset (fencing stale lease objects), busy
        ones retained (a live lease is never invalidated), the oldest
        force-reset beyond ``MAX_RETIRED_RINGS`` (see class docstring)."""
        with self._lock:
            self._retired.append(self._ring)
            self._reuse_base += self._ring.reuse_waits
            self._ring = new_ring
            # busy() takes the ring lock nested inside the holder lock:
            # holder->ring is the one permitted nesting order, which is
            # why acquire's swapped-out predicate (which runs under the
            # ring lock) reads the holder WITHOUT its lock.
            drained, keep = [], []
            for ring in self._retired:
                (keep if ring.busy() else drained).append(ring)
            while len(keep) > self.MAX_RETIRED_RINGS:
                drained.append(keep.pop(0))
            self._retired = keep
        for ring in drained:
            ring.reset()

    def reset(self) -> None:
        """Trainer ``stop()``: every lease on every live ring goes stale
        and every slab frees (the ``StagingRing.reset`` contract, applied
        to the current AND every retained retired ring)."""
        with self._lock:
            rings = [*self._retired, self._ring]
            self._retired = []
        for ring in rings:
            ring.reset()


def auto_num_slabs(queue_capacity: int, actor_threads: int, rows: int) -> int:
    """Ring depth at which steady-state acquisition never blocks: rows for
    every queued fragment + one open lease per actor, plus one slab filling
    and one in flight."""
    return max(2, -(-(queue_capacity + actor_threads) // max(rows, 1)) + 2)
