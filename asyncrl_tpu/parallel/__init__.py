from asyncrl_tpu.parallel.mesh import (
    DP_AXIS,
    TIME_AXIS,
    TP_AXIS,
    dp_sharded,
    make_mesh,
    num_dp,
    replicated,
)

__all__ = [
    "DP_AXIS",
    "TIME_AXIS",
    "TP_AXIS",
    "dp_sharded",
    "make_mesh",
    "num_dp",
    "replicated",
]
