"""Device-mesh construction and sharding helpers.

The reference exchanges gradients/weights through Python shared memory and
queues between threads (SURVEY.md §5.8a). The TPU-native equivalent is a
``jax.sharding.Mesh`` whose collectives ride ICI within a slice and DCN
across slices: data-parallel gradient reduction is ``lax.pmean`` inside
``shard_map`` (compiler-scheduled all-reduce), weight "publishing" is a no-op
because params are replicated by construction.

Multi-host: call ``jax.distributed.initialize`` before building the mesh and
order axes (dcn, ici) so the inner, bandwidth-hungry axis maps to ICI
(SURVEY.md §5.8b); ``make_mesh`` uses all visible devices either way.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"  # data parallel: envs + batch sharded, grads all-reduced
TP_AXIS = "tp"  # reserved: model-parallel axis for future large policies
TIME_AXIS = "sp"  # reserved: time-axis (sequence) sharding, parallel/timeshard

# ``jax.shard_map`` graduated out of jax.experimental only in newer jax
# releases; THE import site for the whole framework lives here so every
# learner/population/timeshard call works on both (keyword call convention
# — f, mesh=, in_specs=, out_specs= — is identical across the two).
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    # Older jax: the experimental namespace is the only spelling, and its
    # static replication checker is weaker than the vma inference the
    # bodies here were written against — it cannot see through an optax
    # update chain that a new param tree derived from psum'd grads is still
    # replicated, and rejects the P() out_specs. check_rep=True must stay
    # on (it also enables the transpose rewrite that psums grads of
    # replicated inputs — the gradient semantics every learner body relies
    # on), so instead each output subtree whose spec leaves mesh axes
    # unmentioned is passed through an identity collective (pmean for
    # floats, pmax for ints/bools): a numeric no-op on genuinely
    # replicated values that the checker CAN infer.
    from jax.experimental.shard_map import shard_map as _experimental_smap

    def _assert_replicated(x, axes):
        if jnp.issubdtype(x.dtype, jnp.inexact):
            return jax.lax.pmean(x, axes)
        return jax.lax.pmax(x, axes)

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
        import jax.tree_util as jtu

        if check_vma is False:
            # The caller explicitly opted out of replication/vma checking
            # (the Pallas interpreter under shard_map cannot infer vma —
            # tests/test_pallas_scan.py). Forward the same opt-out; the
            # identity-collective wrapping below exists only to SATISFY
            # the checker, so it is skipped along with it.
            # lint: sharding-ok(explicit check_vma=False forward: caller opted out; wrapping exists only to satisfy the checker being disabled)
            return _experimental_smap(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=False,
            )

        axis_names = tuple(mesh.axis_names)

        def wrapped(*args):
            out = f(*args)
            spec_leaves, spec_def = jtu.tree_flatten(
                out_specs, is_leaf=lambda s: isinstance(s, P)
            )
            subtrees = spec_def.flatten_up_to(out)
            fixed = []
            for spec, sub in zip(spec_leaves, subtrees):
                named = set()
                for entry in spec:
                    if entry is None:
                        continue
                    if isinstance(entry, str):
                        named.add(entry)
                    else:
                        named.update(entry)
                missing = tuple(n for n in axis_names if n not in named)
                if missing:
                    sub = jax.tree.map(
                        lambda x: _assert_replicated(jnp.asarray(x), missing),
                        sub,
                    )
                fixed.append(sub)
            return jtu.tree_unflatten(spec_def, fixed)

        return _experimental_smap(
            wrapped, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=True,
        )


def axis_size(axis_name):
    """Mapped-axis size inside a ``shard_map`` body. ``jax.lax.axis_size``
    on jax releases that have it; the ``psum(1, axis)`` idiom (which XLA
    constant-folds) everywhere else. Accepts a name or tuple of names."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def reduce_grads(grads, axes, impl: str = "psum"):
    """Cross-shard reduction for gradients of REPLICATED params computed by
    ``jax.grad`` INSIDE a shard_map body. Under jax>=0.8 vma semantics the
    transpose of the implicit replicated->varying broadcast psums those
    cotangents automatically (the bodies scale their loss by 1/axis_size to
    match); the pre-graduation shard_map does no such thing inside the body
    — each shard would silently keep its LOCAL gradient — so this inserts
    the reduction explicitly there. No-op on new jax (a second reduction
    would double-count — which is also why ``impl`` cannot apply there;
    ``resolve_scan_impl`` rejects ring on that path) and on an unsharded
    mesh.

    ``impl``: "psum"/"auto" — one compiler-scheduled all-reduce of the
    whole tree; "ring" — the deterministic-order bidirectional ring
    schedule over the flattened tree (``ops.ring_reduce``), which exposes
    2(n-1) chunked neighbor transfers the scheduler can overlap with the
    tail of the backward pass. Ring sums in a fixed order, so it is
    run-to-run deterministic but differs from psum within the float
    summation ULP bound (bit-equal at n=2)."""
    if not axes or hasattr(jax, "shard_map"):
        return grads
    if impl in ("psum", "auto"):
        return jax.tree.map(lambda g: jax.lax.psum(g, axes), grads)
    if impl == "ring":
        from asyncrl_tpu.ops.ring_reduce import ring_all_reduce_grads

        return ring_all_reduce_grads(grads, axes)
    raise ValueError(f"unknown grad_reduce impl {impl!r}; expected psum|ring")


def make_mesh(
    mesh_shape: tuple[int, ...] = (-1,),
    mesh_axes: tuple[str, ...] = (DP_AXIS,),
    devices: list | None = None,
) -> Mesh:
    """Build a Mesh over all (or given) devices; one -1 dim is inferred."""
    devices = list(jax.devices()) if devices is None else list(devices)
    shape = list(mesh_shape)
    if -1 in shape:
        known = math.prod(s for s in shape if s != -1)
        if len(devices) % known:
            raise ValueError(
                f"{len(devices)} devices not divisible by mesh shape {mesh_shape}"
            )
        shape[shape.index(-1)] = len(devices) // known
    if math.prod(shape) != len(devices):
        raise ValueError(
            f"mesh shape {tuple(shape)} != device count {len(devices)}"
        )
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, mesh_axes)


def dp_axes(mesh: Mesh) -> tuple[str, ...]:
    """All data-parallel mesh axes: every axis except the reserved
    model-parallel (tp) and time-sharding (sp) axes.

    A single-slice mesh is ``("dp",)``; a multi-slice/multi-host hybrid mesh
    is e.g. ``("dcn", "dp")`` with the inner, bandwidth-hungry axis on ICI
    (SURVEY.md §5.8b). Env batches shard — and gradients all-reduce — over
    the PRODUCT of these axes; collectives take the tuple directly
    (``lax.pmean(x, ("dcn", "dp"))``)."""
    return tuple(n for n in mesh.axis_names if n not in (TP_AXIS, TIME_AXIS))


def dp_size(mesh: Mesh) -> int:
    return math.prod(mesh.shape[a] for a in dp_axes(mesh))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_sharded(mesh: Mesh) -> NamedSharding:
    """Shard the leading (env/batch) dim over ALL data-parallel axes."""
    return NamedSharding(mesh, P(dp_axes(mesh)))


def num_dp(mesh: Mesh) -> int:
    """Total data-parallel degree (product of all dp axes); alias of
    :func:`dp_size`."""
    return dp_size(mesh)
