"""Device-mesh construction and sharding helpers.

The reference exchanges gradients/weights through Python shared memory and
queues between threads (SURVEY.md §5.8a). The TPU-native equivalent is a
``jax.sharding.Mesh`` whose collectives ride ICI within a slice and DCN
across slices: data-parallel gradient reduction is ``lax.pmean`` inside
``shard_map`` (compiler-scheduled all-reduce), weight "publishing" is a no-op
because params are replicated by construction.

Multi-host: call ``jax.distributed.initialize`` before building the mesh and
order axes (dcn, ici) so the inner, bandwidth-hungry axis maps to ICI
(SURVEY.md §5.8b); ``make_mesh`` uses all visible devices either way.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"  # data parallel: envs + batch sharded, grads all-reduced
TP_AXIS = "tp"  # reserved: model-parallel axis for future large policies
TIME_AXIS = "sp"  # reserved: time-axis (sequence) sharding, parallel/timeshard


def make_mesh(
    mesh_shape: tuple[int, ...] = (-1,),
    mesh_axes: tuple[str, ...] = (DP_AXIS,),
    devices: list | None = None,
) -> Mesh:
    """Build a Mesh over all (or given) devices; one -1 dim is inferred."""
    devices = list(jax.devices()) if devices is None else list(devices)
    shape = list(mesh_shape)
    if -1 in shape:
        known = math.prod(s for s in shape if s != -1)
        if len(devices) % known:
            raise ValueError(
                f"{len(devices)} devices not divisible by mesh shape {mesh_shape}"
            )
        shape[shape.index(-1)] = len(devices) // known
    if math.prod(shape) != len(devices):
        raise ValueError(
            f"mesh shape {tuple(shape)} != device count {len(devices)}"
        )
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, mesh_axes)


def dp_axes(mesh: Mesh) -> tuple[str, ...]:
    """All data-parallel mesh axes: every axis except the reserved
    model-parallel (tp) and time-sharding (sp) axes.

    A single-slice mesh is ``("dp",)``; a multi-slice/multi-host hybrid mesh
    is e.g. ``("dcn", "dp")`` with the inner, bandwidth-hungry axis on ICI
    (SURVEY.md §5.8b). Env batches shard — and gradients all-reduce — over
    the PRODUCT of these axes; collectives take the tuple directly
    (``lax.pmean(x, ("dcn", "dp"))``)."""
    return tuple(n for n in mesh.axis_names if n not in (TP_AXIS, TIME_AXIS))


def dp_size(mesh: Mesh) -> int:
    return math.prod(mesh.shape[a] for a in dp_axes(mesh))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_sharded(mesh: Mesh) -> NamedSharding:
    """Shard the leading (env/batch) dim over ALL data-parallel axes."""
    return NamedSharding(mesh, P(dp_axes(mesh)))


def num_dp(mesh: Mesh) -> int:
    """Total data-parallel degree (product of all dp axes); alias of
    :func:`dp_size`."""
    return dp_size(mesh)
