"""Multi-host initialization and hybrid (DCN x ICI) mesh construction.

The reference is single-host: its "distributed backend" is Python threads +
queues (SURVEY.md §5.8a). The TPU-native counterpart scales the same trainer
across hosts and pod slices with zero algorithm changes:

1. every host calls :func:`initialize` (a thin ``jax.distributed`` wrapper)
   before any JAX computation;
2. :func:`make_hybrid_mesh` builds a ``Mesh`` with axes ``("dcn", "dp")`` —
   the outer axis crosses slices over DCN, the inner axis stays within a
   slice on ICI, so the compiler schedules the bandwidth-hungry part of
   every gradient all-reduce on ICI (SURVEY.md §5.8b);
3. the learners shard envs/batches and reduce gradients over ALL
   data-parallel axes (``parallel.mesh.dp_axes``), so the exact same train
   step runs on one chip, one slice, or many slices.

Single-host multi-device falls back transparently (dcn axis of size 1).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from asyncrl_tpu.parallel.mesh import DP_AXIS, make_mesh

DCN_AXIS = "dcn"


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the multi-host JAX runtime (call once per host, before any
    computation). On Cloud TPU all arguments are auto-detected from the
    environment; pass them explicitly elsewhere (coordinator ``host:port``,
    world size, this host's rank)."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_hybrid_mesh(
    dcn_size: int | None = None, devices: list | None = None
) -> Mesh:
    """Mesh with axes ``(dcn, dp)``: ``dcn_size`` groups (default: one per
    process/host) with the remaining device factor inside each group.

    Device order: ``jax.devices()`` is sorted so that each process's local
    devices are contiguous, which makes the leading reshape axis exactly the
    host/slice boundary — DCN-adjacent groups land on the dcn axis, ICI
    neighbours on dp, the layout SURVEY.md §5.8b prescribes.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if dcn_size is None:
        dcn_size = max(jax.process_count(), 1)
    return make_mesh((dcn_size, -1), (DCN_AXIS, DP_AXIS), devices=devices)
