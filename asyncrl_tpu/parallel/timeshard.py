"""Time-axis (sequence) parallelism for the reverse affine recurrence.

The reference has no sequence parallelism to port — its "sequence" axis is
the rollout time axis, processed whole on one host (SURVEY.md §5.7). On TPU
the analogue that matters is sharding that time axis across the mesh for
long-horizon fragments: V-trace/GAE are first-order affine recurrences, so a
T-sharded solve needs only one tiny all_gather of per-segment aggregates —
the distributed classic two-level scan:

1. each device solves its local segment with zero inflow (associative scan,
   O(log T_local) depth),
2. per-segment aggregates (a-product, zero-inflow solution at segment start)
   are all_gathered over the ``sp`` axis — [n_seg] scalars per batch
   element, riding ICI,
3. a segment-level scan of those aggregates yields each segment's inflow;
   one fused multiply-add corrects the local solution.

This makes million-step fragments (or future recurrent/attention policies
with long horizons) scale across chips without serializing time.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from asyncrl_tpu.ops.scan import reverse_linear_scan
from asyncrl_tpu.parallel.mesh import TIME_AXIS, axis_size, shard_map


def reverse_linear_scan_timesharded(
    a: jax.Array, b: jax.Array, axis_name: str = TIME_AXIS
) -> jax.Array:
    """Solve x_t = b_t + a_t * x_{t+1}, x_T = 0, with the time axis sharded.

    Must be called INSIDE shard_map/pmap over ``axis_name``; ``a``/``b`` are
    the local time segment [T_local, ...], segments ordered by axis index
    (device i holds times [i*T_local, (i+1)*T_local)).
    """
    # (1) local solve with zero inflow from the right.
    x_local = reverse_linear_scan(a, b)
    # suffix a-products: prod_{s=t..end} a_s — the factor an inflow picks up
    # travelling from the segment end back to time t.
    suffix_prod = jnp.flip(jnp.cumprod(jnp.flip(a, axis=0), axis=0), axis=0)

    # (2) per-segment aggregates: x at segment start = B_seg + A_seg * inflow.
    a_seg = suffix_prod[0]
    b_seg = x_local[0]
    a_all = jax.lax.all_gather(a_seg, axis_name)  # [n_seg, ...]
    b_all = jax.lax.all_gather(b_seg, axis_name)

    # (3) segment-level solve: y[k] = solution at segment k's first time.
    # The inflow into segment k is y[k+1] (zero for the last segment).
    y = reverse_linear_scan(a_all, b_all)
    n_seg = y.shape[0]
    idx = jax.lax.axis_index(axis_name)
    zero = jnp.zeros_like(y[0])
    inflow = jnp.where(
        idx + 1 < n_seg,
        jax.lax.dynamic_index_in_dim(
            y, jnp.minimum(idx + 1, n_seg - 1), axis=0, keepdims=False
        ),
        zero,
    )
    return x_local + suffix_prod * inflow


def shift_from_next_shard(
    x: jax.Array, fill: jax.Array, axis_name: str = TIME_AXIS
) -> jax.Array:
    """Time-sharded ``x[t+1]``: shift the local segment up by one, filling
    the last local slot with the NEXT shard's first element (via a one-hop
    ``ppermute`` riding ICI); the final shard's last slot gets ``fill``
    (the bootstrap). This is the boundary exchange every one-step-lookahead
    (V-trace/GAE deltas) needs once the time axis is sharded."""
    n = axis_size(axis_name)
    if n == 1:
        return jnp.concatenate([x[1:], fill[None]], axis=0)
    # Each shard i sends its first element to shard i-1.
    from_next = jax.lax.ppermute(
        x[0], axis_name, perm=[(i, i - 1) for i in range(1, n)]
    )
    idx = jax.lax.axis_index(axis_name)
    last = jnp.where(idx == n - 1, fill, from_next)
    return jnp.concatenate([x[1:], last[None]], axis=0)


def vtrace_timesharded(
    behaviour_logp: jax.Array,
    target_logp: jax.Array,
    rewards: jax.Array,
    discounts: jax.Array,
    values: jax.Array,
    bootstrap_value: jax.Array,
    rho_clip: float = 1.0,
    c_clip: float = 1.0,
    axis_name: str = TIME_AXIS,
):
    """V-trace with the TIME axis sharded over ``axis_name`` (sequence
    parallelism for long-horizon fragments — SURVEY.md §5.7).

    Must run INSIDE shard_map over ``axis_name``; every input is the local
    [T_local, B] segment (``bootstrap_value`` [B] is replicated; only the
    last shard consumes it). Cross-shard communication: two one-hop
    ``ppermute``s (the t+1 value/target shifts) + the tiny per-segment
    all_gather inside the distributed scan. Matches ``ops.vtrace.vtrace``
    on the gathered result exactly (tests/test_timeshard.py).
    """
    from asyncrl_tpu.ops.vtrace import VTraceOutput

    log_rhos = target_logp - behaviour_logp
    rhos = jnp.exp(log_rhos)
    clipped_rhos = jnp.minimum(rho_clip, rhos)
    clipped_cs = jnp.minimum(c_clip, rhos)

    values_tp1 = shift_from_next_shard(values, bootstrap_value, axis_name)
    deltas = clipped_rhos * (rewards + discounts * values_tp1 - values)

    vs_minus_v = reverse_linear_scan_timesharded(
        jax.lax.stop_gradient(discounts * clipped_cs),
        jax.lax.stop_gradient(deltas),
        axis_name,
    )
    vs = vs_minus_v + values

    vs_tp1 = shift_from_next_shard(vs, bootstrap_value, axis_name)
    pg_advantages = clipped_rhos * (rewards + discounts * vs_tp1 - values)

    # Global clip fractions: equal-sized shards -> pmean of local means.
    rho_clip_frac = jax.lax.pmean(
        jnp.mean((rhos > rho_clip).astype(jnp.float32)), axis_name
    )
    c_clip_frac = jax.lax.pmean(
        jnp.mean((rhos > c_clip).astype(jnp.float32)), axis_name
    )
    return VTraceOutput(
        vs=jax.lax.stop_gradient(vs),
        pg_advantages=jax.lax.stop_gradient(pg_advantages),
        rho_clip_frac=rho_clip_frac,
        c_clip_frac=c_clip_frac,
    )


def gae_timesharded(
    rewards: jax.Array,
    discounts: jax.Array,
    values: jax.Array,
    bootstrap_value: jax.Array,
    gae_lambda: float = 0.95,
    axis_name: str = TIME_AXIS,
):
    """GAE with the time axis sharded over ``axis_name`` (see
    ``vtrace_timesharded`` for the calling contract)."""
    from asyncrl_tpu.ops.gae import GAEOutput

    values_tp1 = shift_from_next_shard(values, bootstrap_value, axis_name)
    deltas = rewards + discounts * values_tp1 - values
    advantages = reverse_linear_scan_timesharded(
        jax.lax.stop_gradient(discounts * gae_lambda),
        jax.lax.stop_gradient(deltas),
        axis_name,
    )
    returns = advantages + values
    return GAEOutput(
        advantages=jax.lax.stop_gradient(advantages),
        returns=jax.lax.stop_gradient(returns),
    )


def n_step_returns_timesharded(
    rewards: jax.Array,
    discounts: jax.Array,
    bootstrap_value: jax.Array,
    axis_name: str = TIME_AXIS,
) -> jax.Array:
    """Time-sharded discounted n-step returns (A3C targets): the bootstrap
    folds into the LAST shard's final step; everything else is the
    distributed reverse scan."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    is_last = (idx == n - 1).astype(rewards.dtype)
    rewards_ext = rewards.at[-1].add(
        is_last * discounts[-1] * bootstrap_value
    )
    return reverse_linear_scan_timesharded(
        jax.lax.stop_gradient(discounts),
        jax.lax.stop_gradient(rewards_ext),
        axis_name,
    )


def make_timesharded_solver(
    mesh: Mesh, axis_name: str = TIME_AXIS
) -> Callable[[jax.Array, jax.Array], jax.Array]:
    """Wrap the in-shard solver as a standalone jitted function over global
    [T, ...] arrays, time-sharded on ``axis_name`` of ``mesh``."""

    solver = shard_map(
        lambda a, b: reverse_linear_scan_timesharded(a, b, axis_name),
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name)),
        out_specs=P(axis_name),
    )
    return jax.jit(solver)
