"""Multi-host launch: ``python -m asyncrl_tpu.cli.launch`` (one invocation
per host).

The reference is single-host (threads + queues, SURVEY.md §5.8a); this is
the TPU-native multi-host entry. Every host runs the SAME command (plus its
own ``--process-id``), joins the ``jax.distributed`` runtime, builds the
hybrid (dcn × dp) mesh over the global device set, and drives the identical
train step — gradients all-reduce over ICI within a slice and DCN across
slices, with zero algorithm changes (parallel/distributed.py).

On Cloud TPU pods the coordinator/world-size/rank are auto-detected — just
run the same command on every host with no distributed flags. Elsewhere
(e.g. CPU multi-process testing, tests/test_multiprocess.py) pass
``--coordinator host:port --num-processes N --process-id I`` explicitly.
"""

from __future__ import annotations

import argparse
import json


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="asyncrl-tpu-launch",
        description="Join a multi-host run and train (one invocation per "
        "host; same command everywhere).",
    )
    parser.add_argument("preset", help="preset name (see asyncrl_tpu.configs)")
    parser.add_argument(
        "overrides", nargs="*", help="config overrides as key=value"
    )
    parser.add_argument(
        "--coordinator", default=None,
        help="coordinator host:port (omit on Cloud TPU: auto-detected)",
    )
    parser.add_argument(
        "--num-processes", type=int, default=None,
        help="world size (omit on Cloud TPU)",
    )
    parser.add_argument(
        "--process-id", type=int, default=None,
        help="this host's rank (omit on Cloud TPU)",
    )
    parser.add_argument(
        "--dcn-size", type=int, default=None,
        help="outer mesh axis size (default: one group per process)",
    )
    parser.add_argument(
        "--steps", type=int, default=None, help="override total_env_steps"
    )
    args = parser.parse_args(argv)

    from asyncrl_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )

    import jax

    from asyncrl_tpu.api.trainer import Trainer
    from asyncrl_tpu.cli.common import resolve_config

    cfg = resolve_config(args.preset, args.overrides, args.steps)
    if cfg.backend != "tpu":
        raise SystemExit(
            f"multi-host launch is Anakin-only (backend='tpu'); "
            f"got {cfg.backend!r}"
        )

    mesh = distributed.make_hybrid_mesh(dcn_size=args.dcn_size)
    is_lead = jax.process_index() == 0
    if is_lead:
        print(
            json.dumps(
                {
                    "processes": jax.process_count(),
                    "global_devices": jax.device_count(),
                    "local_devices": jax.local_device_count(),
                    "mesh": {
                        ax: int(mesh.shape[ax]) for ax in mesh.axis_names
                    },
                }
            ),
            flush=True,
        )

    trainer = Trainer(cfg, mesh=mesh)
    # Every process drives the same jitted steps (multi-controller SPMD);
    # only the lead process reports.
    hist = trainer.train(callback=print if is_lead else None)
    if is_lead and hist:
        final = {
            k: float(v)
            for k, v in hist[-1].items()
            if isinstance(v, (int, float)) or getattr(v, "ndim", 1) == 0
        }
        print(json.dumps({"final": final}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
