"""Demo/eval entry: ``python -m asyncrl_tpu.cli.play <preset> [opts]``.

The reference family ships a demo/play script alongside training (greedy
rollouts of a trained model, reward printout — SURVEY.md §3.5 "Evaluation").
This is that script: restore a checkpoint (or play from init for a dry
run), run greedy episodes on device, print per-episode returns, and
optionally dump episode frames/observations to an ``.npz`` for offline
inspection (pixel envs: [T, H, W, C] uint8 frames ready for any viewer;
vector envs: raw observation trajectories).
"""

from __future__ import annotations

import argparse
import json


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="asyncrl-tpu-play",
        description="Greedy-play a trained agent: per-episode returns, "
        "optional trajectory dump.",
    )
    parser.add_argument("preset", help="preset name (see asyncrl_tpu.configs)")
    parser.add_argument(
        "overrides", nargs="*", help="config overrides as key=value"
    )
    parser.add_argument(
        "--restore", metavar="DIR", default=None,
        help="checkpoint directory to restore (default: play from init)",
    )
    parser.add_argument(
        "--episodes", type=int, default=8, help="episodes to play"
    )
    parser.add_argument(
        "--max-steps", type=int, default=3200, help="step cap per episode"
    )
    parser.add_argument(
        "--save", metavar="FILE.npz", default=None,
        help="dump one episode's observation trajectory to FILE.npz",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit results as one JSON line"
    )
    args = parser.parse_args(argv)

    from asyncrl_tpu.api.factory import make_agent
    from asyncrl_tpu.cli.common import apply_platform_guard, resolve_config

    cfg = resolve_config(args.preset, args.overrides)
    apply_platform_guard(cfg)

    agent = make_agent(cfg, restore=args.restore)
    try:
        returns: list[float] = []
        if args.episodes:
            try:
                # One batched device rollout for all episodes (tpu backend).
                returns = [
                    float(r)
                    for r in agent.evaluate(
                        num_episodes=args.episodes,
                        max_steps=args.max_steps,
                        return_episodes=True,
                    )
                ]
            except TypeError:
                # Host backends expose only the mean; report it as one row.
                returns = [
                    agent.evaluate(
                        num_episodes=args.episodes, max_steps=args.max_steps
                    )
                ]
        if returns:
            mean = sum(returns) / len(returns)
            if args.json:
                print(
                    json.dumps(
                        {
                            "preset": args.preset,
                            "restored": args.restore,
                            "episode_returns": returns,
                            "mean_return": mean,
                        }
                    )
                )
            else:
                for i, r in enumerate(returns):
                    print(f"episode {i}: return {r:.1f}")
                print(f"mean over {len(returns)} episodes: {mean:.2f}")

        if args.save:
            _dump_trajectory(agent, cfg, args.save, args.max_steps)
            print(f"trajectory saved to {args.save}")
    finally:
        close = getattr(agent, "close", None)
        if close is not None:
            close()
    return 0


def _dump_trajectory(agent, cfg, path: str, max_steps: int) -> None:
    """Greedy-roll one episode on device; save obs/action/reward arrays."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from asyncrl_tpu.models.networks import is_recurrent
    from asyncrl_tpu.ops import distributions

    # Reuse the agent's own env: only device-env backends own one (Sebulba
    # presets name gymnasium ids that are not in the device registry).
    env = getattr(agent, "env", None)
    if env is None:
        raise SystemExit(
            "--save needs a device-env (backend='tpu') preset; host-pool "
            f"backends ({cfg.backend!r}) have no on-device env to roll out"
        )
    model = agent.model
    params = agent.state.params
    dist = distributions.for_config(cfg, env.spec)
    recurrent = is_recurrent(model)

    from asyncrl_tpu.ops.normalize import normalizing_apply

    napply = normalizing_apply(
        model.apply, getattr(agent.state, "obs_stats", None)
    )

    def body(carry, _):
        env_state, obs, done, key, core = carry
        key, step_key = jax.random.split(key)
        if recurrent:
            # Single-episode rollout: no mid-trajectory reset needed (the
            # scan freezes at the first done), batch dim of 1 for the core.
            dist_params, _, core = napply(params, obs[None], core)
        else:
            dist_params, _ = napply(params, obs[None])
        action = dist.mode(dist_params)[0]
        new_state, ts = env.step(env_state, action, step_key)
        # Freeze the trajectory after the first episode end.
        keep = jnp.logical_not(done)
        out = (obs, action, jnp.where(keep, ts.reward, 0.0), done)
        new_done = jnp.logical_or(done, ts.done)
        carry = jax.tree.map(
            lambda n, o: jnp.where(keep, n, o), (new_state, ts.obs), (env_state, obs)
        ) + (new_done, key, core)
        return carry, out

    @jax.jit
    def rollout(key):
        init_key, run_key = jax.random.split(key)
        env_state = env.init(init_key)
        obs = env.observe(env_state)
        core = model.initial_core(1) if recurrent else None
        _, (obs_traj, act_traj, rew_traj, done_traj) = jax.lax.scan(
            body,
            (env_state, obs, jnp.zeros((), bool), run_key, core),
            None,
            length=max_steps,
        )
        return obs_traj, act_traj, rew_traj, done_traj

    obs_traj, act_traj, rew_traj, done_traj = rollout(jax.random.PRNGKey(7))
    # Trim to the episode length (first True in done_traj, else max_steps).
    # done_traj[t] is the PRE-step flag: the first True marks the first
    # frozen step after the episode, so the valid trajectory is [:argmax).
    done_np = np.asarray(done_traj)
    end = int(done_np.argmax()) if done_np.any() else max_steps
    np.savez_compressed(
        path,
        obs=np.asarray(obs_traj)[:end],
        actions=np.asarray(act_traj)[:end],
        rewards=np.asarray(rew_traj)[:end],
        episode_return=float(np.asarray(rew_traj)[:end].sum()),
    )


if __name__ == "__main__":
    raise SystemExit(main())
