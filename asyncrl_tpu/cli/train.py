"""CLI entry: ``python -m asyncrl_tpu.cli.train <preset> [key=value ...]``.

The reference family drives training through per-workload run scripts
(SURVEY.md §1.2 L6); here one entry point + the preset registry covers all
workloads (BASELINE.json:6-12), with ``key=value`` overrides (SURVEY.md §5.6).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="asyncrl-tpu",
        description="Train an asyncrl_tpu agent from a workload preset.",
    )
    parser.add_argument("preset", help="preset name (see asyncrl_tpu.configs)")
    parser.add_argument(
        "overrides", nargs="*", help="config overrides as key=value"
    )
    parser.add_argument(
        "--steps", type=int, default=None, help="override total_env_steps"
    )
    parser.add_argument(
        "--eval-episodes", type=int, default=32,
        help="greedy-eval episodes after training (0 to skip)",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit one JSON line per window"
    )
    args = parser.parse_args(argv)

    from asyncrl_tpu.api.factory import make_agent
    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils.config import override

    cfg = override(presets.get(args.preset), args.overrides)
    if args.steps is not None:
        cfg = cfg.replace(total_env_steps=args.steps)

    if cfg.backend == "cpu_async":
        # The parity backend is CPU-only by contract; restricting the
        # platform list before any backend initializes keeps JAX's global
        # backend init from even touching an attached accelerator (jax
        # initializes ALL registered platforms on first device query).
        import jax

        jax.config.update("jax_platforms", "cpu")

    agent = make_agent(cfg)

    def report(window: dict) -> None:
        if args.json:
            print(json.dumps(window))
        else:
            print(
                f"steps={window['env_steps']:>10}  "
                f"fps={window['fps']:>12,.0f}  "
                f"ep_return={window['episode_return']:8.2f}  "
                f"loss={window['loss']:8.4f}  "
                f"entropy={window['entropy']:6.3f}"
            )
        sys.stdout.flush()

    agent.train(callback=report)

    if args.eval_episodes:
        ret = agent.evaluate(num_episodes=args.eval_episodes)
        print(
            json.dumps({"eval_episodes": args.eval_episodes, "mean_return": ret})
            if args.json
            else f"greedy eval over {args.eval_episodes} episodes: {ret:.1f}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
