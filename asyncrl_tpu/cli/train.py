"""CLI entry: ``python -m asyncrl_tpu.cli.train <preset> [key=value ...]``.

The reference family drives training through per-workload run scripts
(SURVEY.md §1.2 L6); here one entry point + the preset registry covers all
workloads (BASELINE.json:6-12), with ``key=value`` overrides (SURVEY.md §5.6).
"""

from __future__ import annotations

import argparse
import json


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="asyncrl-tpu",
        description="Train an asyncrl_tpu agent from a workload preset.",
    )
    parser.add_argument("preset", help="preset name (see asyncrl_tpu.configs)")
    parser.add_argument(
        "overrides", nargs="*", help="config overrides as key=value"
    )
    parser.add_argument(
        "--steps", type=int, default=None, help="override total_env_steps"
    )
    parser.add_argument(
        "--eval-episodes", type=int, default=32,
        help="greedy-eval episodes after training (0 to skip)",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit one JSON line per window"
    )
    parser.add_argument(
        "--jsonl", metavar="FILE", default=None,
        help="also append one JSON line per window to FILE",
    )
    parser.add_argument(
        "--logdir", metavar="DIR", default=None,
        help="also write TensorBoard scalar summaries under DIR",
    )
    parser.add_argument(
        "--profile", metavar="DIR", default=None,
        help="capture a jax.profiler trace of the training run into DIR "
        "(view with tensorboard --logdir DIR)",
    )
    args = parser.parse_args(argv)

    from asyncrl_tpu.api.factory import make_agent
    from asyncrl_tpu.cli.common import apply_platform_guard, resolve_config

    cfg = resolve_config(args.preset, args.overrides, args.steps)
    apply_platform_guard(cfg)

    agent = make_agent(cfg)

    from asyncrl_tpu.utils import metrics as metrics_mod

    sink = metrics_mod.MultiSink(
        metrics_mod.StdoutSink(as_json=args.json),
        metrics_mod.JsonlSink(args.jsonl) if args.jsonl else None,
        metrics_mod.TensorBoardSink(args.logdir) if args.logdir else None,
    )

    import jax

    try:
        if args.profile:
            jax.profiler.start_trace(args.profile)
        try:
            agent.train(callback=sink)
        finally:
            if args.profile:
                jax.profiler.stop_trace()
            sink.close()

        if args.eval_episodes:
            ret = agent.evaluate(num_episodes=args.eval_episodes)
            print(
                json.dumps(
                    {"eval_episodes": args.eval_episodes, "mean_return": ret}
                )
                if args.json
                else f"greedy eval over {args.eval_episodes} episodes: {ret:.1f}"
            )
    finally:
        close = getattr(agent, "close", None)
        if close is not None:
            close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
