"""Suite sweep: ``python -m asyncrl_tpu.cli.suite [--games ...] [opts]``.

The reference's Atari-57 workload is a *suite* run — one agent per game,
same hyperparameters, results aggregated across the family (BASELINE.json:9;
SURVEY.md §1.1). This entry point reproduces that shape over any set of
registered envs: it trains each game sequentially on the chip (suites are
throughput-bound, so one-at-a-time keeps every run at full batch size),
greedy-evaluates, and emits a per-game JSONL plus an aggregate summary
(mean/median of final returns — the "human-normalized median" slot of the
Atari-57 protocol, with raw returns since these games have no human
baseline).

Default game set: the six-game Atari stand-in family (JaxPong, JaxBreakout,
and the MinAtar-style four) — swap with ``--games`` for e.g. the procedural
or locomotion families.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

# Image-observation variants throughout: the default preset's CNN torso
# must be able to consume every game in the default sweep.
ATARI_FAMILY = [
    "JaxPongPixels-v0",
    "JaxBreakoutPixels-v0",
    "JaxSpaceInvaders-v0",
    "JaxFreeway-v0",
    "JaxAsterix-v0",
    "JaxSeaquest-v0",
]


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="asyncrl-tpu-suite",
        description="Train one agent per game over an env suite "
        "(the Atari-57 workload shape) and aggregate results.",
    )
    parser.add_argument(
        "overrides", nargs="*", help="config overrides as key=value"
    )
    parser.add_argument(
        "--games", nargs="+", default=None,
        help="env ids to sweep (default: the five-game Atari stand-in "
        "family); 'all' = every registered env",
    )
    parser.add_argument(
        "--preset", default="atari_impala",
        help="base preset supplying hyperparameters (default atari_impala)",
    )
    parser.add_argument(
        "--steps", type=int, default=None, help="override total_env_steps"
    )
    parser.add_argument(
        "--eval-episodes", type=int, default=32,
        help="greedy-eval episodes per game",
    )
    parser.add_argument(
        "--jsonl", metavar="FILE", default=None,
        help="append one JSON line per game to FILE",
    )
    args = parser.parse_args(argv)

    from asyncrl_tpu.api.factory import make_agent
    from asyncrl_tpu.cli.common import apply_platform_guard, resolve_config
    from asyncrl_tpu.envs import registered

    games = args.games or ATARI_FAMILY
    if games == ["all"]:
        games = registered()
    unknown = [g for g in games if g not in registered()]
    if unknown:
        print(
            f"unknown envs {unknown}; registered: {registered()}",
            file=sys.stderr,
        )
        return 2

    base = resolve_config(args.preset, args.overrides, args.steps)
    apply_platform_guard(base)

    from asyncrl_tpu.envs.registry import make as make_env
    from asyncrl_tpu.utils.metrics import JsonlSink

    def incompatible(game: str) -> str | None:
        """Config/game mismatches detectable before spending train time."""
        spec = make_env(game).spec
        if base.torso in ("nature_cnn", "impala_cnn") and (
            len(spec.obs_shape) != 3
        ):
            return (
                f"torso {base.torso!r} needs image-shaped obs, "
                f"{game} has {spec.obs_shape}"
            )
        return None

    results = []
    sink = JsonlSink(args.jsonl) if args.jsonl else None

    def emit(row: dict) -> None:
        print(json.dumps(row), flush=True)
        if sink:
            sink.write(row)

    try:
        for game in games:
            skip = incompatible(game)
            if skip:
                emit({"game": game, "skipped": skip})
                continue
            cfg = base.replace(env_id=game)
            t0 = time.perf_counter()
            try:
                agent = make_agent(cfg)
                try:
                    hist = agent.train()
                    ret = agent.evaluate(num_episodes=args.eval_episodes)
                finally:
                    close = getattr(agent, "close", None)
                    if close is not None:
                        close()
            except Exception as e:  # keep the sweep alive per game
                emit({"game": game, "error": f"{type(e).__name__}: {e}"})
                continue
            row = {
                "game": game,
                "final_return": ret,
                "train_return_last_window": (
                    float(hist[-1]["episode_return"])
                    if hist and "episode_return" in hist[-1]
                    else None
                ),
                "env_steps": cfg.total_env_steps,
                "wall_s": round(time.perf_counter() - t0, 1),
            }
            results.append(row)
            emit(row)

        if results:
            finals = sorted(r["final_return"] for r in results)
            n = len(finals)
            summary = {
                "suite_size": n,
                "mean_final_return": sum(finals) / n,
                "median_final_return": (
                    finals[n // 2]
                    if n % 2
                    else (finals[n // 2 - 1] + finals[n // 2]) / 2
                ),
                "total_wall_s": round(sum(r["wall_s"] for r in results), 1),
            }
            emit({"suite_summary": summary})
    finally:
        if sink:
            sink.close()
    return 0 if results else 1


if __name__ == "__main__":
    raise SystemExit(main())
