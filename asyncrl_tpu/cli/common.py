"""Shared CLI plumbing: preset/override resolution and the cpu_async
platform guard, used by every entry point (train / suite / play / launch)
so fixes cannot drift between them."""

from __future__ import annotations


def resolve_config(
    preset: str, overrides: list[str], steps: int | None = None
):
    """Preset + ``key=value`` overrides + optional --steps, resolved."""
    from asyncrl_tpu.configs import presets
    from asyncrl_tpu.utils.config import override

    cfg = override(presets.get(preset), overrides)
    if steps is not None:
        cfg = cfg.replace(total_env_steps=steps)
    return cfg


def apply_platform_guard(cfg) -> None:
    """The cpu_async parity backend is CPU-only by contract: restrict the
    platform list BEFORE any backend initializes, so JAX's global init
    never touches an attached accelerator (jax initializes ALL registered
    platforms on first device query)."""
    if cfg.backend == "cpu_async":
        import jax

        jax.config.update("jax_platforms", "cpu")
