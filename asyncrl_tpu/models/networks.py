"""Policy/value networks for the reference's workload suites (SURVEY.md §1.2
L2): MLP torso for classic control / continuous-control stand-ins, Nature-CNN
and IMPALA-ResNet torsos for pixel suites (Atari/Procgen), with a shared
categorical policy head + value head.

TPU notes: matmuls run in bfloat16 when ``compute_dtype`` says so (params and
loss math stay f32 — MXU-friendly mixed precision); conv torsos use NHWC which
XLA:TPU prefers.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ORTHO = nn.initializers.orthogonal


class MLPTorso(nn.Module):
    hidden_sizes: Sequence[int] = (64, 64)
    compute_dtype: jnp.dtype = jnp.float32
    obs_rank: int = 1  # trailing dims that form one observation; flattened

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.reshape(*x.shape[: x.ndim - self.obs_rank], -1)
        x = x.astype(self.compute_dtype)
        for size in self.hidden_sizes:
            x = nn.Dense(size, dtype=self.compute_dtype, kernel_init=ORTHO(jnp.sqrt(2)))(x)
            x = nn.tanh(x)
        return x


class NatureCNN(nn.Module):
    """DQN/Nature conv torso (84x84 stacked frames)."""

    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.compute_dtype)
        x = nn.relu(nn.Conv(32, (8, 8), strides=(4, 4), dtype=self.compute_dtype)(x))
        x = nn.relu(nn.Conv(64, (4, 4), strides=(2, 2), dtype=self.compute_dtype)(x))
        x = nn.relu(nn.Conv(64, (3, 3), strides=(1, 1), dtype=self.compute_dtype)(x))
        x = x.reshape(*x.shape[:-3], -1)
        x = nn.relu(nn.Dense(512, dtype=self.compute_dtype, kernel_init=ORTHO(jnp.sqrt(2)))(x))
        return x


class ResidualBlock(nn.Module):
    channels: int
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        y = nn.relu(x)
        y = nn.Conv(self.channels, (3, 3), dtype=self.compute_dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.channels, (3, 3), dtype=self.compute_dtype)(y)
        return x + y


class ImpalaCNN(nn.Module):
    """IMPALA deep ResNet torso (Espeholt et al. 2018 'large' network)."""

    channels: Sequence[int] = (16, 32, 32)
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.compute_dtype)
        for ch in self.channels:
            x = nn.Conv(ch, (3, 3), dtype=self.compute_dtype)(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            x = ResidualBlock(ch, self.compute_dtype)(x)
            x = ResidualBlock(ch, self.compute_dtype)(x)
        x = nn.relu(x)
        x = x.reshape(*x.shape[:-3], -1)
        x = nn.relu(nn.Dense(256, dtype=self.compute_dtype, kernel_init=ORTHO(jnp.sqrt(2)))(x))
        return x


class ActorCritic(nn.Module):
    """Shared-torso policy + value network.

    ``__call__`` returns ``(dist_params, value)`` in float32 regardless of
    compute dtype, so losses and V-trace stay full-precision. For discrete
    envs ``dist_params`` are logits [..., A]; for continuous envs they are
    concat(mean, log_std) [..., 2*D] with log_std a learned
    state-independent bias (the standard continuous-PPO head) — interpreted
    by ``ops.distributions``.
    """

    num_actions: int
    torso: str = "mlp"  # "mlp" | "nature_cnn" | "impala_cnn"
    hidden_sizes: Sequence[int] = (64, 64)
    channels: Sequence[int] = (16, 32, 32)
    compute_dtype: jnp.dtype = jnp.float32
    obs_rank: int = 1  # rank of one observation (e.g. 3 for H,W,C images)
    continuous: bool = False
    action_dim: int = 0

    @nn.compact
    def __call__(self, obs: jax.Array) -> tuple[jax.Array, jax.Array]:
        if self.torso == "mlp":
            h = MLPTorso(self.hidden_sizes, self.compute_dtype, self.obs_rank)(obs)
        elif self.torso == "nature_cnn":
            h = NatureCNN(self.compute_dtype)(obs)
        elif self.torso == "impala_cnn":
            h = ImpalaCNN(self.channels, self.compute_dtype)(obs)
        else:
            raise ValueError(f"unknown torso {self.torso!r}")
        if self.continuous:
            mean = nn.Dense(
                self.action_dim, dtype=jnp.float32, kernel_init=ORTHO(0.01)
            )(h)
            log_std = self.param(
                "log_std", nn.initializers.zeros, (self.action_dim,), jnp.float32
            )
            dist_params = jnp.concatenate(
                [mean, jnp.broadcast_to(log_std, mean.shape)], axis=-1
            )
        else:
            dist_params = nn.Dense(
                self.num_actions, dtype=jnp.float32, kernel_init=ORTHO(0.01)
            )(h)
        value = nn.Dense(1, dtype=jnp.float32, kernel_init=ORTHO(1.0))(h)[..., 0]
        return dist_params.astype(jnp.float32), value.astype(jnp.float32)


def build_model(config, env_spec) -> ActorCritic:
    """Construct the ActorCritic matching a Config + EnvSpec."""
    compute_dtype = (
        jnp.bfloat16 if config.precision == "bf16_matmul" else jnp.float32
    )
    return ActorCritic(
        num_actions=env_spec.num_actions,
        torso=config.torso,
        hidden_sizes=tuple(config.hidden_sizes),
        channels=tuple(config.channels),
        compute_dtype=compute_dtype,
        obs_rank=len(env_spec.obs_shape),
        continuous=env_spec.continuous,
        action_dim=env_spec.action_dim,
    )
