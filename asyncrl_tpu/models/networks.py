"""Policy/value networks for the reference's workload suites (SURVEY.md §1.2
L2): MLP torso for classic control / continuous-control stand-ins, Nature-CNN
and IMPALA-ResNet torsos for pixel suites (Atari/Procgen), with a shared
categorical policy head + value head.

TPU notes: matmuls run in bfloat16 when ``compute_dtype`` says so (params and
loss math stay f32 — MXU-friendly mixed precision); conv torsos use NHWC which
XLA:TPU prefers.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ORTHO = nn.initializers.orthogonal


class MLPTorso(nn.Module):
    hidden_sizes: Sequence[int] = (64, 64)
    compute_dtype: jnp.dtype = jnp.float32
    obs_rank: int = 1  # trailing dims that form one observation; flattened

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.reshape(*x.shape[: x.ndim - self.obs_rank], -1)
        x = x.astype(self.compute_dtype)
        for size in self.hidden_sizes:
            x = nn.Dense(size, dtype=self.compute_dtype, kernel_init=ORTHO(jnp.sqrt(2)))(x)
            x = nn.tanh(x)
        return x


class NatureCNN(nn.Module):
    """DQN/Nature conv torso (84x84 stacked frames)."""

    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.compute_dtype)
        x = nn.relu(nn.Conv(32, (8, 8), strides=(4, 4), dtype=self.compute_dtype)(x))
        x = nn.relu(nn.Conv(64, (4, 4), strides=(2, 2), dtype=self.compute_dtype)(x))
        x = nn.relu(nn.Conv(64, (3, 3), strides=(1, 1), dtype=self.compute_dtype)(x))
        x = x.reshape(*x.shape[:-3], -1)
        x = nn.relu(nn.Dense(512, dtype=self.compute_dtype, kernel_init=ORTHO(jnp.sqrt(2)))(x))
        return x


class ResidualBlock(nn.Module):
    channels: int
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        y = nn.relu(x)
        y = nn.Conv(self.channels, (3, 3), dtype=self.compute_dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.channels, (3, 3), dtype=self.compute_dtype)(y)
        return x + y


class ImpalaCNN(nn.Module):
    """IMPALA deep ResNet torso (Espeholt et al. 2018 'large' network).

    ``remat=True`` rematerializes at RESIDUAL-BLOCK granularity
    (``nn.remat``): the backward pass keeps only stage-boundary
    activations live and recomputes each block's conv intermediates when
    its gradient is needed — block granularity bounds simultaneous
    liveness by one block's internals, where whole-torso remat would
    still need every conv activation alive at once during the replayed
    backward. Param tree is identical either way (lifted transform), so
    checkpoints swap freely between the two."""

    channels: Sequence[int] = (16, 32, 32)
    compute_dtype: jnp.dtype = jnp.float32
    remat: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.compute_dtype)
        # Explicit names pin the param paths to the non-remat auto-naming
        # (nn.remat would otherwise prefix the class name with "Checkpoint",
        # silently forking the checkpoint format).
        block = nn.remat(ResidualBlock) if self.remat else ResidualBlock
        for i, ch in enumerate(self.channels):
            x = nn.Conv(ch, (3, 3), dtype=self.compute_dtype)(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            x = block(ch, self.compute_dtype, name=f"ResidualBlock_{2 * i}")(x)
            x = block(
                ch, self.compute_dtype, name=f"ResidualBlock_{2 * i + 1}"
            )(x)
        x = nn.relu(x)
        x = x.reshape(*x.shape[:-3], -1)
        x = nn.relu(nn.Dense(256, dtype=self.compute_dtype, kernel_init=ORTHO(jnp.sqrt(2)))(x))
        return x


def _apply_torso(module: nn.Module, obs: jax.Array) -> jax.Array:
    """Shared torso dispatch for the (Recurrent)ActorCritic modules; reads
    the torso hyperparameters off ``module``."""
    if module.torso == "mlp":
        # name= pins the remat param path to the auto name (see ImpalaCNN).
        cls = nn.remat(MLPTorso) if module.remat else MLPTorso
        return cls(
            module.hidden_sizes, module.compute_dtype, module.obs_rank,
            name="MLPTorso_0" if module.remat else None,
        )(obs)
    if module.torso == "nature_cnn":
        cls = nn.remat(NatureCNN) if module.remat else NatureCNN
        return cls(
            module.compute_dtype,
            name="NatureCNN_0" if module.remat else None,
        )(obs)
    if module.torso == "impala_cnn":
        return ImpalaCNN(
            module.channels, module.compute_dtype, remat=module.remat
        )(obs)
    raise ValueError(f"unknown torso {module.torso!r}")


def _apply_heads(
    module: nn.Module, h: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Shared policy + value heads: returns ``(dist_params, value)`` in
    float32 regardless of compute dtype, so losses and V-trace stay
    full-precision. For discrete envs ``dist_params`` are logits [..., A];
    for continuous envs concat(mean, log_std) [..., 2*D] with log_std a
    learned state-independent bias (the standard continuous-PPO head) —
    interpreted by ``ops.distributions``."""
    if module.continuous:
        mean = nn.Dense(
            module.action_dim, dtype=jnp.float32, kernel_init=ORTHO(0.01)
        )(h)
        log_std = module.param(
            "log_std", nn.initializers.zeros, (module.action_dim,), jnp.float32
        )
        dist_params = jnp.concatenate(
            [mean, jnp.broadcast_to(log_std, mean.shape)], axis=-1
        )
    else:
        dist_params = nn.Dense(
            module.num_actions, dtype=jnp.float32, kernel_init=ORTHO(0.01)
        )(h)
    value = nn.Dense(1, dtype=jnp.float32, kernel_init=ORTHO(1.0))(h)[..., 0]
    return dist_params.astype(jnp.float32), value.astype(jnp.float32)


class ActorCritic(nn.Module):
    """Shared-torso policy + value network (see ``_apply_heads`` for the
    head/output contract)."""

    num_actions: int
    torso: str = "mlp"  # "mlp" | "nature_cnn" | "impala_cnn"
    hidden_sizes: Sequence[int] = (64, 64)
    channels: Sequence[int] = (16, 32, 32)
    compute_dtype: jnp.dtype = jnp.float32
    obs_rank: int = 1  # rank of one observation (e.g. 3 for H,W,C images)
    continuous: bool = False
    action_dim: int = 0
    remat: bool = False

    @nn.compact
    def __call__(self, obs: jax.Array) -> tuple[jax.Array, jax.Array]:
        return _apply_heads(self, _apply_torso(self, obs))


def _q_head(module: nn.Module, h: jax.Array) -> jax.Array:
    """Shared Q head for the (Recurrent)QNetwork pair: one Q-value per
    action, f32 regardless of compute dtype (same drift-prevention role as
    ``_apply_heads`` for the actor-critic pair).

    ``module.dueling`` switches to the dueling decomposition (Wang et al.
    2016): Q(s,a) = V(s) + A(s,a) - mean_a A(s,a) — separate value and
    advantage streams, identifiable via the mean-advantage constraint."""
    if getattr(module, "dueling", False):
        value = nn.Dense(
            1, dtype=jnp.float32, kernel_init=ORTHO(1.0)
        )(h).astype(jnp.float32)
        adv = nn.Dense(
            module.num_actions, dtype=jnp.float32, kernel_init=ORTHO(0.01)
        )(h).astype(jnp.float32)
        return value + adv - jnp.mean(adv, axis=-1, keepdims=True)
    return nn.Dense(
        module.num_actions, dtype=jnp.float32, kernel_init=ORTHO(0.01)
    )(h).astype(jnp.float32)


def _zero_core(batch_size: int, core_size: int):
    """Zero LSTM (c, h) carry — shared by every recurrent module."""
    zeros = jnp.zeros((batch_size, core_size), jnp.float32)
    return (zeros, zeros)


class QNetwork(nn.Module):
    """Q-value network for the async Q-learning family (the A3C paper's
    value-based siblings — async one-step/n-step Q; PAPERS.md:8).

    Same torso zoo as ``ActorCritic``; the head emits one Q-value per action.
    Returns ``(q_values, max_q)`` so it satisfies the generic
    ``(dist_params, value)`` apply contract — the rollout interprets
    ``q_values`` through ``ops.distributions.EpsilonGreedy`` and the learner
    reads them directly in ``qlearn_loss``.
    """

    num_actions: int
    torso: str = "mlp"
    hidden_sizes: Sequence[int] = (64, 64)
    channels: Sequence[int] = (16, 32, 32)
    compute_dtype: jnp.dtype = jnp.float32
    obs_rank: int = 1
    dueling: bool = False
    remat: bool = False

    @nn.compact
    def __call__(self, obs: jax.Array) -> tuple[jax.Array, jax.Array]:
        q = _q_head(self, _apply_torso(self, obs))
        return q, jnp.max(q, axis=-1)


class RecurrentActorCritic(nn.Module):
    """Recurrent policy + value network: torso -> LSTM core -> heads.

    The async-rl/A3C family's LSTM variant (the A3C paper's recurrent agent;
    IMPALA's LSTM agent). TPU-idiomatic: the core state is an explicit
    ``(c, h)`` pytree carried through the rollout ``lax.scan`` — the same
    carry that holds env states — so the whole recurrent rollout stays one
    fused XLA program. Call as ``apply(params, obs[B], core) ->
    (dist_params, value, new_core)``; the CALLER resets the core where
    episodes end (``reset_core``), keeping the cell itself stateless.
    """

    num_actions: int
    torso: str = "mlp"
    hidden_sizes: Sequence[int] = (64, 64)
    channels: Sequence[int] = (16, 32, 32)
    core_size: int = 256
    compute_dtype: jnp.dtype = jnp.float32
    obs_rank: int = 1
    continuous: bool = False
    action_dim: int = 0
    remat: bool = False

    @nn.compact
    def __call__(self, obs, core):
        h = _apply_torso(self, obs)
        # LSTM math in f32: tiny vs the torso, and carries must not
        # accumulate bf16 rounding across hundreds of steps.
        cell = nn.OptimizedLSTMCell(self.core_size, dtype=jnp.float32)
        core, h = cell(core, h.astype(jnp.float32))
        dist_params, value = _apply_heads(self, h)
        return dist_params, value, core

    def initial_core(self, batch_size: int):
        """Zero (c, h) carry for ``batch_size`` envs."""
        return _zero_core(batch_size, self.core_size)


class RecurrentQNetwork(nn.Module):
    """DRQN-style recurrent Q network: torso -> LSTM core -> Q head.

    The Q-learning family's answer to partial observability (Hausknecht &
    Stone's DRQN recipe applied the A3C-LSTM way): same call/carry contract
    as ``RecurrentActorCritic`` — ``apply(params, obs[B], core) ->
    (q_values, max_q, new_core)`` with the CALLER resetting the core at
    episode boundaries — so every recurrent code path (rollout scan,
    learner re-forward, eval) works unchanged.
    """

    num_actions: int
    torso: str = "mlp"
    hidden_sizes: Sequence[int] = (64, 64)
    channels: Sequence[int] = (16, 32, 32)
    core_size: int = 256
    compute_dtype: jnp.dtype = jnp.float32
    obs_rank: int = 1
    dueling: bool = False
    remat: bool = False

    @nn.compact
    def __call__(self, obs, core):
        h = _apply_torso(self, obs)
        # LSTM math in f32 for the same carry-rounding reason as
        # RecurrentActorCritic.
        cell = nn.OptimizedLSTMCell(self.core_size, dtype=jnp.float32)
        core, h = cell(core, h.astype(jnp.float32))
        q = _q_head(self, h)
        return q, jnp.max(q, axis=-1), core

    def initial_core(self, batch_size: int):
        return _zero_core(batch_size, self.core_size)


def reset_core(core, done):
    """Zero the recurrent carry where ``done`` (episode boundary); ``done``
    is [B] bool/float, core leaves are [B, H]."""
    keep = 1.0 - done.astype(jnp.float32)
    return jax.tree.map(lambda c: c * keep[:, None], core)


def is_recurrent(model) -> bool:
    return isinstance(model, (RecurrentActorCritic, RecurrentQNetwork))


def build_model(config, env_spec):
    """Construct the (Recurrent)ActorCritic matching a Config + EnvSpec."""
    compute_dtype = (
        jnp.bfloat16 if config.precision == "bf16_matmul" else jnp.float32
    )
    if config.algo == "qlearn":
        if env_spec.continuous:
            raise ValueError(
                "algo='qlearn' requires a discrete action space; "
                f"{config.env_id!r} is continuous"
            )
        q_common = dict(
            num_actions=env_spec.num_actions,
            torso=config.torso,
            hidden_sizes=tuple(config.hidden_sizes),
            channels=tuple(config.channels),
            compute_dtype=compute_dtype,
            obs_rank=len(env_spec.obs_shape),
            dueling=config.dueling,
            remat=config.remat,
        )
        if config.core == "lstm":
            return RecurrentQNetwork(core_size=config.core_size, **q_common)
        if config.core != "ff":
            raise ValueError(f"unknown core {config.core!r}; expected ff|lstm")
        return QNetwork(**q_common)
    common = dict(
        num_actions=env_spec.num_actions,
        torso=config.torso,
        hidden_sizes=tuple(config.hidden_sizes),
        channels=tuple(config.channels),
        compute_dtype=compute_dtype,
        obs_rank=len(env_spec.obs_shape),
        continuous=env_spec.continuous,
        action_dim=env_spec.action_dim,
        remat=config.remat,
    )
    if config.core == "lstm":
        return RecurrentActorCritic(core_size=config.core_size, **common)
    if config.core != "ff":
        raise ValueError(f"unknown core {config.core!r}; expected ff|lstm")
    return ActorCritic(**common)
