from asyncrl_tpu.models.networks import (
    ActorCritic,
    ImpalaCNN,
    MLPTorso,
    NatureCNN,
    build_model,
)

__all__ = ["ActorCritic", "ImpalaCNN", "MLPTorso", "NatureCNN", "build_model"]
