from asyncrl_tpu.models.networks import (
    ActorCritic,
    ImpalaCNN,
    MLPTorso,
    NatureCNN,
    RecurrentActorCritic,
    build_model,
    is_recurrent,
    reset_core,
)

__all__ = [
    "ActorCritic",
    "ImpalaCNN",
    "MLPTorso",
    "NatureCNN",
    "RecurrentActorCritic",
    "build_model",
    "is_recurrent",
    "reset_core",
]
