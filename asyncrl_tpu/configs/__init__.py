from asyncrl_tpu.configs.presets import PRESETS, get

__all__ = ["PRESETS", "get"]
