"""Workload presets mirroring the reference's five benchmark configs
(BASELINE.json:6-12). Suites whose native deps are absent in this image
(ale-py / procgen / brax — SURVEY.md §7.4 R1) target JAX-native stand-in
envs (JaxPong-v0, JaxPendulum-v0); swap ``env_id`` when the real suites are
installable. Presets whose stand-in env is not yet registered fail fast with
a KeyError naming the registered envs.
"""

from __future__ import annotations

from asyncrl_tpu.envs.pong import ALE_MAX_STEPS
from asyncrl_tpu.utils.config import Config

# BASELINE.json:7 — "CartPole-v1, 4 async CPU actors, A3C (smoke test)".
# The 4 async actors become a vectorized env batch on the tpu backend; the
# cpu_async backend reproduces the literal 4-thread layout.
cartpole_a3c = Config(
    env_id="CartPole-v1",
    algo="a3c",
    backend="tpu",
    num_envs=64,
    unroll_len=32,
    total_env_steps=400_000,
    learning_rate=1e-3,
    entropy_coef=0.01,
    gamma=0.99,
)

# BASELINE.json:8 — "PongNoFrameskip-v4, IMPALA V-trace, 256 vectorized
# envs". ale-py is unavailable; JaxPong-v0 is the JAX-native stand-in
# (envs/pong.py) with Pong-like dynamics and reward scale.
pong_impala = Config(
    env_id="JaxPong-v0",
    algo="impala",
    backend="tpu",
    num_envs=256,
    unroll_len=32,
    total_env_steps=5_000_000,
    learning_rate=6e-4,
    entropy_coef=0.01,
    torso="mlp",
    hidden_sizes=(256, 256),
    actor_staleness=2,
)

# BASELINE.json:9 — "Atari-57 suite, IMPALA, 1024 envs/chip". Pixel-obs
# Pong (84x84x4, on-device rendering) stands in for the ALE games;
# JaxBreakoutPixels-v0 (envs/breakout.py) is the second game of the family
# (`atari_impala env_id=JaxBreakoutPixels-v0` switches games, exactly like
# swapping ALE roms in the reference suite).
atari_impala = pong_impala.replace(
    env_id="JaxPongPixels-v0", num_envs=1024, torso="impala_cnn"
)
# Wide-channel variant (64/128/128 vs the parity 16/32/32): the IMPALA-CNN's
# narrow output channels cap MXU lane utilization at ~22% (docs/MFU.md), so
# per-chip pixel throughput at high MFU requires a wider torso. NOT a parity
# config — it trains a bigger model — but the principled option when raw
# pixel fps/chip is the goal rather than reference-equivalent training.
# Geometry is pre-fit for one v5e: wide activations are ~4x narrow, so 256
# envs + grad_accum microbatching + block remat lands at the footprint the
# narrow 1024-env fit geometry measured (~15.7G of the v5e's HBM).
atari_impala_wide = atari_impala.replace(
    channels=(64, 128, 128), num_envs=256, grad_accum=4, remat=True
)
# Breakout's reward lands ~23 steps after the paddle hit that caused it and
# returns run to 288/wall, so the learner sees scaled rewards (value loss
# would otherwise dominate under grad clipping) and less entropy pressure.
breakout_impala = pong_impala.replace(
    env_id="JaxBreakout-v0", reward_scale=0.1, entropy_coef=0.003
)

# BASELINE.json:10 — "Procgen-16, PPO + GAE, 4096 envs data-parallel".
# JaxChaser-v0 (envs/gridworlds.py) carries the defining Procgen property:
# a fresh procedurally-generated level every episode, CNN observations.
# `procgen_ppo env_id=JaxMaze-v0` switches games (sparse-reward variant).
procgen_ppo = Config(
    env_id="JaxChaser-v0",
    algo="ppo",
    backend="tpu",
    num_envs=4096,
    unroll_len=16,
    total_env_steps=50_000_000,
    learning_rate=5e-4,
    entropy_coef=0.01,
    ppo_epochs=2,
    ppo_minibatches=8,
    torso="impala_cnn",
)

# BASELINE.json:11 — "Brax Ant/Humanoid, PPO, 8192 envs". brax absent; the
# pure-JAX Pendulum swing-up (envs/pendulum.py, continuous-control classic)
# is the on-TPU-physics stand-in. Hyperparameters validated to reach ≈ −200
# eval return (solved ≈ −150, random ≈ −1280) in ~0.5M env steps.
brax_ppo = Config(
    env_id="JaxPendulum-v0",
    algo="ppo",
    backend="tpu",
    num_envs=8192,
    unroll_len=64,
    total_env_steps=10_000_000,
    learning_rate=1e-3,
    gamma=0.95,
    gae_lambda=0.95,
    entropy_coef=0.001,
    reward_scale=0.1,
    ppo_epochs=4,
    ppo_minibatches=8,
)

# BASELINE.json:11 with *rigid-body* on-TPU physics: the planar locomotion
# family (envs/locomotion.py, engine envs/physics2d.py) — articulated
# multi-joint control like Brax Ant/Humanoid, with physics+rollout+update
# fused into one XLA program at 8192 HBM-resident worlds.
hopper_ppo = Config(
    env_id="JaxHopper-v0",
    algo="ppo",
    backend="tpu",
    num_envs=8192,
    unroll_len=32,
    total_env_steps=30_000_000,
    learning_rate=3e-4,
    gamma=0.99,
    gae_lambda=0.95,
    entropy_coef=0.001,
    reward_scale=0.1,
    ppo_epochs=4,
    ppo_minibatches=8,
    torso="mlp",
    hidden_sizes=(256, 256),
)
walker_ppo = hopper_ppo.replace(env_id="JaxWalker2d-v0")
halfcheetah_ppo = hopper_ppo.replace(env_id="JaxHalfCheetah-v0")
# The two tasks BASELINE.json:11 names, as planar on-TPU-physics analogues
# (real MuJoCo Ant/Humanoid run via mujoco_ant_ppo / mujoco_humanoid_ppo).
brax_ant_ppo = hopper_ppo.replace(env_id="JaxAnt-v0")
brax_humanoid_ppo = hopper_ppo.replace(env_id="JaxHumanoid-v0")

# Extra smoke presets used by tests and quick benchmarking.
cartpole_impala = cartpole_a3c.replace(algo="impala", actor_staleness=2)
cartpole_ppo = cartpole_a3c.replace(algo="ppo", learning_rate=3e-4)

# Async n-step Q-learning (the A3C paper's value-based sibling family):
# ε-greedy actors on the per-env Ape-X ε ladder, double-Q bootstrap from the
# target network (= the stale actor_params copy, refreshed every
# actor_staleness updates).
# Hyperparameters from an on-chip sweep (2026-07-30): value-based learning
# off the on-policy stream (no replay; the parallel env batch decorrelates
# instead, as in the A3C paper) wants a FAST target refresh, light gradient
# clipping, and long n-step unrolls for value propagation — slow targets
# (staleness >= 10) stall CartPole completely.
cartpole_qlearn = cartpole_a3c.replace(
    algo="qlearn",
    num_envs=128,
    unroll_len=32,
    learning_rate=1e-3,
    max_grad_norm=10.0,
    actor_staleness=4,
    exploration_steps=30_000,
    eps_base=0.3,
    eps_alpha=5.0,
    total_env_steps=2_000_000,
)
pong_qlearn = pong_impala.replace(
    algo="qlearn",
    learning_rate=5e-4,
    max_grad_norm=10.0,
    actor_staleness=4,
    exploration_steps=500_000,
)

# The reference's literal default layout (BASELINE.json:7): 4 async CPU
# actor threads, one env each, A3C — the cpu_async differential-testing
# baseline (SURVEY.md §7.2 M4, §8-Q7).
cartpole_a3c_cpu = cartpole_a3c.replace(
    backend="cpu_async",
    num_envs=4,
    actor_threads=4,
    unroll_len=20,
    total_env_steps=200_000,
)

# BASELINE.json:11's real-physics variant: gymnasium's MuJoCo Ant/Humanoid
# through the Sebulba host path (mujoco ships in this image even though brax
# does not — SURVEY.md §7.0). Continuous PPO with the same reward scaling
# brax uses for these tasks. Host envs are C-backed MuJoCo, so actor threads
# overlap physics with device inference.
mujoco_ant_ppo = Config(
    env_id="Ant-v5",
    algo="ppo",
    backend="sebulba",
    host_pool="gym",
    num_envs=64,
    actor_threads=4,
    unroll_len=64,
    total_env_steps=5_000_000,
    learning_rate=3e-4,
    gamma=0.97,
    gae_lambda=0.95,
    entropy_coef=0.001,
    reward_scale=0.1,
    ppo_epochs=4,
    ppo_minibatches=8,
    torso="mlp",
    hidden_sizes=(256, 256),
)
mujoco_humanoid_ppo = mujoco_ant_ppo.replace(env_id="Humanoid-v5")

# Continuous control through the NATIVE C++ pool (envpool.cc Pendulum, the
# float-action C ABI): the host-path twin of brax_ppo — same Gaussian-head
# PPO, envs stepped by the GIL-releasing engine instead of living in HBM.
pendulum_native_ppo = Config(
    env_id="JaxPendulum-v0",
    algo="ppo",
    backend="sebulba",
    host_pool="native",
    num_envs=128,
    actor_threads=4,
    unroll_len=64,
    total_env_steps=2_000_000,
    learning_rate=1e-3,
    gamma=0.95,
    entropy_coef=0.001,
    reward_scale=0.1,
    ppo_epochs=4,
    ppo_minibatches=8,
)

# Self-play ladder (Config.selfplay): the rival paddle is a frozen snapshot
# of the agent itself, promoted every selfplay_refresh updates; greedy eval
# still measures vs the calibrated scripted tracker (the 18.0-bar metric).
# EXPERIMENTAL — measured NET-NEGATIVE for the flagship 18.0 metric at a
# matched budget (BENCH_HISTORY selfplay_vs_direct: ladder 2.0 vs direct
# 11.5 at 400M frames). Do not use for time-to-target work; see
# docs/ARCHITECTURE.md "Self-play" for the descope decision.
pong_selfplay = pong_impala.replace(
    env_id="JaxPongDuel-v0",
    selfplay=True,
    selfplay_refresh=200,
    # Symmetric-game entropy: self-play collapses faster than fixed-
    # opponent training, keep exploration pressure a bit higher.
    entropy_coef=0.02,
)

# The 18.0-bar time-to-target recipe (BASELINE.json:2; the tuning history
# is in BENCH_HISTORY.json: kind=diagnosis showed defense solved and every
# game truncation-capped at ~16.3 points scored, so the shaping targets
# scoring RATE). step_cost=0.01 prices a 184-step point at ~-0.84 shaped
# reward; gamma=0.995 keeps credit on the setup shots 2-3 court crossings
# before a winner (0.99^100=0.37 vs 0.995^100=0.61); the entropy floor
# 1e-4 sharpens late shot selection. Driven by scripts/run_to_target.py
# via scripts/tpu_window.sh.
pong_t2t = pong_impala.replace(
    step_cost=0.01,
    gamma=0.995,
    learning_rate=1.5e-4,
    entropy_coef_final=1e-4,
    entropy_anneal_steps=30_000,
    updates_per_call=32,
    eval_every=40,
    eval_episodes=32,
    total_env_steps=20_000_000_000,
)

# Batch-scaled t2t recipe for the FRESH strict-cap arm: 4x the envs (and
# frames per wall-second — the vector path's mfu is ~0.001, so batch is
# nearly free) with a mild lr bump for the bigger per-update batch. The
# r4 diagnosis puts the 3000-cap bar at >=93% of one-ply-oracle scoring
# rate (181 -> ~158 steps/point); the fresh arm tests whether shaping
# from step one PLUS 4x frame budget escapes the conservative-play basin
# the resumed arm learned in. (The resumed arm keeps pong_t2t — its
# checkpoint's geometry.)
pong_t2t_1024 = pong_t2t.replace(num_envs=1024, learning_rate=2e-4)

# ALE-faithful variant of the t2t recipe (VERDICT r3 Weak #4 / Next #1):
# identical training recipe, but the episode cap is ALE's
# PongNoFrameskip-v4 semantics — 108,000 frames = 27,000 skip-4 decisions
# (envs/pong.py ALE_MAX_STEPS) — instead of the repo's strictly-harder
# 3000-step cap. Under this cap games run to 21 points, so the 18.0 bar
# measures win margin (as in ALE) rather than scoring rate. Both caps'
# eval numbers are recorded by scripts/eval_caps.py; ledger rows carry
# pong_max_steps so the judge can tell the bars apart.
pong_t2t_ale = pong_t2t.replace(pong_max_steps=ALE_MAX_STEPS)

# ALE-style frame-skip EXPERIMENT (retired from the chip queue, round 5):
# PongNoFrameskip-v4 is always played through skip-4 preprocessing, so
# this preset reads the ALE bar at 27,000 skip-4 decisions = 108,000 core
# frames, with the skip-4-scaled recipe (gamma 0.995^4, step_cost
# 0.01x4). The CPU probe validated the recipe LEARNS fast (zero crossing
# at ~48M decisions, runs/pong18_skip4_cpu) — but the skip-4 ORACLE
# (scripts/pong_oracle.py, kind=feasibility) showed this game's
# kinematics cap skip-4 greedy play far below the bar: one-ply ceiling
# 7.9 vs the per-core-step rival, and 11.25 after the rival was
# decision-quantized for balance AND the cap raised so every game runs
# to completion (win-margin semantics, cap 6000; the skip-1 comparator
# measures 19.25 at completion cap) — the paddle moves 2.5 half-heights
# per decision, so the spin exploit's contact precision is unreachable. JaxPong's court physics are calibrated for skip-1
# control; 18.0 under skip-4 is NOT a meaningful bar here, and the
# skip-1 `pong_t2t_ale` remains the parity claim. Retired as a BAR —
# but reborn as a CURRICULUM phase: the CPU probe showed skip-4
# training + skip-1 finish crosses the ALE bar at ~6x fewer core frames
# than pure skip-1 (runs/pong18_skip4_cpu reached=true at 0.74B
# decisions, confirmation 18.72), so the watcher's pong18_curr arm runs
# one short skip-4 burst under this preset before finishing under
# pong_t2t_ale.
pong_t2t_ale4 = pong_t2t_ale.replace(
    frame_skip=4,
    gamma=0.98,
    step_cost=0.04,
)

# The PIXEL-path 18.0 hunt (VERDICT r4 Next #2): the reference flagship's
# real shape — BASELINE.json:8 is PongNoFrameskip-v4, i.e. 84x84x4 pixel
# observations with ALE episode semantics — where the vector arms above
# measure the same game from its 6-dim state. Geometry: the 1024-env/chip
# fit (atari_impala + grad_accum=4 + block remat, the measured ~15.7G HBM
# footprint); ALE cap (pong_max_steps=27,000 decisions).
#
# frame_skip=1, NOT ALE's skip-4 — a feasibility decision, not an
# oversight (round 5): the skip-4 oracle (scripts/pong_oracle.py,
# kind=feasibility rows) showed JaxPong's skip-1-calibrated kinematics
# cap skip-4 greedy play at ~11 — the 18.0 bar is unreachable under
# skip-4 regardless of observations (see pong_t2t_ale4 above). At skip-1
# the bar is proven reachable: this preset's VECTOR twin (pong_t2t_ale)
# evaluates 20+. The skip-4/max-pool/sticky knobs remain available
# (frame_skip=4 frame_pool=true sticky_actions=0.25 overrides) for
# strict-ALE-preprocessing runs that accept the lower ceiling.
#
# Recipe: the PROVEN skip-1 t2t economics (pong_t2t: gamma 0.995,
# step_cost 0.01, entropy floor 1e-4), with lr 3e-4 for the 4x bigger
# 1024-env per-update batch (a first-recipe hypothesis like
# pong_t2t_1024's lr — no headline until it has a curve) and the pixel
# benches' updates_per_call=8 call fusion.
#
# Frames-to-18 expectation (stated BEFORE the arm runs, so the curve can
# falsify it): the vector twin reached 18.0 under this cap at ~18.0B
# decisions (runs/pong18_tpu metrics.jsonl); pixel representation
# learning (recovering the 6-dim state from 84x84x4) adds a factor we
# bound at 1-3x => 18-54B decisions, i.e. ~110-330 chip-hours at the
# measured 45,984 fps 1024-fit throughput. A multi-ROUND accumulation
# arm (runs/pong18_pixels): each watcher window banks curve +
# reached=false rows, and the MFU work (docs/MFU.md) is what shrinks the
# wall-clock denominator.
pong_pixels_t2t = pong_t2t.replace(
    env_id="JaxPongPixels-v0",
    torso="impala_cnn",
    num_envs=1024,
    grad_accum=4,
    remat=True,
    updates_per_call=8,
    pong_max_steps=ALE_MAX_STEPS,
    learning_rate=3e-4,
)

# The serving-arc preset (ROADMAP item 4; scripts/gateway_smoke.sh):
# pong IMPALA on the sebulba host path with the serve core AND the
# external gateway mounted — wire clients hit /v1/act while training
# continues and weights swap live. Tenant matrix: a latency-tier "gold"
# class (tight p95, stale-degradation so availability survives a core
# outage), a rate-limited "bulk" class (shed + Retry-After), and the "*"
# catch-all. gateway_port=-1 binds an ephemeral port the harness reads
# back; set a fixed port for real exposure.
pong_serve = pong_impala.replace(
    backend="sebulba",
    host_pool="jax",
    num_envs=16,
    actor_threads=2,
    unroll_len=16,
    inference_server=True,
    serve=True,
    gateway_port=-1,
    gateway_tenant_spec=(
        "gold:stale:p95_ms=250,inflight=32;"
        "bulk:shed:rps=50,burst=25;"
        "*:fallback"
    ),
)

PRESETS: dict[str, Config] = {
    "cartpole_a3c": cartpole_a3c,
    "cartpole_a3c_cpu": cartpole_a3c_cpu,
    "cartpole_impala": cartpole_impala,
    "cartpole_ppo": cartpole_ppo,
    "cartpole_qlearn": cartpole_qlearn,
    "pong_qlearn": pong_qlearn,
    "pong_impala": pong_impala,
    "pong_t2t": pong_t2t,
    "pong_t2t_1024": pong_t2t_1024,
    "pong_t2t_ale": pong_t2t_ale,
    "pong_t2t_ale4": pong_t2t_ale4,
    "pong_pixels_t2t": pong_pixels_t2t,
    "pong_selfplay": pong_selfplay,
    "pong_serve": pong_serve,
    "atari_impala": atari_impala,
    "atari_impala_wide": atari_impala_wide,
    "breakout_impala": breakout_impala,
    "procgen_ppo": procgen_ppo,
    "brax_ppo": brax_ppo,
    "hopper_ppo": hopper_ppo,
    "walker_ppo": walker_ppo,
    "halfcheetah_ppo": halfcheetah_ppo,
    "brax_ant_ppo": brax_ant_ppo,
    "brax_humanoid_ppo": brax_humanoid_ppo,
    "mujoco_ant_ppo": mujoco_ant_ppo,
    "mujoco_humanoid_ppo": mujoco_humanoid_ppo,
    "pendulum_native_ppo": pendulum_native_ppo,
}


def get(name: str) -> Config:
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    return PRESETS[name]
