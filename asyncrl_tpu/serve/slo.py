"""SLOs and admission control for the serving core.

A serving system that faces real traffic needs an answer to overload that
is better than "queues grow until everything is slow": per-client latency
targets, a measurement of whether they hold (p50/p95/p99 through the obs
histogram registry), and an admission gate that **sheds or backpressures**
new traffic the moment the p95 breaches target — so the requests that ARE
admitted still meet their SLO instead of everyone missing together.

The gate is a token bucket whose refill is **completion-driven during
breach**: while the rolling p95 is inside target, admission is free
(subject only to the ``max_inflight`` cap); the moment p95 breaches, each
admission consumes a token and each request *completion* refills one —
admission locks step with service rate (one-in-one-out), inflight stops
growing, and the rolling window recovers. Out of breach the bucket refills
to its burst instantly. This needs no tuned rate constant: the service
rate itself is the refill clock, which is the only rate that is always
correct.

Two overload responses, chosen per gate:

- ``shed=False`` (backpressure, the trainer's mode): admission *blocks*
  until a token frees. Actor threads slow down instead of erroring — the
  pipeline's natural flow control. The blocked time is the client-side
  ``serve.admit_wait`` span, so the obs report attributes it ("clients
  held at the serve admission gate — the server is the bottleneck").
- ``shed=True`` (external-traffic mode): admission raises
  :class:`RequestShed` immediately. The caller (a front-end, a retry
  layer) owns the retry policy; the serve core stays inside target.

Counters (obs/registry.py, drained into every metrics window):
``server_overload`` — admissions that found the gate in breach;
``serve_shed`` — requests refused. Latency observations feed the
``serve_latency_ms`` histogram (p50/p95/p99/max exported per window).
The elastic runtime (asyncrl_tpu/runtime/elastic.py) reads the two
counters' per-window deltas as a scale-DOWN signal: actors overrunning
the admission gate means fewer actors, not a bigger gate.

Breach state also feeds the health detectors (obs/health.py) through two
gauges maintained wherever the rolling window recomputes:
``serve_p95_rolling_ms`` (the breach signal itself — the histogram's p95
is lifetime-cumulative, the gauge is the rolling window) and
``serve_slo_breached`` (0/1). The ``slo_breach`` detector fires on
breach *persistence* (2+ consecutive windows), and ``/healthz`` degrades
the ``serve-core`` component.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

from asyncrl_tpu.obs import registry as obs_registry
from asyncrl_tpu.obs import spans as span_names
from asyncrl_tpu.obs import trace
from asyncrl_tpu.rollout.inference_server import ServerClosed

LATENCY_HISTOGRAM = "serve_latency_ms"
OVERLOAD_COUNTER = "server_overload"
SHED_COUNTER = "serve_shed"
P95_GAUGE = "serve_p95_rolling_ms"
BREACH_GAUGE = "serve_slo_breached"


class RequestShed(RuntimeError):
    """Raised to a client whose request was refused by a shedding
    admission gate (p95 over target, no tokens). Deliberately a plain
    RuntimeError subclass: an in-repo client that cannot tolerate sheds
    (an actor thread) should not enable shed mode, not special-case it."""


class SLOGate:
    """Latency-target admission gate (see module doc).

    ``p95_target_ms=0`` disables breach detection (the gate only enforces
    ``max_inflight``); ``max_inflight=0`` removes the inflight cap. The
    default-constructed gate is therefore a no-op on the admit path — the
    trainer's serve core costs nothing until targets are configured.
    """

    def __init__(
        self,
        p95_target_ms: float = 0.0,
        max_inflight: int = 0,
        shed: bool = False,
        window: int = 512,
        metrics_prefix: str = "",
    ):
        if p95_target_ms < 0:
            raise ValueError(f"p95_target_ms must be >= 0: {p95_target_ms}")
        if max_inflight < 0:
            raise ValueError(f"max_inflight must be >= 0: {max_inflight}")
        self.p95_target_ms = p95_target_ms
        self.max_inflight = max_inflight
        self.shed = shed
        self._cond = threading.Condition()
        # Preemption drain (runtime/durability.py): a closed gate refuses
        # every admission with ServerClosed — requests already admitted
        # finish normally, so close() is the clean "stop taking traffic"
        # edge of the drain protocol.
        self._closed = False  # guarded-by: _cond
        # Rolling latency window (ms); sorted on demand only when a target
        # is configured — the disabled gate never pays for it.
        self._lat: deque[float] = deque(maxlen=window)  # guarded-by: _cond
        self._inflight = 0  # guarded-by: _cond
        # Token bucket: burst tokens available outside breach; during
        # breach each admit consumes one and each finish refills one.
        self._burst = max(1, max_inflight) if max_inflight else 1
        self._tokens = float(self._burst)  # guarded-by: _cond
        # Cached rolling p95 (ms), refreshed only where the window
        # mutates — the admit path reads it O(1).
        self._p95_cache = 0.0  # guarded-by: _cond
        # ``metrics_prefix`` re-homes this gate's instruments (the
        # gateway's per-tenant SLO classes export
        # ``gateway_<tenant>_latency_ms_p95`` etc. instead of folding
        # into the serve core's counters); empty keeps the historical
        # serve-core names bit-for-bit.
        p = metrics_prefix
        self._counter_overload = obs_registry.counter(
            f"{p}_overload" if p else OVERLOAD_COUNTER
        )
        self._counter_shed = obs_registry.counter(
            f"{p}_shed" if p else SHED_COUNTER
        )
        self._histogram = obs_registry.histogram(
            f"{p}_latency_ms" if p else LATENCY_HISTOGRAM
        )
        # Health-detector feed (module docstring): rolling p95 + breach
        # flag as gauges, refreshed where the rolling window recomputes.
        self._gauge_p95 = obs_registry.gauge(
            f"{p}_p95_rolling_ms" if p else P95_GAUGE
        )
        self._gauge_breach = obs_registry.gauge(
            f"{p}_slo_breached" if p else BREACH_GAUGE
        )

    # ------------------------------------------------------------ metrics

    def _recompute_p95_locked(self) -> None:  # holds: _cond
        """Refresh the cached p95. Called ONLY where the window mutates
        (:meth:`finished`) — once per completion, never per admission
        attempt, so the per-request admit path stays O(1) (the
        obs/registry discipline: instrumentation must never be a hot-path
        cost)."""
        if not self._lat:
            self._p95_cache = 0.0
            return
        ordered = sorted(self._lat)
        rank = max(0, min(len(ordered) - 1, int(0.95 * len(ordered))))
        self._p95_cache = ordered[rank]

    def p95_ms(self) -> float:
        """Rolling-window p95 latency (ms) — the breach signal. With no
        target configured the cache is not maintained on the hot path, so
        this diagnostic read recomputes on demand."""
        with self._cond:
            if self.p95_target_ms <= 0:
                self._recompute_p95_locked()
            return self._p95_cache

    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    def _in_breach_locked(self) -> bool:  # holds: _cond
        return (
            self.p95_target_ms > 0
            and self._p95_cache > self.p95_target_ms
        )

    # ---------------------------------------------------------- admission

    def admit(
        self,
        stop: Callable[[], bool] | None = None,
        timeout_s: float = 30.0,
    ) -> None:  # budget: timeout_s
        """Admit one request or refuse it.

        Returns when admitted (inflight is counted from here — pair with
        :meth:`finished`). Raises :class:`RequestShed` in shed mode when
        the gate is in breach with no tokens, or — in backpressure mode —
        when ``timeout_s`` elapses without admission (a bounded wait, so a
        dead server cannot wedge clients in the gate forever). A ``stop``
        predicate turning true raises :class:`ServerClosed` instead — the
        server died, which must never masquerade as load shedding (the
        caller re-raises its real fatal cause). Blocked time is the
        ``serve.admit_wait`` span."""
        deadline = time.monotonic() + timeout_s
        overload_counted = False
        with trace.span(span_names.SERVE_ADMIT_WAIT):
            with self._cond:
                while True:
                    if self._closed:
                        raise ServerClosed(
                            "serve admission gate closed (preemption "
                            "drain); no new requests are admitted"
                        )
                    if stop is not None and stop():
                        raise ServerClosed(
                            "serve core stopped while a request waited at "
                            "the admission gate"
                        )
                    capped = (
                        self.max_inflight > 0
                        and self._inflight >= self.max_inflight
                    )
                    breach = self._in_breach_locked()
                    if breach and not overload_counted:
                        # Once per request, not per wait iteration.
                        overload_counted = True
                        self._counter_overload.inc()
                    if not capped and (not breach or self._tokens >= 1.0):
                        if breach:
                            self._tokens -= 1.0
                        self._inflight += 1
                        return
                    if self.shed:
                        self._counter_shed.inc()
                        raise RequestShed(
                            "serve admission refused: "
                            + (
                                f"p95 {self._p95_cache:.1f}ms over "
                                f"target {self.p95_target_ms:.1f}ms"
                                if breach
                                else f"inflight cap {self.max_inflight} "
                                "reached"
                            )
                        )
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self._counter_shed.inc()
                        raise RequestShed(
                            "serve admission timed out under backpressure "
                            f"({timeout_s:.1f}s)"
                        )
                    self._cond.wait(timeout=min(remaining, 0.05))

    def finished(
        self, latency_ms: float, trace_id: str | None = None
    ) -> None:
        """Record one completed request: feeds the latency window and the
        registry histogram, refills one token during breach, and wakes
        backpressured admitters. ``trace_id`` (a request journal's id)
        becomes the histogram bucket's exemplar — a p95/p99 breach in the
        summary then links to a concrete journal via
        ``Histogram.exemplars()``."""
        self._histogram.observe(latency_ms, exemplar=trace_id)
        with self._cond:
            self._inflight -= 1
            self._lat.append(latency_ms)
            if self.p95_target_ms > 0:
                self._recompute_p95_locked()
                # Gauge writes UNDER _cond, deliberately: two client
                # threads completing concurrently must publish their
                # breach states in recompute order — a stale breached=1
                # applied after a recovery would hold /healthz degraded
                # until the next completion. The nesting is acyclic (the
                # gauge's lock is only ever taken alone) and non-blocking.
                self._gauge_p95.set(self._p95_cache)
                self._gauge_breach.set(
                    1.0 if self._in_breach_locked() else 0.0
                )
            if self._tokens < self._burst:
                self._tokens += 1.0
            self._cond.notify_all()

    def close(self) -> None:
        """Stop admitting (preemption drain, runtime/durability.py;
        gateway degradation, serve/gateway.py): every waiting and future
        :meth:`admit` raises ``ServerClosed``; in-flight requests complete
        and :meth:`finished` normally. Idempotent, and reversible via
        :meth:`reopen` — a drain that ends in process exit simply never
        reopens, while a gateway that degrades-then-recovers does."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def reopen(self) -> None:
        """Resume admissions after :meth:`close` (the degrade-then-recover
        edge: a rebuilt gateway or a resumed serve core must be able to
        take traffic again without constructing a fresh gate and losing
        the rolling latency window). Idempotent; a no-op on a gate that
        was never closed."""
        with self._cond:
            self._closed = False
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def abandoned(self) -> None:
        """Un-count an admitted request that never reached dispatch (its
        submit failed between gate and queue). No latency observation —
        the request was not served."""
        with self._cond:
            self._inflight -= 1
            if self._tokens < self._burst:
                self._tokens += 1.0
            self._cond.notify_all()
